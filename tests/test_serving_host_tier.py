"""Hierarchical KV cache (ISSUE 18): host-DRAM offload tier,
chunk-aligned prefix digests, and prefix-cache-aware routing.

Pins the cross-tier ledger invariants: evict→page-in round trips are
bitwise on the raw wire (both compute dtypes, both pool forms), the
int8 wire decodes within the PR 14 block-scale contract, refcounts
never leak across evict/preempt/resume/handoff interleavings, and the
router's affinity scoring mirrors the engine's digest namespaces
exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import (
    extract_kv, generate, init_kv_cache, prefill)
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.serving import ServingEngine
from apex_tpu.serving.cluster.handoff import decode_kv, encode_kv
from apex_tpu.serving.host_tier import (
    HostTier, resolve_host_tier_bytes, resolve_host_tier_wire)
from apex_tpu.serving.paged_cache import chunk_salt, prefix_block_hashes


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _rand_kv(rng, n_tokens, dtype=np.float32, layers=2, g=4, dh=16):
    k = rng.standard_normal((layers, n_tokens, g, dh)).astype(dtype)
    v = rng.standard_normal((layers, n_tokens, g, dh)).astype(dtype)
    return k, v


class TestResolveKnobs:
    def test_env_beats_caller(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_HOST_TIER_BYTES", "4096")
        assert resolve_host_tier_bytes(None) == 4096
        assert resolve_host_tier_bytes(1) == 4096
        monkeypatch.setenv("APEX_TPU_HOST_TIER_WIRE", "int8")
        assert resolve_host_tier_wire("raw") == "int8"

    def test_off_and_zero_disable(self, monkeypatch):
        for off in ("off", "0", " OFF "):
            monkeypatch.setenv("APEX_TPU_HOST_TIER_BYTES", off)
            assert resolve_host_tier_bytes(1 << 20) is None

    def test_malformed_warns_by_name_and_falls_back(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_HOST_TIER_BYTES", "lots")
        with pytest.warns(UserWarning,
                          match="APEX_TPU_HOST_TIER_BYTES"):
            assert resolve_host_tier_bytes(2048) == 2048
        monkeypatch.setenv("APEX_TPU_HOST_TIER_WIRE", "bf16")
        with pytest.warns(UserWarning,
                          match="APEX_TPU_HOST_TIER_WIRE"):
            assert resolve_host_tier_wire("int8") == "int8"

    def test_suffixed_byte_counts(self, monkeypatch):
        """The worker CLI ships strings: plain ints and 256m/2g-style
        binary suffixes both resolve, env or caller."""
        monkeypatch.setenv("APEX_TPU_HOST_TIER_BYTES", "256m")
        assert resolve_host_tier_bytes(None) == 256 << 20
        monkeypatch.delenv("APEX_TPU_HOST_TIER_BYTES")
        assert resolve_host_tier_bytes("2g") == 2 << 30
        assert resolve_host_tier_bytes("64K") == 64 << 10
        assert resolve_host_tier_bytes("4096") == 4096
        assert resolve_host_tier_bytes("off") is None
        with pytest.raises(ValueError):
            resolve_host_tier_bytes("lots")

    def test_caller_validation(self):
        with pytest.raises(ValueError, match="host_tier_bytes"):
            resolve_host_tier_bytes(0)
        with pytest.raises(ValueError, match="host_tier_wire"):
            resolve_host_tier_wire("fp8")
        assert resolve_host_tier_bytes(None) is None
        assert resolve_host_tier_wire(None) == "raw"


class TestHostTierStore:
    def test_request_round_trip_bitwise_raw(self):
        rng = np.random.default_rng(0)
        tier = HostTier(1 << 22, wire="raw", block_size=4)
        for dtype in (np.float32, "bfloat16"):
            dt = jnp.dtype(dtype)
            k, v = _rand_kv(rng, 7)
            k, v = (np.asarray(jnp.asarray(k, dt)),
                    np.asarray(jnp.asarray(v, dt)))
            assert tier.put_request(1, 7, k, v)
            assert tier.has_request(1, 7)
            k2, v2 = tier.take_request(1, 7)
            assert k2.dtype == k.dtype and not tier.has_request(1, 7)
            np.testing.assert_array_equal(k2, k)
            np.testing.assert_array_equal(v2, v)

    def test_int8_wire_bounded_by_block_scale_contract(self):
        """PR 14 contract: the int8 wire quantizes per block with
        scale = maxabs/127, so the decode error is bounded by half a
        quantization step per element."""
        rng = np.random.default_rng(1)
        tier = HostTier(1 << 22, wire="int8", block_size=4)
        k, v = _rand_kv(rng, 16)
        assert tier.put_request(2, 16, k, v)
        k2, v2 = tier.take_request(2, 16)
        for got, want in ((k2, k), (v2, v)):
            got = np.asarray(got, np.float32)
            # per-wire-block maxabs bounds the step; one global bound
            # using the tensor max is looser but still tight enough to
            # catch a broken codec
            step = np.abs(want).max() / 127.0
            assert np.abs(got - want).max() <= step * 0.5 + 1e-7

    def test_lru_bytes_bound_and_eviction_counting(self):
        rng = np.random.default_rng(2)
        k, v = _rand_kv(rng, 4)
        one = 2 * k.nbytes                      # bytes per entry
        tier = HostTier(int(one * 2.5), wire="raw", block_size=4)
        for rid in range(3):
            assert tier.put_request(rid, 4, k, v)
        st = tier.stats()
        assert st["bytes"] <= tier.capacity_bytes
        assert st["entries"] == 2 and st["evictions"] == 1
        assert not tier.has_request(0, 4)       # oldest evicted
        assert tier.has_request(1, 4) and tier.has_request(2, 4)
        # a miss is counted; the evicted request falls back to replay
        assert tier.take_request(0, 4) is None
        assert tier.stats()["misses"] == 1

    def test_oversize_refused_not_stored(self):
        rng = np.random.default_rng(3)
        k, v = _rand_kv(rng, 32)
        tier = HostTier(k.nbytes // 2, wire="raw", block_size=4)
        assert not tier.put_request(9, 32, k, v)
        st = tier.stats()
        assert st["entries"] == 0 and st["bytes"] == 0
        assert st["evictions"] == 1             # refusal is counted

    def test_digest_parking_raw_wire_only(self):
        rng = np.random.default_rng(4)
        k, v = _rand_kv(rng, 4)
        raw = HostTier(1 << 22, wire="raw", block_size=4)
        assert raw.put_block(b"d" * 32, k, v)
        assert raw.has_block(b"d" * 32)
        k2, v2 = raw.peek_block(b"d" * 32)      # peek keeps the copy
        np.testing.assert_array_equal(k2, k)
        assert raw.has_block(b"d" * 32)
        assert raw.newest_digests() == [b"d" * 32]
        # the no-alias rule across tiers: an int8 tier refuses the
        # digest namespace entirely (digest hits skip token re-checks)
        q = HostTier(1 << 22, wire="int8", block_size=4)
        assert not q.put_block(b"d" * 32, k, v)
        assert not q.has_block(b"d" * 32)

    def test_prefetch_stages_decode_ahead(self):
        rng = np.random.default_rng(5)
        k, v = _rand_kv(rng, 6)
        tier = HostTier(1 << 22, wire="raw", block_size=4)
        tier.put_request(3, 6, k, v)
        assert tier.prefetch_request(3, 6)
        assert not tier.prefetch_request(3, 6)  # already staged
        k2, _v2 = tier.take_request(3, 6)
        np.testing.assert_array_equal(k2, k)
        assert not tier.prefetch_request(4, 4)  # absent: no-op


def _preempting_engine(params, cfg, **kw):
    """6 blocks of 4 and two 6-token prompts decoding 10: both admit,
    both outgrow the pool mid-decode — the youngest gets preempted
    (the TestPreemption geometry, with the offload tier switched on)."""
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("prompt_buckets", (8,))
    kw.setdefault("cache_layout", "paged")
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 6)
    kw.setdefault("reserve_blocks", 0)
    return ServingEngine(params, cfg, **kw)


class TestPageInResume:
    def test_resume_is_page_in_not_replay_fp32(self, model):
        """THE ACCEPTANCE PIN: with the tier on, a preempted request
        resumes by paging its raw-wire copy back in — greedy output
        stays token-identical to never being preempted, and the
        hit-rate counters show resume, not replay."""
        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        rng = np.random.RandomState(7)
        p1 = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        p2 = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        reg = telemetry.configure()
        try:
            engine = _preempting_engine(params, cfg,
                                        host_tier_bytes=1 << 24)
            resps = engine.run([dict(prompt=p1, max_new_tokens=10),
                                dict(prompt=p2, max_new_tokens=10)])
            assert reg.counter("serving.preemptions").value >= 1
            assert reg.counter("serving.host_tier.resumes").value >= 1
            assert reg.counter("serving.host_tier.replays").value == 0
            for r, p in zip(resps, (p1, p2)):
                solo = np.asarray(generate(
                    params, jnp.asarray(p[None]), cfg,
                    max_new_tokens=10))[0, 6:]
                np.testing.assert_array_equal(
                    r.tokens, solo, err_msg=f"request {r.request_id}")
            assert engine.idle
            assert engine.stats()["blocks_in_use"] == 0
            assert engine._mgr.n_in_use == 0
            # no parked request copy survives its own resume
            assert not [key for key in engine._host._lru
                        if key[0] == "req"]
        finally:
            telemetry.shutdown()

    @pytest.mark.parametrize("compute,wire", [
        ("float32", "int8"),
        ("bfloat16", "native"),
        ("bfloat16", "int8"),
    ])
    def test_resume_matches_replay_across_pool_forms(
            self, compute, wire):
        """Raw-wire parking is bitwise at the POOL level on every
        compute dtype × pool form: the int8 pool dequantizes for
        parking and requantizes on page-in, and requantization is
        idempotent — so a paged-in engine continues token-identically
        to an identical engine that replays prefill instead."""
        cfg = _cfg(compute_dtype=jnp.dtype(compute))
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(9)
        reqs = [dict(prompt=rng.randint(0, cfg.vocab_size, (6,))
                     .astype(np.int32), max_new_tokens=8)
                for _ in range(2)]
        kw = {} if wire == "native" else {"cache_wire": wire}
        base = _preempting_engine(params, cfg, **kw)
        want = base.run([dict(r) for r in reqs])
        tiered = _preempting_engine(params, cfg,
                                    host_tier_bytes=1 << 24, **kw)
        got = tiered.run([dict(r) for r in reqs])
        assert base.stats()["preemptions"] >= 1
        for w, g in zip(want, got):
            np.testing.assert_array_equal(
                g.tokens, w.tokens, err_msg=f"request {g.request_id}")
        assert tiered.idle and tiered._mgr.n_in_use == 0

    def test_int8_wire_resume_decodes_and_completes(self, model):
        """The compressed wire decodes-but-may-diverge (PR 14): the
        run must complete every request with full token counts and a
        leak-free ledger; token identity is only the raw wire's
        contract."""
        cfg, params = model
        rng = np.random.RandomState(13)
        engine = _preempting_engine(params, cfg,
                                    host_tier_bytes=1 << 24,
                                    host_tier_wire="int8")
        resps = engine.run(
            [dict(prompt=rng.randint(0, cfg.vocab_size, (6,))
                  .astype(np.int32), max_new_tokens=10)
             for _ in range(2)])
        assert engine.stats()["preemptions"] >= 1
        assert all(r.tokens.size == 10 for r in resps)
        assert engine.idle and engine._mgr.n_in_use == 0
        assert engine.stats()["host_tier"]["wire"] == "int8"

    def test_page_in_failure_unwinds_and_replays(self, model):
        """The _admit unwind pattern, page-in edition: an insert raise
        mid-page-in frees the claimed blocks and keeps the request at
        the queue front; the retry degrades to a prefill replay (the
        parked copy was popped by take) and still serves full
        output."""
        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        rng = np.random.RandomState(8)
        reg = telemetry.configure()
        try:
            engine = _preempting_engine(params, cfg,
                                        host_tier_bytes=1 << 24)
            for _ in range(2):
                engine.submit(rng.randint(0, cfg.vocab_size, (8,)),
                              max_new_tokens=12)
            engine._admit()
            resps = []
            while not engine.stats()["queued"]:
                resps.extend(engine.step())    # drive to a preemption
            real_insert = engine._insert_prefill_kv
            boom = {"armed": True}

            def flaky_insert(*a, **k):
                if boom["armed"]:
                    boom["armed"] = False
                    raise RuntimeError("injected page-in failure")
                return real_insert(*a, **k)

            engine._insert_prefill_kv = flaky_insert
            with pytest.raises(RuntimeError, match="page-in"):
                while True:
                    resps.extend(engine.step())
            # nothing leaked, nothing dropped
            assert engine._mgr.n_in_use <= 4   # only the live lane
            assert engine.stats()["queued"] == 1
            resps.extend(engine.run([]))
            assert sorted(r.request_id for r in resps) == [0, 1]
            assert all(r.tokens.size == 12 for r in resps)
            assert engine._mgr.n_in_use == 0
            # the lost parked copy shows up as a replay, honestly
            assert reg.counter("serving.host_tier.replays").value >= 1
        finally:
            telemetry.shutdown()


class TestChunkAlignedDigests:
    def test_chunked_admissions_publish_and_share(self, model):
        """PR 15's follow-up closed: every full block-aligned chunk's
        digest publishes as it lands, so a second chunked admission of
        the same prompt shares the leading whole chunks instead of
        re-prefilling them — and stays greedy-identical."""
        cfg, params = model
        rng = np.random.RandomState(21)
        prompt = rng.randint(0, cfg.vocab_size, (20,)).astype(np.int32)
        want = np.asarray(generate(
            params, jnp.asarray(prompt[None]), cfg,
            max_new_tokens=6))[0, 20:]
        engine = ServingEngine(params, cfg, max_slots=2, max_len=40,
                               prompt_buckets=(8, 24),
                               cache_layout="paged", block_size=4,
                               chunk_tokens=8)
        engine.submit(prompt, max_new_tokens=6)
        # land request 0's chunks (publication happens per chunk)
        engine.step()                           # admits + first chunk
        while engine.stats()["prefilling"]:
            engine.step()
        inv = engine.stats()["digest_inventory"]
        assert inv["chunk_tokens"] == 8 and inv["hbm"]
        engine.submit(prompt, max_new_tokens=6)
        done = engine.run([])
        # 2 whole chunks = 4 blocks shared (the final chunk always
        # runs so the sharer samples its own first token)
        assert engine.stats().get("preemptions", 0) == 0
        shared = max(r.request_id for r in done)  # both completed
        assert shared == 1
        for r in done:
            np.testing.assert_array_equal(
                r.tokens, want, err_msg=f"request {r.request_id}")
        assert engine._mgr.n_in_use == 0

    def test_chunk_share_counts_blocks(self, model):
        cfg, params = model
        rng = np.random.RandomState(22)
        prompt = rng.randint(0, cfg.vocab_size, (20,)).astype(np.int32)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=40,
                               prompt_buckets=(8, 24),
                               cache_layout="paged", block_size=4,
                               chunk_tokens=8)
        engine.submit(prompt, max_new_tokens=12)
        engine.step()
        while engine.stats()["prefilling"]:
            engine.step()
        engine.submit(prompt, max_new_tokens=12)
        saw_shared = 0
        while not engine.idle:
            engine.step()
            saw_shared = max(saw_shared,
                             engine.stats()["prefix_shared_blocks"])
        assert saw_shared >= 4      # 2 whole chunks x (8/4) blocks
        assert engine._mgr.n_in_use == 0

    def test_chunk_digests_namespace_separate_from_flash(self):
        toks = np.arange(16, dtype=np.int32)
        flash = prefix_block_hashes(toks, 4)
        chunk = prefix_block_hashes(toks, 4, salt=chunk_salt(8))
        assert len(flash) == len(chunk) == 4
        assert all(a != b for a, b in zip(flash, chunk))

    def test_cold_chunk_prefix_pages_in_from_host(self, model):
        """The cross-tier chunk path: a completed chunked request's
        published digests park in the host tier; a later identical
        prompt pages the leading chunks back in instead of
        re-prefilling them."""
        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        rng = np.random.RandomState(23)
        prompt = rng.randint(0, cfg.vocab_size, (20,)).astype(np.int32)
        want = np.asarray(generate(
            params, jnp.asarray(prompt[None]), cfg,
            max_new_tokens=6))[0, 20:]
        reg = telemetry.configure()
        try:
            engine = ServingEngine(params, cfg, max_slots=2,
                                   max_len=40, prompt_buckets=(8, 24),
                                   cache_layout="paged", block_size=4,
                                   chunk_tokens=8,
                                   host_tier_bytes=1 << 24)
            first = engine.run([dict(prompt=prompt, max_new_tokens=6)])
            # the cold prefix now lives ONLY in the host tier
            assert engine.stats()["blocks_in_use"] == 0
            assert engine.stats()["host_tier"]["pages"] >= 4
            assert engine.stats()["digest_inventory"]["host"]
            second = engine.run([dict(prompt=prompt,
                                      max_new_tokens=6)])
            assert reg.counter(
                "serving.host_tier.page_ins").value >= 4
            for r in first + second:
                np.testing.assert_array_equal(
                    r.tokens, want, err_msg=f"request {r.request_id}")
            assert engine._mgr.n_in_use == 0
        finally:
            telemetry.shutdown()


def _make_handoff(params, cfg, prompt, bucket=8):
    """A raw-wire fresh-prefill handoff, exactly as the prefill worker
    builds one (paged scratch, wire round trip)."""
    from apex_tpu.serving.batching import pad_prompt

    n = int(prompt.size)
    scratch = init_kv_cache(cfg, 1, bucket,
                            cache_dtype=cfg.compute_dtype,
                            cache_layout="paged", block_size=4)
    logits, cache = prefill(
        params, jnp.asarray(pad_prompt(prompt, bucket)[None]), cfg,
        prompt_lens=jnp.asarray([n], np.int32), cache=scratch)
    k, v = extract_kv(cache, n, row=0)
    header, blobs = encode_kv(np.asarray(k), np.asarray(v),
                              wire_dtype="raw")
    k2, v2 = decode_kv(header, blobs)
    return k2, v2, int(np.argmax(np.asarray(logits)[0]))


class TestShareableHandoff:
    def test_shareable_handoff_publishes_and_shares(self, model):
        """A raw-wire fresh-prefill handoff is bit-identical to a
        local flash prefill, so ``submit_prefilled(shareable=True)``
        publishes under the flash namespace — a second identical
        handoff shares the pages and decodes identically."""
        cfg, params = model
        rng = np.random.RandomState(31)
        prompt = rng.randint(0, cfg.vocab_size, (7,)).astype(np.int32)
        k, v, first = _make_handoff(params, cfg, prompt)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,),
                               cache_layout="paged", block_size=4)
        engine.submit_prefilled(prompt, k, v, first,
                                max_new_tokens=12, shareable=True)
        engine._admit()
        assert engine.stats()["digest_inventory"]["hbm"]
        engine.submit_prefilled(prompt, k, v, first,
                                max_new_tokens=12, shareable=True)
        saw_shared = 0
        resps = []
        while not engine.idle:
            resps.extend(engine.step())
            saw_shared = max(saw_shared,
                             engine.stats()["prefix_shared_blocks"])
        assert saw_shared >= 1      # 7 tokens -> 1 full shared block
        assert len(resps) == 2
        np.testing.assert_array_equal(resps[0].tokens, resps[1].tokens)
        assert engine._mgr.n_in_use == 0

    def test_unshareable_handoff_stays_private(self, model):
        cfg, params = model
        rng = np.random.RandomState(32)
        prompt = rng.randint(0, cfg.vocab_size, (7,)).astype(np.int32)
        k, v, first = _make_handoff(params, cfg, prompt)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,),
                               cache_layout="paged", block_size=4)
        engine.submit_prefilled(prompt, k, v, first, max_new_tokens=4)
        engine._admit()
        assert not engine.stats()["digest_inventory"]["hbm"]
        assert engine.run([])[0].tokens.size == 4


def _bare_router(**kw):
    from apex_tpu.serving.cluster.router import Router
    from apex_tpu.serving.slo import resolve_slo_targets

    r = object.__new__(Router)
    r._prefill, r._decode = [], []
    r._slo_targets = resolve_slo_targets(None)
    r._caps = kw.get("queue_caps", {})
    r._priority = ("interactive", "standard", "default", "batch")
    r.wire_dtype = "raw"
    r._max_worker_queue = 4
    r._queues = {}
    r._next_rid = 0
    r._pf_rr = 0
    r._last_decode_pick = None
    r._requeued_total = 0
    r._completed_total = 0
    r._drain_completed = []
    return r


class _InvWorker:
    _n = [0]

    def __init__(self, headroom=64, hbm=(), host=(), block_size=4,
                 chunk_tokens=None, host_free=None):
        self._n[0] += 1
        self.addr = f"inv{self._n[0]}"
        self.alive, self.draining = True, False
        self.in_flight = {}
        self.dispatched_since_poll = 0
        self.stats = {"headroom_tokens": headroom, "max_slots": 4,
                      "active": 1, "queued": 0, "block_size": block_size,
                      "digest_inventory": {
                          "block_size": block_size,
                          "chunk_tokens": chunk_tokens,
                          "hbm": list(hbm), "host": list(host)}}
        if host_free is not None:
            self.stats["host_tier"] = {"free_bytes": host_free,
                                       "bytes": 0}


class TestPrefixAffinityRouting:
    def _digests(self, prompt, block_size=4, chunk_tokens=None):
        salt = (chunk_salt(chunk_tokens)
                if chunk_tokens and len(prompt) > chunk_tokens else b"")
        return [h.hex()[:16] for h in prefix_block_hashes(
            np.asarray(prompt, np.int32), block_size, salt=salt)]

    def test_router_digests_mirror_engine_namespaces(self):
        from apex_tpu.serving.cluster.router import _prompt_digests

        prompt = list(range(1, 21))
        assert _prompt_digests(prompt, 4, 0) == self._digests(prompt)
        # a prompt the worker would chunk hashes in the chunk namespace
        assert (_prompt_digests(prompt, 4, 8)
                == self._digests(prompt, chunk_tokens=8))
        # and one shorter than chunk_tokens stays in the flash one
        short = prompt[:6]
        assert _prompt_digests(short, 4, 8) == self._digests(short)

    def test_affinity_beats_headroom(self):
        from apex_tpu.observability import metrics as telemetry

        prompt = list(range(1, 21))
        holder = _InvWorker(headroom=8, hbm=self._digests(prompt))
        bigger = _InvWorker(headroom=640)
        reg = telemetry.configure()
        try:
            r = _bare_router()
            r._decode = [bigger, holder]
            r.submit(prompt, max_new_tokens=4)
            pend = r._queues["default"][0]
            assert r._pick_decode(pend) is holder
            assert reg.counter(
                "cluster.prefix_affinity_hits").value == 1
            # no affinity anywhere -> headroom ordering, no hit count
            r2 = _bare_router()
            r2._decode = [bigger, _InvWorker(headroom=8)]
            r2.submit(list(range(50, 70)), max_new_tokens=4)
            assert r2._pick_decode(r2._queues["default"][0]) is bigger
            assert reg.counter(
                "cluster.prefix_affinity_hits").value == 1
        finally:
            telemetry.shutdown()

    def test_hbm_outweighs_host_at_equal_depth(self):
        prompt = list(range(1, 21))
        digs = self._digests(prompt)
        hbm_holder = _InvWorker(headroom=8, hbm=[digs[-1]])
        host_holder = _InvWorker(headroom=640, host=[digs[-1]])
        r = _bare_router()
        r._decode = [host_holder, hbm_holder]
        r.submit(prompt, max_new_tokens=4)
        pend = r._queues["default"][0]
        assert r._pick_decode(pend) is hbm_holder
        # chain depth: a deeper host match beats a shallow HBM one
        # (5 blocks x1 > 2 blocks x2)
        deep_host = _InvWorker(headroom=8, host=[digs[4]])
        shallow_hbm = _InvWorker(headroom=640, hbm=[digs[1]])
        r2 = _bare_router()
        r2._decode = [shallow_hbm, deep_host]
        r2.submit(prompt, max_new_tokens=4)
        assert r2._pick_decode(r2._queues["default"][0]) is deep_host

    def test_workers_without_inventory_fall_back(self):
        class _Legacy(_InvWorker):
            def __init__(self):
                super().__init__(headroom=128)
                del self.stats["digest_inventory"]

        r = _bare_router()
        legacy, small = _Legacy(), _InvWorker(headroom=16)
        r._decode = [small, legacy]
        r.submit([1, 2, 3], max_new_tokens=4)
        assert r._pick_decode(r._queues["default"][0]) is legacy

    def test_scale_hint_host_tier_awareness(self):
        """Exhausted HBM with an empty router queue and free host-DRAM
        reads as HOLD (preemptions degrade to cheap page-ins), while
        the same exhaustion without the tier still reads grow."""
        r = _bare_router()
        r._decode = [_InvWorker(headroom=0)]
        r._prefill = [_InvWorker()]
        assert r.autoscale_signal()["decode"]["hint"] == 1
        r2 = _bare_router()
        r2._decode = [_InvWorker(headroom=0, host_free=1 << 20)]
        r2._prefill = [_InvWorker()]
        sig = r2.autoscale_signal()
        assert sig["decode"]["hint"] == 0
        assert sig["decode"]["host_tier_free_bytes"] == 1 << 20
        # queued work still demands growth, tier or no tier
        for _ in range(9):
            r2.submit([1, 2], max_new_tokens=2)
        assert r2.autoscale_signal()["decode"]["hint"] == 1


class TestServeDashHostTierRow:
    def test_dash_renders_host_tier_row_from_live_exporter(
            self, model):
        """ISSUE 18 satellite: the dashboard surfaces the per-pool
        host-tier row (parked footprint, hit rate, resumes/replays)
        when the serving.host_tier.* families are present — and hides
        it when the tier is off."""
        import importlib.util
        import io
        import os

        import apex_tpu.observability as obs

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "serve_dash", os.path.join(repo, "tools", "serve_dash.py"))
        dash = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(dash)
        om = dash.load_openmetrics_module()

        cfg, params = model
        rng = np.random.RandomState(41)
        reg = obs.configure(export_port=0)
        try:
            engine = _preempting_engine(params, cfg,
                                        host_tier_bytes=1 << 24)
            engine.run([dict(prompt=rng.randint(0, cfg.vocab_size,
                                                (6,)).astype(np.int32),
                             max_new_tokens=10) for _ in range(2)])
            assert reg.counter("serving.host_tier.resumes").value >= 1
            out = io.StringIO()
            snap = dash.one_frame(om, reg.exporter.url, out=out)
            assert snap["host_tier_bytes"] is not None
            assert snap["host_tier_resumes"] >= 1
            text = out.getvalue()
            assert "host tier" in text and "resumes" in text
        finally:
            obs.shutdown()
        # tier off: families absent, row hidden
        reg = obs.configure(export_port=0)
        try:
            engine = _preempting_engine(params, cfg)
            engine.run([dict(prompt=rng.randint(0, cfg.vocab_size,
                                                (6,)).astype(np.int32),
                             max_new_tokens=4)])
            out = io.StringIO()
            snap = dash.one_frame(om, reg.exporter.url, out=out)
            assert snap["host_tier_bytes"] is None
            assert "host tier" not in out.getvalue()
        finally:
            obs.shutdown()
