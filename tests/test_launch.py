"""Multi-host bootstrap env resolution (init_process_group analog)."""

import jax

from apex_tpu.parallel.launch import distributed_env, init_distributed


class TestDistributedEnv:
    def test_jax_native_vars(self):
        env = {"COORDINATOR_ADDRESS": "10.0.0.1:1234",
               "PROCESS_ID": "3", "NUM_PROCESSES": "16"}
        assert distributed_env(env) == ("10.0.0.1:1234", 3, 16)

    def test_torch_style_vars(self):
        env = {"MASTER_ADDR": "host0", "MASTER_PORT": "29500",
               "RANK": "2", "WORLD_SIZE": "8"}
        assert distributed_env(env) == ("host0:29500", 2, 8)

    def test_torch_default_port_and_node_rank(self):
        env = {"MASTER_ADDR": "host0", "NODE_RANK": "1",
               "WORLD_SIZE": "4"}
        coord, pid, nproc = distributed_env(env)
        assert coord == "host0:8476" and pid == 1 and nproc == 4

    def test_rank_beats_node_rank(self):
        # torchrun, 2 nodes x 4 procs: only the global RANK is unique
        env = {"MASTER_ADDR": "host0", "RANK": "5", "NODE_RANK": "1",
               "WORLD_SIZE": "8"}
        assert distributed_env(env)[1] == 5

    def test_empty(self):
        assert distributed_env({}) == (None, None, None)

    def test_native_wins_over_torch(self):
        env = {"COORDINATOR_ADDRESS": "c:1", "MASTER_ADDR": "m",
               "PROCESS_ID": "0", "RANK": "9", "NUM_PROCESSES": "2",
               "WORLD_SIZE": "99"}
        assert distributed_env(env) == ("c:1", 0, 2)


class TestInitDistributed:
    def test_single_host_noop(self, monkeypatch):
        for var in ("COORDINATOR_ADDRESS", "MASTER_ADDR", "RANK",
                    "WORLD_SIZE", "PROCESS_ID", "NUM_PROCESSES"):
            monkeypatch.delenv(var, raising=False)
        import apex_tpu.parallel.launch as launch
        monkeypatch.setattr(launch, "_initialized", False)
        assert init_distributed() == 1
        # idempotent
        assert init_distributed() == jax.process_count()

    def test_world_size_one_noop(self, monkeypatch):
        import apex_tpu.parallel.launch as launch
        monkeypatch.setattr(launch, "_initialized", False)
        monkeypatch.setenv("MASTER_ADDR", "localhost")
        monkeypatch.setenv("WORLD_SIZE", "1")
        monkeypatch.setenv("RANK", "0")
        assert init_distributed() == 1

    def test_latched_initialized_short_circuits(self, monkeypatch):
        import apex_tpu.parallel.launch as launch
        monkeypatch.setattr(launch, "_initialized", True)

        def boom(*a, **k):
            raise AssertionError("must not re-initialize")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        assert init_distributed("10.0.0.1:1", 8, 0) == jax.process_count()

    def test_world_size_without_coordinator_raises(self, monkeypatch):
        import pytest

        import apex_tpu.parallel.launch as launch
        monkeypatch.setattr(launch, "_initialized", False)
        for var in ("COORDINATOR_ADDRESS", "MASTER_ADDR"):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv("WORLD_SIZE", "8")
        monkeypatch.setenv("RANK", "2")
        with pytest.raises(RuntimeError, match="no coordinator"):
            init_distributed()

    def test_master_addr_without_ranks_raises(self, monkeypatch):
        import pytest

        import apex_tpu.parallel.launch as launch
        monkeypatch.setattr(launch, "_initialized", False)
        for var in ("COORDINATOR_ADDRESS", "WORLD_SIZE", "RANK",
                    "NODE_RANK", "PROCESS_ID", "NUM_PROCESSES",
                    *launch._CLUSTER_ENV_MARKERS):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv("MASTER_ADDR", "host0")
        with pytest.raises(RuntimeError, match="WORLD_SIZE"):
            init_distributed()

    def test_master_addr_under_cluster_warns_and_defers(self, monkeypatch):
        import warnings

        import apex_tpu.parallel.launch as launch
        monkeypatch.setattr(launch, "_initialized", False)
        for var in ("COORDINATOR_ADDRESS", "WORLD_SIZE", "RANK",
                    "NODE_RANK", "PROCESS_ID", "NUM_PROCESSES",
                    *launch._CLUSTER_ENV_MARKERS):
            monkeypatch.delenv(var, raising=False)
        # Slurm host where a site profile incidentally exports MASTER_ADDR:
        # must NOT abort, and must NOT pass the untrustworthy coordinator
        # through (it is often localhost — every node would connect to
        # itself); jax's cluster plugin autodetects all three fields.
        monkeypatch.setenv("MASTER_ADDR", "host0")
        monkeypatch.setenv("SLURM_JOB_ID", "1234")
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            init_distributed()
        assert any("managed-cluster" in str(w.message) for w in caught)
        assert calls == [{"coordinator_address": None,
                          "num_processes": None, "process_id": None}]


class TestProbeJax:
    """The killable subprocess probe both gates depend on
    (utils/probe.py — a dead tunnel hangs jax.devices() in C++)."""

    def test_probe_returns_value(self, monkeypatch):
        from apex_tpu.utils.probe import probe_jax

        monkeypatch.setenv("APEX_TPU_PROBE_CACHE_TTL", "0")
        # conftest pins the child env to CPU: a real jax evaluates
        assert probe_jax("1 + 1", timeout_s=120) == "2"

    def test_probe_failure_returns_none_and_reports(self, monkeypatch,
                                                    capsys):
        from apex_tpu.utils.probe import probe_jax

        monkeypatch.setenv("APEX_TPU_PROBE_CACHE_TTL", "0")
        got = probe_jax("jax.nonexistent_attr_xyz", timeout_s=120,
                        label="unit probe")
        assert got is None
        err = capsys.readouterr().out
        assert "unit probe" in err and "failed" in err

    def test_probe_backend_info_shared_expression(self, monkeypatch,
                                                  tmp_path):
        """bench and the dryrun gate probe the SAME expression, so one
        cached outage verdict covers both gates of a driver run."""
        import apex_tpu.utils.probe as probe

        # the probe child must not load the axon sitecustomize (it
        # overrides JAX_PLATFORMS and would hang on a dead tunnel —
        # in production that hang IS the signal; in this unit test we
        # want the CPU answer)
        monkeypatch.delenv("PYTHONPATH", raising=False)
        monkeypatch.setattr(probe, "_CACHE_PATH",
                            str(tmp_path / "cache.json"))
        monkeypatch.setenv("APEX_TPU_PROBE_CACHE_TTL", "300")
        got = probe.probe_backend_info(timeout_s=120)
        assert got is not None
        platform, count = got
        assert platform == "cpu" and count >= 1   # conftest pins CPU
        # the second gate's call must be served from the cache
        import subprocess as sp

        def boom(*a, **kw):
            raise AssertionError("second gate must not re-probe")

        monkeypatch.setattr(sp, "run", boom)
        assert probe.probe_backend_info(timeout_s=120) == (platform, count)

    def test_probe_backend_info_malformed_cache_reprobes(self, monkeypatch,
                                                         tmp_path, capsys):
        """ISSUE 1 satellite: a corrupted/foreign cache entry (empty
        count like ``"cpu:"``, non-numeric count, colon-less garbage) is
        REJECTED at the cache layer — a fresh probe runs instead of the
        gates trusting garbage (or reading healthy hosts as unreachable)
        for a whole TTL."""
        import json as _json
        import time as _time

        import apex_tpu.utils.probe as probe

        path = tmp_path / "cache.json"
        expr = ("jax.devices()[0].platform + ':' + str(len("
                "jax.devices()))")
        monkeypatch.delenv("PYTHONPATH", raising=False)
        monkeypatch.setattr(probe, "_CACHE_PATH", str(path))
        monkeypatch.setenv("APEX_TPU_PROBE_CACHE_TTL", "300")
        for bad in ("cpu:not_a_number", "cpu:", "garbage", ":8"):
            path.write_text(_json.dumps(
                {expr: {"t": _time.time(), "val": bad}}))
            got = probe.probe_backend_info(timeout_s=120)
            assert got is not None and got[0] == "cpu", bad
            # the re-probe replaced the malformed entry with a valid one
            assert probe._parse_backend_info(
                _json.loads(path.read_text())[expr]["val"]) is not None
        # wrong-type entries are ignored entirely (cache miss, no crash)
        path.write_text(_json.dumps({expr: {"t": "yesterday", "val": 7}}))
        assert probe._cache_get(expr) is probe._MISS
        # a non-dict top-level document is a miss on read and replaced
        # on write, not a crash in either gate
        path.write_text(_json.dumps(["garbage"]))
        assert probe._cache_get(expr) is probe._MISS
        probe._cache_put(expr, "cpu:1")
        assert probe._cache_get(expr) == "cpu:1"

    def test_resolve_timeout_env_override(self, monkeypatch, capsys):
        """ISSUE 5 satellite: APEX_TPU_PROBE_TIMEOUT is the operator
        knob for slow-to-answer tunnels (BENCH_r05 lost every row to
        the hard-coded 45s) — it beats caller values, malformed values
        warn by name and fall through."""
        from apex_tpu.utils.probe import resolve_timeout

        monkeypatch.delenv("APEX_TPU_PROBE_TIMEOUT", raising=False)
        assert resolve_timeout(None) == 45            # default
        assert resolve_timeout(None, default=60) == 60
        assert resolve_timeout(90) == 90              # caller value
        monkeypatch.setenv("APEX_TPU_PROBE_TIMEOUT", "120")
        assert resolve_timeout(None) == 120
        assert resolve_timeout(30) == 120             # env beats caller
        monkeypatch.setenv("APEX_TPU_PROBE_TIMEOUT", "12.9")
        assert resolve_timeout(None) == 12            # float accepted
        for bad in ("abc", "-5", "0", ""):
            monkeypatch.setenv("APEX_TPU_PROBE_TIMEOUT", bad)
            capsys.readouterr()
            assert resolve_timeout(33) == 33, bad
            out = capsys.readouterr().out
            if bad:   # empty string is falsy — silently ignored
                assert "APEX_TPU_PROBE_TIMEOUT" in out, bad

    def test_probe_log_line_names_timeout(self, monkeypatch, capsys):
        """The chosen timeout (and its env provenance) lands in the
        probe log line so a skipped-row post-mortem can see which
        timeout actually applied."""
        import apex_tpu.utils.probe as probe

        monkeypatch.delenv("PYTHONPATH", raising=False)
        monkeypatch.setenv("APEX_TPU_PROBE_CACHE_TTL", "0")
        monkeypatch.setenv("APEX_TPU_PROBE_TIMEOUT", "77")
        assert probe.probe_jax("1 + 1", label="timeout probe") == "2"
        out = capsys.readouterr().out
        assert "timeout 77s" in out
        assert "(from APEX_TPU_PROBE_TIMEOUT)" in out

    def test_probe_backend_info_fresh_malformed_result(self, monkeypatch,
                                                       capsys):
        """A FRESH probe answer that does not parse degrades to None
        (printed + cached as an outage verdict), never a ValueError out
        of the gates."""
        import subprocess as sp
        import types

        import apex_tpu.utils.probe as probe

        monkeypatch.setenv("APEX_TPU_PROBE_CACHE_TTL", "0")
        for bad in ("cpu:", "cpu:eight", "no_colon_here"):
            monkeypatch.setattr(
                sp, "run",
                lambda *a, bad=bad, **kw: types.SimpleNamespace(
                    stdout=f"PROBE={bad}\n", stderr="", returncode=0))
            assert probe.probe_backend_info(timeout_s=5) is None
            out = capsys.readouterr().out
            assert "unparseable" in out and repr(bad)[1:-1] in out

    def test_parse_backend_info(self):
        from apex_tpu.utils.probe import _parse_backend_info

        assert _parse_backend_info("cpu:8") == ("cpu", 8)
        assert _parse_backend_info("tpu:1") == ("tpu", 1)
        for bad in ("cpu:", "cpu", ":8", "cpu:x", "", "cpu:１"):
            assert _parse_backend_info(bad) is None, bad

    def test_probe_cache_shares_verdicts(self, monkeypatch, tmp_path,
                                         capsys):
        """An outage verdict (None) is reused within the TTL so the
        second gate of a driver invocation does not re-pay the hang
        timeout (VERDICT r4 #7); TTL=0 opts out."""
        import subprocess as sp

        import apex_tpu.utils.probe as probe

        monkeypatch.setattr(probe, "_CACHE_PATH",
                            str(tmp_path / "cache.json"))
        monkeypatch.setenv("APEX_TPU_PROBE_CACHE_TTL", "300")
        runs = []
        real_run = sp.run

        def counting_run(*a, **kw):
            runs.append(1)
            return real_run(*a, **kw)

        monkeypatch.setattr(sp, "run", counting_run)
        assert probe.probe_jax("40 + 2", timeout_s=120) == "42"
        assert probe.probe_jax("40 + 2", timeout_s=120) == "42"
        assert len(runs) == 1   # second call served from the cache
        assert "cached" in capsys.readouterr().out
        # failures cache too — the expensive case on a dead tunnel
        assert probe.probe_jax("jax.nope_xyz", timeout_s=120,
                               label="p1") is None
        assert probe.probe_jax("jax.nope_xyz", timeout_s=120,
                               label="p2") is None
        assert len(runs) == 2
        # expired entries re-probe
        monkeypatch.setenv("APEX_TPU_PROBE_CACHE_TTL", "0")
        assert probe.probe_jax("40 + 2", timeout_s=120) == "42"
        assert len(runs) == 3
