"""Chunked prefill (ISSUE 15): the model-level primitive and the
engine's mixed prefill/decode step batching.

The acceptance pins: chunked-vs-monolithic prefill greedy
token-identical on both cache layouts (first-token-identical on the
int8 ``cache_wire`` pool), including across a mid-prefill
preempt→resume and with speculative decoding enabled; one prefill
chunk per engine step interleaved with co-resident decode; and the
tokens-admittable headroom signal."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import observability as obs
from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import (
    decode_step, init_kv_cache, prefill, prefill_chunked)
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.serving import ServingEngine


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 128)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _greedy_continue(params, cfg, logits, cache, steps=6):
    """argmax continuation — the real token-identity check (cache
    CONTENT equality is too strict: chunk vs flash accumulation order
    may differ in low bits; what must not differ is the decode)."""
    toks = []
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    toks.append(np.asarray(tok))
    for _ in range(steps - 1):
        logits, cache = decode_step(params, tok, cache, cfg)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        toks.append(np.asarray(tok))
    return np.stack(toks, 1)


class TestPrefillChunked:
    @pytest.mark.parametrize("layout", ["contiguous", "paged"])
    @pytest.mark.parametrize("chunk", [16, 13, 64])
    def test_greedy_identical_to_monolithic(self, model, layout,
                                            chunk):
        """Dividing, non-dividing, and larger-than-prompt chunk sizes:
        the final chunk's last-token logits ARE the first-token logits
        and the greedy continuation is token-identical."""
        cfg, params = model
        rng = np.random.RandomState(0)
        prompt = jnp.asarray(rng.randint(0, 128, (2, 37)))
        kw = dict(cache_layout=layout, block_size=8)
        c1 = init_kv_cache(cfg, 2, 60, **kw)
        lg_m, cm = prefill(params, prompt, cfg, cache=c1)
        c2 = init_kv_cache(cfg, 2, 60, **kw)
        lg_c, cc = prefill_chunked(params, prompt, cfg,
                                   chunk_tokens=chunk, cache=c2)
        assert (np.asarray(jnp.argmax(lg_m, -1))
                == np.asarray(jnp.argmax(lg_c, -1))).all()
        gm = _greedy_continue(params, cfg, lg_m, cm)
        gc = _greedy_continue(params, cfg, lg_c, cc)
        assert (gm == gc).all()
        assert (np.asarray(cc["pos"]) == 37).all()

    @pytest.mark.parametrize("layout", ["contiguous", "paged"])
    def test_ragged_rows_pick_their_own_last_token(self, model,
                                                   layout):
        """Rows whose prompt ends inside an EARLIER chunk must return
        that chunk's logits row, and every row's continuation matches
        the monolithic ragged prefill."""
        cfg, params = model
        rng = np.random.RandomState(1)
        prompt = jnp.asarray(rng.randint(0, 128, (3, 37)))
        lens = jnp.asarray([37, 20, 5], jnp.int32)
        kw = dict(cache_layout=layout, block_size=8)
        lg_m, cm = prefill(params, prompt, cfg, prompt_lens=lens,
                           cache=init_kv_cache(cfg, 3, 60, **kw))
        lg_c, cc = prefill_chunked(
            params, prompt, cfg, chunk_tokens=16, prompt_lens=lens,
            cache=init_kv_cache(cfg, 3, 60, **kw))
        assert (np.asarray(jnp.argmax(lg_m, -1))
                == np.asarray(jnp.argmax(lg_c, -1))).all()
        gm = _greedy_continue(params, cfg, lg_m, cm)
        gc = _greedy_continue(params, cfg, lg_c, cc)
        assert (gm == gc).all()
        assert np.asarray(cc["pos"]).tolist() == [37, 20, 5]

    def test_int8_pool_first_token_identical(self, model):
        """int8 cache_wire: the PR-14 contract — deterministic and
        first-token-identical (later chunks read the quantized prefix,
        so the trajectory beyond it carries the documented int8
        divergence allowance)."""
        cfg, params = model
        rng = np.random.RandomState(2)
        prompt = jnp.asarray(rng.randint(0, 128, (2, 37)))
        kw = dict(cache_layout="paged", block_size=8,
                  cache_wire="int8")
        lg_m, _ = prefill(params, prompt, cfg,
                          cache=init_kv_cache(cfg, 2, 60, **kw))
        lg_c, _ = prefill_chunked(
            params, prompt, cfg, chunk_tokens=16,
            cache=init_kv_cache(cfg, 2, 60, **kw))
        assert (np.asarray(jnp.argmax(lg_m, -1))
                == np.asarray(jnp.argmax(lg_c, -1))).all()
        # deterministic: a second chunked run is bitwise the first
        lg_c2, _ = prefill_chunked(
            params, prompt, cfg, chunk_tokens=16,
            cache=init_kv_cache(cfg, 2, 60, **kw))
        assert (np.asarray(lg_c) == np.asarray(lg_c2)).all()

    def test_bad_args_raise(self, model):
        cfg, params = model
        prompt = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="chunk_tokens"):
            prefill_chunked(params, prompt, cfg, chunk_tokens=0)
        with pytest.raises(ValueError, match="exceeds the cache"):
            prefill_chunked(params, prompt, cfg, chunk_tokens=4,
                            cache=init_kv_cache(cfg, 1, 4))


def _reqs(rng, n_short=2, long_prompt=60):
    reqs = [dict(prompt=rng.randint(0, 128, (long_prompt,)),
                 max_new_tokens=8, slo_class="batch")]
    reqs += [dict(prompt=rng.randint(0, 128, (7 + 3 * i,)),
                  max_new_tokens=6) for i in range(n_short)]
    return reqs


def _run_engine(params, cfg, reqs, **kw):
    eng = ServingEngine(params, cfg, **kw)
    out = eng.run([dict(r, prompt=r["prompt"].copy()) for r in reqs])
    return eng, out


class TestEngineChunked:
    @pytest.mark.parametrize("layout", ["contiguous", "paged"])
    def test_greedy_identical_to_monolithic_engine(self, model,
                                                   layout):
        cfg, params = model
        rng = np.random.RandomState(3)
        reqs = _reqs(rng)
        kw = dict(max_slots=3, max_len=96, cache_layout=layout)
        if layout == "paged":
            kw["block_size"] = 8
        _, ref = _run_engine(params, cfg, reqs, **kw)
        eng, out = _run_engine(params, cfg, reqs, chunk_tokens=16,
                               **kw)
        assert [r.tokens.tolist() for r in out] == [
            r.tokens.tolist() for r in ref]
        # the long prompt actually went through the chunked path
        assert eng.stats()["chunk_tokens"] == 16

    def test_spec_decode_composes(self, model):
        """spec + chunked greedy == plain engine greedy: the lane
        joins the speculative batch after its last chunk."""
        cfg, params = model
        rng = np.random.RandomState(4)
        reqs = _reqs(rng)
        kw = dict(max_slots=3, max_len=96, cache_layout="paged",
                  block_size=8)
        _, ref = _run_engine(params, cfg, reqs, **kw)
        _, out = _run_engine(params, cfg, reqs, chunk_tokens=16,
                             spec="ngram", **kw)
        assert [r.tokens.tolist() for r in out] == [
            r.tokens.tolist() for r in ref]

    def test_int8_wire_first_token_identical(self, model):
        cfg, params = model
        rng = np.random.RandomState(5)
        reqs = _reqs(rng)
        kw = dict(max_slots=3, max_len=96, cache_layout="paged",
                  block_size=8, cache_wire="int8")
        _, ref = _run_engine(params, cfg, reqs, **kw)
        _, out = _run_engine(params, cfg, reqs, chunk_tokens=16, **kw)
        assert [r.tokens.tolist()[0] for r in out] == [
            r.tokens.tolist()[0] for r in ref]

    def test_decode_progresses_between_chunks(self, model):
        """The mixed-step property itself: while the long prompt is
        mid-prefill, co-resident lanes keep emitting — a short request
        FINISHES before the long one produces its first token."""
        cfg, params = model
        rng = np.random.RandomState(6)
        eng = ServingEngine(params, cfg, max_slots=2, max_len=96,
                            cache_layout="paged", block_size=8,
                            chunk_tokens=8)
        short = eng.submit(rng.randint(0, 128, (6,)),
                           max_new_tokens=4)
        eng.step()                       # short admits and decodes
        long_rid = eng.submit(rng.randint(0, 128, (60,)),
                              max_new_tokens=4)
        order = []
        while not eng.idle:
            for r in eng.step():
                order.append(r.request_id)
        assert order.index(short) < order.index(long_rid)
        # and the long prompt really streamed: >1 chunk counted
        st = eng.stats()
        assert st["prefilling"] == 0

    def test_chunk_telemetry(self, model):
        """serving.prefill_chunks counts every chunk; the progress
        gauges exist (and drain to zero) on a chunked engine; exactly
        one prefill_calls per request."""
        cfg, params = model
        reg = obs.configure()
        try:
            rng = np.random.RandomState(7)
            reqs = _reqs(rng, n_short=1, long_prompt=40)
            _, out = _run_engine(params, cfg, reqs, chunk_tokens=16,
                                 max_slots=2, max_len=96,
                                 cache_layout="paged", block_size=8)
            assert len(out) == 2
            recs = reg.snapshot()
            chunks = sum(r["value"] for r in recs
                         if r["kind"] == "counter"
                         and r["name"] == "serving.prefill_chunks")
            assert chunks == 3           # ceil(40/16)
            calls = sum(r["value"] for r in recs
                        if r["kind"] == "counter"
                        and r["name"] == "serving.prefill_calls")
            assert calls == 2
            gauges = {r["name"]: r["value"] for r in recs
                      if r["kind"] == "gauge"}
            assert gauges.get("serving.prefilling") == 0
            assert gauges.get("serving.prefill_progress_total") == 0
        finally:
            obs.shutdown()

    def test_mid_prefill_preempt_resume_parity(self, model):
        """A prefilling lane evicted between chunks (pool pressure)
        resumes by replaying its chunks — greedy outputs identical to
        a monolithic engine run of the same requests."""
        cfg, params = model
        rng = np.random.RandomState(8)
        # tiny pool: the shorts' tail allocation must evict the
        # youngest (the long, still prefilling) at least once
        reqs = [dict(prompt=rng.randint(0, 128, (10,)),
                     max_new_tokens=10) for _ in range(2)]
        reqs.append(dict(prompt=rng.randint(0, 128, (40,)),
                         max_new_tokens=4, slo_class="batch"))
        kw = dict(max_slots=3, max_len=64, cache_layout="paged",
                  block_size=4, num_blocks=20, reserve_blocks=0)
        eng_ref, ref = _run_engine(params, cfg, reqs, **kw)
        eng, out = _run_engine(params, cfg, reqs, chunk_tokens=8,
                               **kw)
        assert [r.tokens.tolist() for r in out] == [
            r.tokens.tolist() for r in ref]
        assert eng.stats()["preemptions"] >= 1

    def test_short_prompts_keep_monolithic_path(self, model):
        """Prompts <= chunk_tokens admit through the one-shot path
        (prefix sharing stays available for them)."""
        cfg, params = model
        reg = obs.configure()
        try:
            eng = ServingEngine(params, cfg, max_slots=2, max_len=64,
                                cache_layout="paged", block_size=8,
                                chunk_tokens=32)
            eng.submit(np.arange(1, 9), max_new_tokens=2)
            while not eng.idle:
                eng.step()
            chunks = sum(r["value"] for r in reg.snapshot()
                         if r["kind"] == "counter"
                         and r["name"] == "serving.prefill_chunks")
            assert chunks == 0
        finally:
            obs.shutdown()

    def test_chunked_blocks_never_prefix_shared(self, model):
        """Two identical long prompts through the chunked path share
        nothing (chunk-written pages are digest-invisible by design)."""
        cfg, params = model
        rng = np.random.RandomState(9)
        prompt = rng.randint(0, 128, (40,))
        eng = ServingEngine(params, cfg, max_slots=2, max_len=64,
                            cache_layout="paged", block_size=8,
                            chunk_tokens=16)
        eng.submit(prompt.copy(), max_new_tokens=24)
        eng.submit(prompt.copy(), max_new_tokens=24)
        for _ in range(6):
            eng.step()
        assert eng.stats()["prefix_shared_blocks"] == 0
        while not eng.idle:
            eng.step()


class TestChunkKnob:
    def test_env_override_beats_caller(self, model, monkeypatch):
        cfg, params = model
        monkeypatch.setenv("APEX_TPU_CHUNK_TOKENS", "24")
        eng = ServingEngine(params, cfg, max_slots=1, max_len=64,
                            chunk_tokens=8)
        assert eng.chunk_tokens == 24
        monkeypatch.setenv("APEX_TPU_CHUNK_TOKENS", "off")
        eng = ServingEngine(params, cfg, max_slots=1, max_len=64,
                            chunk_tokens=8)
        assert eng.chunk_tokens is None

    def test_env_malformed_warns_by_name(self, model, monkeypatch):
        cfg, params = model
        monkeypatch.setenv("APEX_TPU_CHUNK_TOKENS", "banana")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            eng = ServingEngine(params, cfg, max_slots=1, max_len=64,
                                chunk_tokens=8)
        assert eng.chunk_tokens == 8
        assert any("APEX_TPU_CHUNK_TOKENS" in str(x.message)
                   for x in w)

    def test_invalid_caller_value_raises(self, model):
        cfg, params = model
        with pytest.raises(ValueError, match="chunk_tokens"):
            ServingEngine(params, cfg, max_slots=1, max_len=64,
                          chunk_tokens=0)


class TestHeadroomTokens:
    def test_paged_headroom_in_tokens(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, max_slots=2, max_len=64,
                            cache_layout="paged", block_size=8,
                            reserve_blocks=1)
        st = eng.stats()
        assert st["headroom_tokens"] == st["free_block_headroom"] * 8

    def test_contiguous_headroom_in_tokens(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, max_slots=3, max_len=64)
        assert eng.stats()["headroom_tokens"] == 3 * 64

    def test_int8_pool_reports_more_tokens_at_matched_bytes(self,
                                                            model):
        """THE over-spawn fix: at matched pool bytes the int8 pool
        genuinely admits ``2*dh/(dh+4)``x the tokens (~1.88x at the
        serving dh=64, 1.6x at this test's dh=16) and headroom_tokens
        says so — a byte-blind signal would read the two pools as
        equal."""
        cfg, params = model
        kw = dict(max_slots=4, max_len=64, cache_layout="paged",
                  block_size=8, cache_dtype=jnp.bfloat16,
                  reserve_blocks=0)
        native = ServingEngine(params, cfg, **kw)
        quant = ServingEngine(params, cfg, cache_wire="int8", **kw)
        # byte-parity default pools (the ISSUE 14 construction)
        ratio = (quant.stats()["headroom_tokens"]
                 / native.stats()["headroom_tokens"])
        dh = cfg.kv_channels
        expected = 2 * dh / (dh + 4)
        assert ratio == pytest.approx(expected, rel=0.05)
        assert ratio > 1.5
