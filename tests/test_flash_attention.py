"""Flash attention kernel vs materialized reference.

Mirrors the reference fmha test pattern (apex/contrib/test/fmha/test_fmha.py:
fused kernel vs PyTorch-composed attention at loose fp16 tolerances).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_attention import flash_attention, mha_reference


def make_qkv(b, s, n, d, dtype=jnp.float32, seed=0, sk=None):
    rng = np.random.RandomState(seed)
    sk = s if sk is None else sk
    q = jnp.asarray(rng.randn(b, s, n, d), dtype) * 0.5
    k = jnp.asarray(rng.randn(b, sk, n, d), dtype) * 0.5
    v = jnp.asarray(rng.randn(b, sk, n, d), dtype) * 0.5
    return q, k, v


TOL = dict(atol=2e-5, rtol=2e-5)
TOL_BF16 = dict(atol=2e-2, rtol=2e-2)


class TestForward:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("shape", [(2, 128, 2, 64), (1, 384, 4, 32)])
    def test_matches_reference(self, causal, shape):
        q, k, v = make_qkv(*shape)
        got = flash_attention(q, k, v, causal=causal)
        want = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)

    def test_unaligned_seq_len(self):
        # seq 100 → padded to the 128-row block internally
        q, k, v = make_qkv(2, 100, 2, 64)
        got = flash_attention(q, k, v, causal=True)
        want = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)

    def test_cross_attention_lengths(self):
        q, k, v = make_qkv(2, 64, 2, 64, sk=192)
        got = flash_attention(q, k, v)
        want = mha_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)

    def test_key_padding_mask(self):
        b, s, n, d = 2, 128, 2, 64
        q, k, v = make_qkv(b, s, n, d)
        lengths = np.array([80, 128])
        kpm = jnp.asarray(
            np.arange(s)[None, :] >= lengths[:, None])
        got = flash_attention(q, k, v, key_padding_mask=kpm)
        want = mha_reference(q, k, v, key_padding_mask=kpm)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)

    def test_bf16(self):
        q, k, v = make_qkv(2, 128, 2, 64, dtype=jnp.bfloat16)
        got = flash_attention(q, k, v, causal=True)
        want = mha_reference(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), causal=True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want), **TOL_BF16)

    def test_generic_mask_falls_back(self):
        q, k, v = make_qkv(1, 64, 2, 32)
        mask = jnp.zeros((1, 1, 64, 64), bool).at[:, :, :, 10].set(True)
        got = flash_attention(q, k, v, mask=mask)
        want = mha_reference(q, k, v, mask=mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)


class TestBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        q, k, v = make_qkv(2, 128, 2, 64, seed=3)

        def f_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal)
            return jnp.sum(o * jnp.cos(o.astype(jnp.float32)))

        def f_ref(q, k, v):
            o = mha_reference(q, k, v, causal=causal)
            return jnp.sum(o * jnp.cos(o.astype(jnp.float32)))

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"d{name}")

    def test_grads_with_key_padding(self):
        b, s, n, d = 2, 128, 2, 32
        q, k, v = make_qkv(b, s, n, d, seed=4)
        kpm = jnp.asarray(np.arange(s)[None, :] >= np.array([96, 128])[:, None])

        g1 = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, key_padding_mask=kpm)), argnums=(0, 1, 2))(
                q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(
            mha_reference(*a, key_padding_mask=kpm)), argnums=(0, 1, 2))(
                q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"d{name}")

    def test_grads_unaligned(self):
        q, k, v = make_qkv(1, 100, 2, 64, seed=5)
        g1 = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, causal=True)), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(
            mha_reference(*a, causal=True)), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"d{name}")
