"""Flash attention kernel vs materialized reference.

Mirrors the reference fmha test pattern (apex/contrib/test/fmha/test_fmha.py:
fused kernel vs PyTorch-composed attention at loose fp16 tolerances).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_attention import flash_attention, mha_reference


def make_qkv(b, s, n, d, dtype=jnp.float32, seed=0, sk=None):
    rng = np.random.RandomState(seed)
    sk = s if sk is None else sk
    q = jnp.asarray(rng.randn(b, s, n, d), dtype) * 0.5
    k = jnp.asarray(rng.randn(b, sk, n, d), dtype) * 0.5
    v = jnp.asarray(rng.randn(b, sk, n, d), dtype) * 0.5
    return q, k, v


TOL = dict(atol=2e-5, rtol=2e-5)
TOL_BF16 = dict(atol=2e-2, rtol=2e-2)


class TestForward:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("shape", [(2, 128, 2, 64), (1, 384, 4, 32)])
    def test_matches_reference(self, causal, shape):
        q, k, v = make_qkv(*shape)
        got = flash_attention(q, k, v, causal=causal)
        want = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)

    def test_unaligned_seq_len(self):
        # seq 100 → padded to the 128-row block internally
        q, k, v = make_qkv(2, 100, 2, 64)
        got = flash_attention(q, k, v, causal=True)
        want = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)

    def test_cross_attention_lengths(self):
        q, k, v = make_qkv(2, 64, 2, 64, sk=192)
        got = flash_attention(q, k, v)
        want = mha_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)

    def test_key_padding_mask(self):
        b, s, n, d = 2, 128, 2, 64
        q, k, v = make_qkv(b, s, n, d)
        lengths = np.array([80, 128])
        kpm = jnp.asarray(
            np.arange(s)[None, :] >= lengths[:, None])
        got = flash_attention(q, k, v, key_padding_mask=kpm)
        want = mha_reference(q, k, v, key_padding_mask=kpm)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)

    def test_bf16(self):
        q, k, v = make_qkv(2, 128, 2, 64, dtype=jnp.bfloat16)
        got = flash_attention(q, k, v, causal=True)
        want = mha_reference(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), causal=True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want), **TOL_BF16)

    def test_generic_mask_falls_back(self):
        q, k, v = make_qkv(1, 64, 2, 32)
        mask = jnp.zeros((1, 1, 64, 64), bool).at[:, :, :, 10].set(True)
        got = flash_attention(q, k, v, mask=mask)
        want = mha_reference(q, k, v, mask=mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)


class TestBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        q, k, v = make_qkv(2, 128, 2, 64, seed=3)

        def f_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal)
            return jnp.sum(o * jnp.cos(o.astype(jnp.float32)))

        def f_ref(q, k, v):
            o = mha_reference(q, k, v, causal=causal)
            return jnp.sum(o * jnp.cos(o.astype(jnp.float32)))

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"d{name}")

    def test_grads_with_key_padding(self):
        b, s, n, d = 2, 128, 2, 32
        q, k, v = make_qkv(b, s, n, d, seed=4)
        kpm = jnp.asarray(np.arange(s)[None, :] >= np.array([96, 128])[:, None])

        g1 = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, key_padding_mask=kpm)), argnums=(0, 1, 2))(
                q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(
            mha_reference(*a, key_padding_mask=kpm)), argnums=(0, 1, 2))(
                q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"d{name}")

    def test_grads_unaligned(self):
        q, k, v = make_qkv(1, 100, 2, 64, seed=5)
        g1 = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, causal=True)), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(
            mha_reference(*a, causal=True)), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"d{name}")


class TestKernelDropout:
    """In-kernel attention dropout (hash-PRNG Philox analog).

    Mirrors the reference multihead_attn dropout checks: determinism per
    seed, correct keep statistics, and fwd/bwd mask consistency.
    """

    def test_dropout_deterministic_per_seed(self):
        q, k, v = make_qkv(2, 128, 2, 32, seed=10)
        rng = jax.random.PRNGKey(7)
        a = flash_attention(q, k, v, dropout_p=0.3, dropout_rng=rng)
        b = flash_attention(q, k, v, dropout_p=0.3, dropout_rng=rng)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = flash_attention(q, k, v, dropout_p=0.3,
                            dropout_rng=jax.random.PRNGKey(8))
        assert not np.allclose(np.asarray(a), np.asarray(c))

    def test_dropout_zero_equals_dense(self):
        q, k, v = make_qkv(2, 128, 2, 32, seed=11)
        base = flash_attention(q, k, v)
        out = flash_attention(q, k, v, dropout_p=0.0,
                              dropout_rng=jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(base), np.asarray(out), **TOL)

    def test_dropout_statistics_via_identity_values(self):
        """With v = I, rows of the output are the dropped attention
        probabilities: zero fraction ~ p, kept entries scaled 1/(1-p)."""
        b, s, n, d = 1, 128, 1, 128
        q, k, _ = make_qkv(b, s, n, d, seed=12)
        v = jnp.eye(d)[None, :, None, :]
        p_drop = 0.4
        out = flash_attention(q, k, v, dropout_p=p_drop,
                              dropout_rng=jax.random.PRNGKey(3))
        probs = flash_attention(q, k, v)  # dense P
        dense = np.asarray(probs, np.float64)
        dropped = np.asarray(out, np.float64)
        # kept entries = dense / (1-p): ratio is 1/(1-p) or 0
        ratio = dropped / np.maximum(dense, 1e-30)
        kept = ratio > 0.5
        np.testing.assert_allclose(
            ratio[kept], 1.0 / (1.0 - p_drop), rtol=1e-3)
        zero_frac = 1.0 - kept.mean()
        assert abs(zero_frac - p_drop) < 0.02, zero_frac

    def test_dropout_mask_consistent_fwd_bwd(self):
        """grad wrt v of sum(out) = column sums of dropped P — matches the
        forward-observed mask exactly if fwd/bwd regenerate the same
        bits."""
        b, s, n, d = 1, 128, 1, 128
        q, k, _ = make_qkv(b, s, n, d, seed=13)
        v = jnp.eye(d)[None, :, None, :]
        rng = jax.random.PRNGKey(5)
        p_drop = 0.25

        out = flash_attention(q, k, v, dropout_p=p_drop, dropout_rng=rng)
        P_dropped = np.asarray(out)[0, :, 0, :]  # [sq, sk]

        dv = jax.grad(lambda vv: jnp.sum(flash_attention(
            q, k, vv, dropout_p=p_drop, dropout_rng=rng)))(v)
        # dL/dv[t, e] = sum_q P_dropped[q, t] (same for every column e)
        col_sums = P_dropped.sum(axis=0)
        got = np.asarray(dv)[0, :, 0, :].mean(axis=-1)
        np.testing.assert_allclose(got, col_sums, atol=1e-5, rtol=1e-4)

    def test_dropout_grad_finite_differences(self):
        """Analytic grads match finite differences through the kernel
        (the dropout mask is deterministic given the seed)."""
        b, s, n, d = 1, 8, 1, 8
        q, k, v = make_qkv(b, s, n, d, seed=14)
        rng = jax.random.PRNGKey(9)

        def f(q_):
            return jnp.sum(jnp.sin(flash_attention(
                q_, k, v, dropout_p=0.3, dropout_rng=rng)))

        g = np.asarray(jax.grad(f)(q))
        eps = 1e-3
        rs = np.random.RandomState(0)
        for _ in range(5):
            i = tuple(rs.randint(x) for x in q.shape)
            dq = np.zeros(q.shape, np.float32)
            dq[i] = eps
            fd = (float(f(q + dq)) - float(f(q - dq))) / (2 * eps)
            np.testing.assert_allclose(fd, g[i], atol=5e-3, rtol=5e-2)

    def test_dropout_with_causal_and_padding(self):
        q, k, v = make_qkv(2, 96, 2, 32, seed=15)
        kpm = jnp.asarray(
            np.arange(96)[None, :] >= np.array([64, 96])[:, None])
        rng = jax.random.PRNGKey(11)
        out = flash_attention(q, k, v, causal=True, key_padding_mask=kpm,
                              dropout_p=0.2, dropout_rng=rng)
        assert np.all(np.isfinite(np.asarray(out)))
        grads = jax.grad(lambda *a: jnp.sum(flash_attention(
            *a, causal=True, key_padding_mask=kpm, dropout_p=0.2,
            dropout_rng=rng)), argnums=(0, 1, 2))(q, k, v)
        for g in grads:
            assert np.all(np.isfinite(np.asarray(g)))

    def test_additive_key_padding_mask(self):
        """Float (additive) key_padding_mask — the reference MHA
        mask_additive mode — fused in-kernel."""
        b, s, n, d = 2, 128, 2, 32
        q, k, v = make_qkv(b, s, n, d, seed=16)
        add = np.zeros((b, s), np.float32)
        add[0, 100:] = -1e30
        add[1, 64:] = -1e30
        out_add = flash_attention(q, k, v,
                                  key_padding_mask=jnp.asarray(add))
        kpm = jnp.asarray(add < 0)
        out_bool = flash_attention(q, k, v, key_padding_mask=kpm)
        np.testing.assert_allclose(
            np.asarray(out_add), np.asarray(out_bool), **TOL)

    def test_fully_masked_sequence_zero_grads(self):
        """Regression: a fully padded sequence (all keys masked) must get
        exact-zero dk/dv and zero dq — the additive-mask bwd kernels must
        honor the lse sentinel, not recompute p = exp(0) = 1."""
        b, s, n, d = 2, 64, 2, 32
        q, k, v = make_qkv(b, s, n, d, seed=17)
        kpm = jnp.asarray(
            np.stack([np.ones(s, bool), np.zeros(s, bool)]))  # row0 all pad
        dq, dk, dv = jax.grad(lambda *a: jnp.sum(flash_attention(
            *a, key_padding_mask=kpm)), argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_array_equal(np.asarray(dq)[0], 0.0)
        np.testing.assert_array_equal(np.asarray(dk)[0], 0.0)
        np.testing.assert_array_equal(np.asarray(dv)[0], 0.0)
        # the unmasked sequence still gets real gradients
        assert np.abs(np.asarray(dv)[1]).sum() > 0


class TestPackedSegments:
    """Packed multi-sequence (cu_seqlens / segment-id) attention — the
    reference fmha varlen mode (fmha_api.cpp:358, fmha.py:33-60)."""

    def _packed_case(self, lengths, n=2, d=32, seed=20, total=None):
        total = total if total is not None else sum(lengths)
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(total, n, d), jnp.float32) * 0.5
        k = jnp.asarray(rng.randn(total, n, d), jnp.float32) * 0.5
        v = jnp.asarray(rng.randn(total, n, d), jnp.float32) * 0.5
        cu = jnp.asarray(np.cumsum([0] + list(lengths)), jnp.int32)
        return q, k, v, cu

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_per_sequence(self, causal):
        from apex_tpu.ops.flash_attention import flash_attention_packed

        lengths = [60, 100, 96]
        q, k, v, cu = self._packed_case(lengths)
        out = flash_attention_packed(q, k, v, cu, causal=causal)
        # oracle: run each sequence separately through the dense ref
        start = 0
        for L in lengths:
            want = mha_reference(
                q[None, start:start + L], k[None, start:start + L],
                v[None, start:start + L], causal=causal)[0]
            np.testing.assert_allclose(
                np.asarray(out[start:start + L]), np.asarray(want),
                atol=3e-5, rtol=3e-5)
            start += L

    def test_padding_tail_isolated(self):
        from apex_tpu.ops.flash_attention import flash_attention_packed

        lengths = [50, 70]
        q, k, v, cu = self._packed_case(lengths, total=160)  # 40 pad slots
        out = flash_attention_packed(q, k, v, cu, causal=False)
        want = flash_attention_packed(
            q[:120], k[:120], v[:120], cu, causal=False)
        # valid positions are unaffected by whatever sits in the padding
        np.testing.assert_allclose(np.asarray(out[:120]),
                                   np.asarray(want), atol=3e-5, rtol=3e-5)

    def test_grads_match_per_sequence(self):
        from apex_tpu.ops.flash_attention import flash_attention_packed

        lengths = [40, 88]
        q, k, v, cu = self._packed_case(lengths)

        def packed_loss(q, k, v):
            o = flash_attention_packed(q, k, v, cu, causal=True)
            return jnp.sum(o * o)

        gq, gk, gv = jax.grad(packed_loss, argnums=(0, 1, 2))(q, k, v)

        start = 0
        for L in lengths:
            sl = slice(start, start + L)

            def seq_loss(qs, ks, vs):
                o = mha_reference(qs[None], ks[None], vs[None],
                                  causal=True)[0]
                return jnp.sum(o * o)

            rq, rk, rv = jax.grad(seq_loss, argnums=(0, 1, 2))(
                q[sl], k[sl], v[sl])
            np.testing.assert_allclose(np.asarray(gq[sl]), np.asarray(rq),
                                       atol=5e-5, rtol=5e-5)
            np.testing.assert_allclose(np.asarray(gk[sl]), np.asarray(rk),
                                       atol=5e-5, rtol=5e-5)
            np.testing.assert_allclose(np.asarray(gv[sl]), np.asarray(rv),
                                       atol=5e-5, rtol=5e-5)
            start += L

    def test_segment_ids_batched(self):
        """[b, s] segment ids on the 4-D API: two packed rows."""
        b, s, n, d = 2, 128, 2, 32
        q, k, v = make_qkv(b, s, n, d, seed=21)
        seg = np.zeros((b, s), np.int32)
        seg[0, 64:] = 1
        seg[1, 40:] = 1
        got = flash_attention(q, k, v, causal=True,
                              segment_ids=jnp.asarray(seg))
        want = mha_reference(q, k, v, causal=True,
                             segment_ids=jnp.asarray(seg))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   **TOL)

    def test_cu_seqlens_helper(self):
        from apex_tpu.ops.flash_attention import segment_ids_from_cu_seqlens

        cu = jnp.asarray([0, 3, 3, 7], jnp.int32)   # empty segment 1
        seg = segment_ids_from_cu_seqlens(cu, 9)
        np.testing.assert_array_equal(
            np.asarray(seg), [0, 0, 0, 2, 2, 2, 2, -1, -1])


class TestDropoutGradCorrectness:
    def test_dropout_grads_match_reference_with_same_mask(self):
        """Advisor round-2 finding: verify the dropout-path *gradients*
        against autodiff through a dense composition that applies the
        identical keep mask (reconstructed from the kernel's counter-based
        hash), catching any fwd/bwd scaling or coordinate mismatch."""
        from apex_tpu.ops.flash_attention import (
            _keep_mask, _seed_from_rng)

        b, s, n, d = 1, 128, 2, 32
        p_drop = 0.3
        q, k, v = make_qkv(b, s, n, d, seed=22)
        rng = jax.random.PRNGKey(5)
        seed = _seed_from_rng(rng)

        def fused_loss(q, k, v):
            o = flash_attention(q, k, v, dropout_p=p_drop, dropout_rng=rng)
            return jnp.sum(o * o)

        # dense composition with the SAME keep bits per (bh, row, col)
        def dense_loss(q, k, v):
            scale = 1.0 / d ** 0.5
            s_ = jnp.einsum("bsnd,btnd->bnst", q, k,
                            preferred_element_type=jnp.float32) * scale
            p = jax.nn.softmax(s_, axis=-1)
            keeps = jnp.stack([
                _keep_mask(seed, jnp.int32(bh), 0, 0, (s, s), 1 - p_drop)
                for bh in range(b * n)]).reshape(b, n, s, s)
            p = jnp.where(keeps, p / (1 - p_drop), 0.0)
            o = jnp.einsum("bnst,btnd->bsnd", p.astype(v.dtype), v)
            return jnp.sum(o * o)

        gf = jax.grad(fused_loss, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for name, a, bb in zip("qkv", gf, gd):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(bb), atol=1e-4, rtol=1e-4,
                err_msg=f"d{name} mismatch under dropout")


class TestTHDIntegration:
    def test_thd_rope_feeds_packed_attention(self):
        """The THD RoPE layout (ops/rope.py) and the packed varlen kernel
        share the cu_seqlens descriptor — apply rotary embeddings per
        sequence then attend per segment, matching the per-sequence
        composition exactly (reference fmha varlen + fused_rope thd)."""
        from apex_tpu.ops.flash_attention import flash_attention_packed
        from apex_tpu.ops.rope import (fused_apply_rotary_pos_emb,
                                       fused_apply_rotary_pos_emb_thd)

        n, d = 2, 32
        lengths = [48, 80]
        total = sum(lengths)
        rng = np.random.RandomState(30)
        t = jnp.asarray(rng.randn(total, n, d), jnp.float32) * 0.5
        v = jnp.asarray(rng.randn(total, n, d), jnp.float32) * 0.5
        cu = jnp.asarray(np.cumsum([0] + lengths), jnp.int32)
        freqs_full = jnp.asarray(
            rng.randn(max(lengths), 1, 1, d) * 0.1, jnp.float32)

        q_thd = fused_apply_rotary_pos_emb_thd(t, cu, freqs_full)
        out = flash_attention_packed(q_thd, q_thd, v, cu, causal=True)

        start = 0
        for L in lengths:
            sl = slice(start, start + L)
            # per-sequence: sbhd rope (restarts positions) + dense attn
            q_seq = fused_apply_rotary_pos_emb(
                t[sl][:, None], freqs_full[:L])[:, 0]
            want = mha_reference(q_seq[None], q_seq[None], v[sl][None],
                                 causal=True)[0]
            np.testing.assert_allclose(
                np.asarray(out[sl]), np.asarray(want),
                atol=5e-5, rtol=5e-5)
            start += L


class TestGroupedKV:
    """GQA/MQA-aware kernels: grouped K/V ([b, s, g, d] with g < n) feed
    the kernels directly — index maps broadcast each group head to its
    rep query heads, and the dkv grid accumulates a whole group per
    dk/dv row, so the repeated [b, s, n, d] tensor (and the autodiff
    sum of its repeat) never exists in HBM."""

    def _grouped(self, b=2, s=128, n=8, g=2, d=32, seed=21, dtype=None):
        rng = np.random.RandomState(seed)
        dt = dtype or jnp.float32
        q = jnp.asarray(rng.randn(b, s, n, d), dt) * 0.5
        k = jnp.asarray(rng.randn(b, s, g, d), dt) * 0.5
        v = jnp.asarray(rng.randn(b, s, g, d), dt) * 0.5
        return q, k, v

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_repeated(self, causal):
        """Grouped input must equal the kernel run on explicitly
        repeated K/V — same math, different HBM footprint."""
        q, k, v = self._grouped()
        rep = q.shape[2] // k.shape[2]
        got = flash_attention(q, k, v, causal=causal)
        want = flash_attention(q, jnp.repeat(k, rep, axis=2),
                               jnp.repeat(v, rep, axis=2), causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6, rtol=1e-6)

    @pytest.mark.parametrize("g", [1, 4])   # MQA and GQA widths
    def test_grads_match_reference(self, g):
        """dq/dk/dv of the grouped kernel vs autodiff of the reference
        composition (repeat inside, so dk/dv come back grouped)."""
        q, k, v = self._grouped(g=g, seed=22)

        def f_kernel(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True))

        def f_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=True))

        g1 = jax.grad(f_kernel, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_, name in zip(g1, g2, "qkv"):
            assert a.shape == b_.shape, name
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=2e-4, rtol=2e-4,
                err_msg=f"grouped d{name}")

    def test_key_padding_and_dropout_parity(self):
        """kpm is batch-indexed and the dropout hash keys off the query
        head — both must be invariant to grouped-vs-repeated K/V."""
        q, k, v = self._grouped(seed=23)
        rep = q.shape[2] // k.shape[2]
        kpm = jnp.asarray(
            np.arange(128)[None, :] >= np.array([96, 128])[:, None])
        rng = jax.random.PRNGKey(7)
        got = flash_attention(q, k, v, causal=True, key_padding_mask=kpm,
                              dropout_p=0.3, dropout_rng=rng)
        want = flash_attention(
            q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2),
            causal=True, key_padding_mask=kpm, dropout_p=0.3,
            dropout_rng=rng)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6, rtol=1e-6)

    def test_segment_ids_grads(self):
        """Packed rows with grouped K/V: block-sparse skip + the grouped
        dkv accumulation must agree with the reference."""
        q, k, v = self._grouped(seed=24)
        seg = jnp.asarray(
            np.repeat(np.arange(4), 32)[None].repeat(2, 0), jnp.int32)
        g1 = jax.grad(lambda *a: jnp.sum(flash_attention(
            *a, causal=True, segment_ids=seg)),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(mha_reference(
            *a, causal=True, segment_ids=seg)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b_, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=2e-4, rtol=2e-4,
                err_msg=f"grouped+seg d{name}")

    def test_fused_backward_matches_split(self, monkeypatch):
        """The fused single-pass backward supports grouping too: its
        dk/dv output block stays resident across a group's consecutive
        q-head grid rows.  Must agree with the split pair exactly."""
        q, k, v = self._grouped(seed=25)

        def grads():
            return jax.grad(lambda *a: jnp.sum(flash_attention(
                *a, causal=True)), argnums=(0, 1, 2))(q, k, v)

        monkeypatch.setenv("APEX_TPU_FLASH_BWD", "fused")
        g_fused = grads()
        monkeypatch.setenv("APEX_TPU_FLASH_BWD", "split")
        g_split = grads()
        assert g_fused[1].shape == k.shape   # grouped dk
        for a, b_, name in zip(g_fused, g_split, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=1e-5, rtol=1e-5,
                err_msg=f"grouped fused d{name}")

    def test_fused_backward_mqa_with_dropout(self, monkeypatch):
        """MQA extreme through the fused kernel with dropout: the
        reconstructed per-q-head dropout stream must match split."""
        q, k, v = self._grouped(g=1, seed=26)
        rng = jax.random.PRNGKey(11)

        def grads():
            return jax.grad(lambda *a: jnp.sum(flash_attention(
                *a, causal=True, dropout_p=0.25, dropout_rng=rng)),
                argnums=(0, 1, 2))(q, k, v)

        monkeypatch.setenv("APEX_TPU_FLASH_BWD", "fused")
        g_fused = grads()
        monkeypatch.setenv("APEX_TPU_FLASH_BWD", "split")
        g_split = grads()
        for a, b_, name in zip(g_fused, g_split, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=1e-5, rtol=1e-5,
                err_msg=f"mqa fused+dropout d{name}")

    def test_invalid_group_ratio_rejected(self):
        q, k, v = self._grouped(n=8, g=3)
        with pytest.raises(ValueError, match="multiple"):
            flash_attention(q, k, v)
        q2, k2, v2 = self._grouped(n=8, g=2)
        with pytest.raises(ValueError, match="head counts differ"):
            flash_attention(q2, k2, v2[:, :, :1])


class TestBackwardModeRouting:
    """auto routes short keys (sk <= APEX_TPU_FLASH_BWD_FUSED_MAX,
    default 512 — the round-5 measured crossover) to the fused
    single-pass backward and longer keys to the split dq/dkv pair, so
    both kernels get implicit coverage from the other grad tests; the
    explicit env-forced cases here pin each kernel regardless of where
    the crossover sits."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_split_backward_matches_reference(self, monkeypatch, causal):
        monkeypatch.setenv("APEX_TPU_FLASH_BWD", "split")
        q, k, v = make_qkv(2, 128, 2, 64, seed=11)
        kpm = jnp.asarray(
            np.arange(128)[None, :] >= np.array([96, 128])[:, None])
        g1 = jax.grad(lambda *a: jnp.sum(flash_attention(
            *a, causal=causal, key_padding_mask=kpm)),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(mha_reference(
            *a, causal=causal, key_padding_mask=kpm)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
                err_msg=f"split d{name}")

    def test_fused_backward_rejects_non_divisor_bq(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_FLASH_BWD", "fused")
        monkeypatch.setenv("APEX_TPU_FLASH_FUSED_BQ", "96")
        q, k, v = make_qkv(1, 256, 2, 32, seed=12)
        with pytest.raises(ValueError, match="must divide"):
            jax.grad(lambda *a: jnp.sum(
                flash_attention(*a, causal=True)))(q, k, v)

    def test_fused_segment_ids_match_split(self, monkeypatch):
        seg = jnp.asarray(
            np.repeat(np.arange(4), 32)[None].repeat(2, 0), jnp.int32)
        q, k, v = make_qkv(2, 128, 2, 32, seed=13)

        def grads():
            return jax.grad(lambda *a: jnp.sum(flash_attention(
                *a, causal=True, segment_ids=seg)),
                argnums=(0, 1, 2))(q, k, v)

        monkeypatch.setenv("APEX_TPU_FLASH_BWD", "fused")
        g_fused = grads()
        monkeypatch.setenv("APEX_TPU_FLASH_BWD", "split")
        g_split = grads()
        for a, b, name in zip(g_fused, g_split, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5,
                err_msg=f"d{name}")
