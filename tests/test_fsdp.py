"""FSDP (ZeRO-3-style full parameter sharding) under GSPMD."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.amp.frontend import make_train_step
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel.fsdp import fsdp_shardings, fsdp_spec
from apex_tpu.parallel.mesh import create_mesh, shard_batch


class TestFsdpSpec:
    def test_largest_divisible_dim(self):
        assert fsdp_spec((16, 64), 8) == P(None, "dp")
        assert fsdp_spec((64, 16), 8) == P("dp", None)
        assert fsdp_spec((6,), 8) == P()          # not divisible
        assert fsdp_spec((8,), 8) == P("dp")


class TestFsdpTraining:
    def test_matches_replicated_training(self):
        mesh = create_mesh()    # dp=8
        rs = np.random.RandomState(0)
        params = {
            "w1": jnp.asarray(rs.randn(16, 64) * 0.1, jnp.float32),
            "b1": jnp.zeros((64,), jnp.float32),
            "w2": jnp.asarray(rs.randn(64, 8) * 0.1, jnp.float32),
        }
        x = jnp.asarray(rs.randn(16, 16), jnp.float32)
        y = jnp.asarray(rs.randn(16, 8), jnp.float32)

        def loss_fn(p, x, y):
            h = jnp.tanh(x @ p["w1"].astype(x.dtype)
                         + p["b1"].astype(x.dtype))
            return jnp.mean((h @ p["w2"].astype(x.dtype) - y) ** 2)

        init, step = make_train_step(loss_fn, fused_adam(lr=1e-2), "O2")

        # replicated oracle
        s_ref = init(params)
        jstep = jax.jit(step)
        for _ in range(4):
            s_ref, m_ref = jstep(s_ref, x, y)

        # fully-sharded: params + masters + opt state over dp
        s_fsdp = init(params)
        s_fsdp = jax.device_put(s_fsdp, fsdp_shardings(s_fsdp, mesh))
        xb = jax.device_put(x, shard_batch(mesh))
        yb = jax.device_put(y, shard_batch(mesh))
        fstep = jax.jit(step)
        with jax.set_mesh(mesh):
            for _ in range(4):
                s_fsdp, m = fstep(s_fsdp, xb, yb)

        for k in params:
            np.testing.assert_allclose(
                np.asarray(s_fsdp.master_params[k]),
                np.asarray(s_ref.master_params[k]),
                atol=1e-5, rtol=1e-5, err_msg=k)
        # the master params really are sharded (1/8 per device)
        shard = s_fsdp.master_params["w1"].sharding
        assert "dp" in str(shard.spec)

    def test_memory_layout_is_sharded(self):
        mesh = create_mesh()
        params = {"w": jnp.zeros((32, 64), jnp.float32)}
        sh = fsdp_shardings(params, mesh)["w"]
        assert sh.spec == P(None, "dp")
