"""FSDP (ZeRO-3-style full parameter sharding) under GSPMD."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.amp.frontend import make_train_step
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel.fsdp import fsdp_shardings, fsdp_spec
from apex_tpu.parallel.mesh import create_mesh, shard_batch


class TestFsdpSpec:
    def test_largest_divisible_dim(self):
        assert fsdp_spec((16, 64), 8) == P(None, "dp")
        assert fsdp_spec((64, 16), 8) == P("dp", None)
        assert fsdp_spec((6,), 8) == P()          # not divisible
        assert fsdp_spec((8,), 8) == P("dp")


class TestFsdpTraining:
    def test_matches_replicated_training(self):
        mesh = create_mesh()    # dp=8
        rs = np.random.RandomState(0)
        params = {
            "w1": jnp.asarray(rs.randn(16, 64) * 0.1, jnp.float32),
            "b1": jnp.zeros((64,), jnp.float32),
            "w2": jnp.asarray(rs.randn(64, 8) * 0.1, jnp.float32),
        }
        x = jnp.asarray(rs.randn(16, 16), jnp.float32)
        y = jnp.asarray(rs.randn(16, 8), jnp.float32)

        def loss_fn(p, x, y):
            h = jnp.tanh(x @ p["w1"].astype(x.dtype)
                         + p["b1"].astype(x.dtype))
            return jnp.mean((h @ p["w2"].astype(x.dtype) - y) ** 2)

        init, step = make_train_step(loss_fn, fused_adam(lr=1e-2), "O2")

        # replicated oracle
        s_ref = init(params)
        jstep = jax.jit(step)
        for _ in range(4):
            s_ref, m_ref = jstep(s_ref, x, y)

        # fully-sharded: params + masters + opt state over dp
        s_fsdp = init(params)
        s_fsdp = jax.device_put(s_fsdp, fsdp_shardings(s_fsdp, mesh))
        xb = jax.device_put(x, shard_batch(mesh))
        yb = jax.device_put(y, shard_batch(mesh))
        fstep = jax.jit(step)
        with jax.set_mesh(mesh):
            for _ in range(4):
                s_fsdp, m = fstep(s_fsdp, xb, yb)

        for k in params:
            np.testing.assert_allclose(
                np.asarray(s_fsdp.master_params[k]),
                np.asarray(s_ref.master_params[k]),
                atol=1e-5, rtol=1e-5, err_msg=k)
        # the master params really are sharded (1/8 per device)
        shard = s_fsdp.master_params["w1"].sharding
        assert "dp" in str(shard.spec)

    def test_memory_layout_is_sharded(self):
        mesh = create_mesh()
        params = {"w": jnp.zeros((32, 64), jnp.float32)}
        sh = fsdp_shardings(params, mesh)["w"]
        assert sh.spec == P(None, "dp")


class TestFsdpGpt:
    """make_gpt_train_step(..., fsdp=True): the ZeRO-3 path on the real
    GPT family (not a toy MLP), with per-device memory evidence."""

    def _cfg(self, **kw):
        from apex_tpu.models.config import TransformerConfig

        kw.setdefault("num_layers", 2)
        kw.setdefault("hidden_size", 128)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_position_embeddings", 32)
        kw.setdefault("compute_dtype", jnp.bfloat16)
        return TransformerConfig(**kw)

    @pytest.mark.slow   # dryrun fsdp phase covers sharded AMP step
    def test_gpt_fsdp_trains_and_shards(self):
        from apex_tpu.models.gpt import make_gpt_train_step

        mesh = create_mesh()    # dp=8
        cfg = self._cfg()
        rs = np.random.RandomState(0)
        tokens = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 32)),
                             jnp.int32)
        labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 32)),
                             jnp.int32)

        init, step = make_gpt_train_step(
            cfg, fused_adam(lr=1e-3), "O2", mesh, fsdp=True)
        state = init(jax.random.PRNGKey(0))

        # ZeRO-3 evidence: per-device bytes of masters + opt state is a
        # fraction of the replicated total (all big leaves split 8-way).
        def bytes_of(tree):
            total = local = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                if not hasattr(leaf, "addressable_shards"):
                    continue
                total += leaf.size * leaf.dtype.itemsize
                sh = leaf.addressable_shards[0].data
                local += sh.size * sh.dtype.itemsize
            return total, local

        t_master, l_master = bytes_of(state.master_params)
        t_opt, l_opt = bytes_of(state.opt_state)
        assert l_master * 4 <= t_master, (l_master, t_master)
        assert l_opt * 4 <= t_opt, (l_opt, t_opt)

        losses = []
        for i in range(3):
            state, m = step(state, tokens, labels)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

        # post-step state keeps the sharded layout (the optimizer update
        # must not silently gather everything back)
        t2, l2 = bytes_of(state.master_params)
        assert l2 * 4 <= t2, (l2, t2)

    # stays default: asserts POST-update-step loss parity (2 steps),
    # which the dryrun fsdp phase deliberately does not cover
    def test_gpt_fsdp_matches_replicated(self):
        from apex_tpu.models.gpt import make_gpt_train_step

        mesh = create_mesh()
        cfg = self._cfg(compute_dtype=jnp.float32)
        rs = np.random.RandomState(1)
        tokens = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 32)),
                             jnp.int32)
        labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 32)),
                             jnp.int32)

        init_f, step_f = make_gpt_train_step(
            cfg, fused_adam(lr=1e-3), "O2", mesh, fsdp=True)
        init_r, step_r = make_gpt_train_step(
            cfg, fused_adam(lr=1e-3), "O2")
        sf = init_f(jax.random.PRNGKey(0))
        sr = init_r(jax.random.PRNGKey(0))
        for _ in range(2):
            sf, mf = step_f(sf, tokens, labels)
            sr, mr = step_r(sr, tokens, labels)
        np.testing.assert_allclose(float(mf["loss"]), float(mr["loss"]),
                                   rtol=1e-4)


class TestFsdpCheckpoint:
    """Sharded (ZeRO-3) train state must round-trip through the orbax
    checkpoint helpers with its dp-sharded layout intact (the reference's
    distributed save/load contract: master weights identical across
    ranks after restore, run_rocm_distributed.sh:10-14 analog)."""

    def test_sharded_state_roundtrip(self, tmp_path):
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.gpt import make_gpt_train_step
        from apex_tpu.utils.checkpoint import (restore_checkpoint,
                                               save_checkpoint)

        mesh = create_mesh()
        cfg = TransformerConfig(
            num_layers=2, hidden_size=128, num_attention_heads=4,
            vocab_size=256, max_position_embeddings=32,
            compute_dtype=jnp.bfloat16)
        init, step = make_gpt_train_step(
            cfg, fused_adam(lr=1e-3), "O2", mesh, fsdp=True)
        state = init(jax.random.PRNGKey(0))
        rs = np.random.RandomState(0)
        tokens = jnp.asarray(rs.randint(0, 256, (8, 32)), jnp.int32)
        labels = jnp.asarray(rs.randint(0, 256, (8, 32)), jnp.int32)
        state, _ = step(state, tokens, labels)

        save_checkpoint(str(tmp_path), 1, state)
        fresh = init(jax.random.PRNGKey(1))     # different values
        restored = restore_checkpoint(str(tmp_path), fresh)

        # values equal AND the dp-sharded placement survived (specs can
        # differ in how they spell size-1 axes; per-device shard shape
        # is the invariant that matters)
        for a, b in zip(jax.tree_util.tree_leaves(state.master_params),
                        jax.tree_util.tree_leaves(
                            restored.master_params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            assert (a.addressable_shards[0].data.shape
                    == b.addressable_shards[0].data.shape), (
                a.sharding, b.sharding)

        # and training continues from the restored state
        restored, m = step(restored, tokens, labels)
        assert np.isfinite(float(m["loss"]))
