"""tools/telemetry_report.py over a fixture stream (ISSUE 1 satellite).

The report tool is the downstream consumer the JSONL schema_version
field exists for, so this tier-1 test pins: exact p50/p95 over a known
span distribution, cumulative-counter semantics (last flush value per
file), garbage-line tolerance, and the newer-schema warning.
"""

import importlib.util
import io
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "data", "telemetry_fixture.jsonl")


@pytest.fixture(scope="module")
def report():
    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(REPO, "tools",
                                         "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_summarize_fixture(report):
    out = io.StringIO()
    records = report.load_records([FIXTURE], out=out)
    assert "unparseable line skipped" in out.getvalue()
    summ = report.summarize(records)
    assert summ["spans"]["step.bench"] == [0.1, 0.2, 0.3, 0.4, 0.5]
    # last cumulative flush wins, not the sum of flush records
    assert summ["counters"]["collectives.psum.calls"] == 5
    assert summ["gauges"]["amp.loss_scale"] == [65536.0, 32768.0]
    assert summ["events"]["amp.loss_scale_change"] == 1
    assert summ["unknown_schema"] == [99]


def test_print_report_table(report):
    out = io.StringIO()
    summ = report.summarize(report.load_records([FIXTURE], out=out))
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "step.bench" in text
    # p50 of [.1 .2 .3 .4 .5] is .3, p95 is .5 (nearest-rank)
    line = next(ln for ln in text.splitlines() if "step.bench" in ln)
    assert "0.3" in line and "0.5" in line
    assert "amp.loss_scale" in text
    assert "newer schema_version" in text and "99" in text


def test_multi_file_counter_aggregation(report, tmp_path):
    """Two ranks' files each contribute their own last cumulative
    total; the report sums across files."""
    a = tmp_path / "rank0.jsonl"
    b = tmp_path / "rank1.jsonl"
    a.write_text('{"schema_version":1,"t":1,"type":"counter",'
                 '"name":"c","value":3}\n')
    b.write_text('{"schema_version":1,"t":1,"type":"counter",'
                 '"name":"c","value":4}\n')
    summ = report.summarize(report.load_records([str(a), str(b)]))
    assert summ["counters"]["c"] == 7


def test_appended_runs_in_one_file_sum_counters(report, tmp_path):
    """The JSONL sink appends: two runs into one path each open with a
    meta record and restart counters at zero — the report must sum the
    per-run totals, not keep only the last run's."""
    f = tmp_path / "appended.jsonl"
    f.write_text(
        '{"schema_version":1,"t":1,"type":"meta","tags":{},"pid":1}\n'
        '{"schema_version":1,"t":2,"type":"counter","name":"c","value":3}\n'
        '{"schema_version":1,"t":3,"type":"meta","tags":{},"pid":2}\n'
        '{"schema_version":1,"t":4,"type":"counter","name":"c","value":2}\n'
        '{"schema_version":1,"t":5,"type":"counter","name":"c","value":4}\n')
    summ = report.summarize(report.load_records([str(f)]))
    # run 1 total 3 + run 2 last flush 4 (intermediate 2 superseded)
    assert summ["counters"]["c"] == 7


def test_main_exit_code(report, capsys):
    assert report.main([FIXTURE]) == 0
    assert "step.bench" in capsys.readouterr().out


def test_missing_schema_version_warns_once_best_effort(report, tmp_path):
    """ISSUE 4 satellite: a record with NO schema_version (hand-edited
    stream, pre-ISSUE-1 writer) is still summarized; one warning names
    the condition instead of silently dropping or crashing."""
    f = tmp_path / "old.jsonl"
    f.write_text(
        '{"t":1,"type":"gauge","name":"legacy.gauge","value":2.0}\n'
        '{"t":2,"type":"gauge","name":"legacy.gauge","value":4.0}\n'
        '{"schema_version":2,"t":3,"type":"gauge","name":"new.gauge",'
        '"value":1.0}\n')
    summ = report.summarize(report.load_records([str(f)]))
    assert summ["gauges"]["legacy.gauge"] == [2.0, 4.0]   # best-effort
    assert summ["missing_schema"] == 2
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert text.count("missing schema_version") == 1       # warn once
    assert "legacy.gauge" in text


def test_since_step_filters_stamped_records(report, tmp_path):
    """--since-step keeps step >= N records; unstamped records (meta,
    trace-time counters) pass through so run identity survives."""
    f = tmp_path / "steps.jsonl"
    f.write_text(
        '{"schema_version":2,"t":1,"type":"meta","tags":{},"pid":1}\n'
        '{"schema_version":2,"t":2,"step":5,"type":"gauge",'
        '"name":"train.loss","value":1.0}\n'
        '{"schema_version":2,"t":3,"step":9,"type":"gauge",'
        '"name":"train.loss","value":2.0}\n'
        '{"schema_version":2,"t":4,"step":10,"type":"gauge",'
        '"name":"train.loss","value":3.0}\n'
        '{"schema_version":2,"t":5,"type":"counter",'
        '"name":"collectives.psum.calls","value":7}\n')
    records = report.load_records([str(f)])
    kept = report.filter_since_step(records, 10)
    summ = report.summarize(kept)
    assert summ["gauges"]["train.loss"] == [3.0]
    assert summ["counters"]["collectives.psum.calls"] == 7  # unstamped
    # no filter = identity
    assert report.filter_since_step(records, None) is records


def test_ring_summary_derives_tp(report, tmp_path):
    """ISSUE 5 satellite: collectives.ring.* get a derived view — the
    per-call hop count implies the ring (tp) size, since every ring
    loop books exactly tp−1 hops."""
    f = tmp_path / "ring.jsonl"
    f.write_text(
        '{"schema_version":2,"t":1,"type":"counter",'
        '"name":"collectives.ring.calls","value":6}\n'
        '{"schema_version":2,"t":2,"type":"counter",'
        '"name":"collectives.ring.hops","value":42}\n'
        '{"schema_version":2,"t":3,"type":"counter",'
        '"name":"collectives.ring.bytes","value":4096}\n')
    summ = report.summarize(report.load_records([str(f)]))
    ring = report.ring_summary(summ["counters"])
    assert ring["calls"] == 6 and ring["hops"] == 42
    assert ring["hops_per_call"] == 7 and ring["tp"] == 8
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "ring collectives" in text
    assert "ring size (tp) 8" in text
    # no ring calls -> no derived section, not a crash
    assert report.ring_summary({"collectives.psum.calls": 3}) is None
    # mixed-tp streams: non-integral hops/call is flagged, not rounded
    mixed = report.ring_summary({"collectives.ring.calls": 2.0,
                                 "collectives.ring.hops": 3.0})
    assert mixed["tp"] is None


def test_serving_summary_fixture(report, tmp_path):
    """ISSUE 6 satellite: the paged serving gauges/counters get a
    derived view — block-pool high-water, preemption rate per admitted
    request, and the prefix-share ratio at the pool high-water."""
    f = tmp_path / "serving.jsonl"
    f.write_text(
        '{"schema_version":2,"t":1,"type":"gauge",'
        '"name":"serving.blocks_in_use","value":3}\n'
        '{"schema_version":2,"t":2,"type":"gauge",'
        '"name":"serving.blocks_in_use","value":10}\n'
        '{"schema_version":2,"t":3,"type":"gauge",'
        '"name":"serving.blocks_in_use","value":0}\n'
        '{"schema_version":2,"t":4,"type":"gauge",'
        '"name":"serving.prefix_shared_blocks","value":4}\n'
        '{"schema_version":2,"t":5,"type":"counter",'
        '"name":"serving.requests","value":8}\n'
        '{"schema_version":2,"t":6,"type":"counter",'
        '"name":"serving.preemptions","value":2}\n')
    summ = report.summarize(report.load_records([str(f)]))
    serving = report.serving_summary(summ)
    assert serving["blocks_high_water"] == 10
    assert serving["blocks_last"] == 0            # drained, no leak
    assert serving["preemption_rate"] == 0.25
    assert serving["prefix_shared_high_water"] == 4
    # unequal series lengths (truncated stream): upper-bound fallback
    assert serving["prefix_share_ratio"] == 0.4
    # the engine emits both gauges in lockstep — equal-length series
    # pair record-for-record, and the ratio is the shared count AT the
    # high-water instant, not the stream max (which can postdate it)
    paired = report.serving_summary({
        "gauges": {"serving.blocks_in_use": [3.0, 10.0, 5.0],
                   "serving.prefix_shared_blocks": [0.0, 2.0, 4.0]},
        "counters": {"serving.requests": 8.0}})
    assert paired["prefix_share_ratio"] == 0.2    # 2/10, not 4/10
    assert paired["prefix_shared_high_water"] == 4
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "paged serving" in text
    assert "block-pool high-water 10" in text
    assert "rate 0.25" in text
    assert "share ratio 0.4" in text
    # a contiguous-engine stream (no block gauges) -> no section
    assert report.serving_summary(
        {"gauges": {"serving.queue_depth": [1.0]},
         "counters": {"serving.requests": 3.0}}) is None


def test_since_step_cli_flag(report, tmp_path, capsys):
    f = tmp_path / "steps.jsonl"
    f.write_text(
        '{"schema_version":2,"t":2,"step":1,"type":"gauge",'
        '"name":"train.loss","value":1.0}\n'
        '{"schema_version":2,"t":3,"step":8,"type":"gauge",'
        '"name":"train.loss","value":99.0}\n')
    assert report.main(["--since-step", "5", str(f)]) == 0
    out = capsys.readouterr().out
    line = next(ln for ln in out.splitlines() if "train.loss" in ln)
    assert "99" in line and line.split()[1] == "1"   # count == 1


def test_spec_summary_fixture(report, tmp_path):
    """ISSUE 8 satellite: the speculative-decoding counters get a
    derived view — accept rate = accepted/draft and the verify-call
    amortization (emitted tokens per per-sequence verify pass)."""
    f = tmp_path / "spec.jsonl"
    f.write_text(
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"generate.spec.draft_tokens","value":80}\n'
        '{"schema_version":3,"t":2,"type":"counter",'
        '"name":"generate.spec.accepted_tokens","value":60}\n'
        '{"schema_version":3,"t":3,"type":"counter",'
        '"name":"generate.spec.verify_calls","value":10}\n')
    summ = report.summarize(report.load_records([str(f)]))
    spec = report.spec_summary(summ["counters"])
    assert spec["accept_rate"] == 0.75            # 60 / 80
    assert spec["tokens_per_verify"] == 7.0       # (60 + 10) / 10
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "speculative decoding" in text
    assert "accept rate 0.75" in text
    assert "tokens/verify 7" in text
    # a spec-off stream (no draft counter) -> no section
    assert report.spec_summary({"serving.requests": 3.0}) is None
    # verify counter missing entirely (wounded stream): rate still
    # reported, amortization honestly absent
    partial = report.spec_summary({
        "generate.spec.draft_tokens": 8.0,
        "generate.spec.accepted_tokens": 4.0})
    assert partial["accept_rate"] == 0.5
    assert partial["tokens_per_verify"] is None


def test_controller_summary_fixture(report, tmp_path):
    """ISSUE 15 satellite: the elastic-controller counters/gauges get
    a derived view — actions by kind+pool, drained requests,
    chip-seconds, final pool sizes — and an absent stream hides the
    section."""
    f = tmp_path / "ctrl.jsonl"
    f.write_text(
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"controller.actions","value":2,'
        '"tags":{"action":"spawn","pool":"decode"}}\n'
        '{"schema_version":3,"t":2,"type":"counter",'
        '"name":"controller.actions","value":1,'
        '"tags":{"action":"drain","pool":"decode"}}\n'
        '{"schema_version":3,"t":3,"type":"counter",'
        '"name":"controller.drained_requests","value":3}\n'
        '{"schema_version":3,"t":4,"type":"gauge",'
        '"name":"controller.chip_seconds","value":41.5}\n'
        '{"schema_version":3,"t":5,"type":"gauge",'
        '"name":"controller.pool_size","value":2,'
        '"tags":{"pool":"decode"}}\n'
        '{"schema_version":3,"t":6,"type":"gauge",'
        '"name":"controller.pool_size","value":1,'
        '"tags":{"pool":"prefill"}}\n')
    summ = report.summarize(report.load_records([str(f)]))
    ctrl = report.controller_summary(summ)
    assert ctrl["spawns"] == 2
    assert ctrl["drains"] == 1
    assert ctrl["drained_requests"] == 3
    assert ctrl["chip_seconds"] == 41.5
    assert ctrl["pool_size_last"] == {"decode": 2.0, "prefill": 1.0}
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "elastic pool controller" in text
    assert "spawns 2" in text and "drains 1" in text
    assert "chip-seconds 41.5" in text
    assert "decode:2" in text
    # a controller-free stream -> no section
    assert report.controller_summary(
        {"counters": {"serving.requests": 3.0}, "gauges": {}}) is None


# -- aggregate_telemetry --window (ISSUE 9 satellite) ------------------------


@pytest.fixture(scope="module")
def aggregate():
    spec = importlib.util.spec_from_file_location(
        "aggregate_telemetry", os.path.join(REPO, "tools",
                                            "aggregate_telemetry.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _segment(sketch_mod, name, values, counter):
    """One appended run segment: meta + a cumulative sketch flush + a
    cumulative counter flush."""
    import json

    sk = sketch_mod.LogBucketSketch()
    for v in values:
        sk.observe(v)
    return (
        json.dumps({"type": "meta", "schema_version": 3}) + "\n"
        + json.dumps({"type": "sketch", "name": name,
                      "tags": {"slo_class": "interactive"},
                      "value": sk.to_dict()}) + "\n"
        + json.dumps({"type": "counter",
                      "name": "serving.goodput.met",
                      "tags": {"slo_class": "interactive"},
                      "value": counter}) + "\n")


def test_window_merges_only_last_n_segments(aggregate, tmp_path):
    """--window N: an autoscaler polling recent fleet percentiles must
    not see lifetime history — only each file's last N run segments
    merge.  Lifetime (no window) still merges everything."""
    sketch_mod = aggregate.load_sketch_module()
    f = tmp_path / "host0.jsonl"
    f.write_text(
        _segment(sketch_mod, "serving.ttft_ms", [1.0] * 8, 8.0)
        + _segment(sketch_mod, "serving.ttft_ms", [100.0] * 4, 4.0)
        + _segment(sketch_mod, "serving.ttft_ms", [1000.0] * 2, 2.0))
    key = "serving.ttft_ms{slo_class=interactive}"
    records = aggregate.load_records([str(f)])

    lifetime = aggregate.aggregate(records)
    assert lifetime["sketches"][key]["count"] == 14
    assert lifetime["counters"][
        "serving.goodput.met{slo_class=interactive}"] == 14.0

    last1 = aggregate.aggregate(aggregate.windowed(records, 1))
    assert last1["sketches"][key]["count"] == 2
    assert last1["sketches"][key]["p50"] >= 1000.0 * 0.96
    assert last1["goodput"]["interactive"]["met"] == 2.0

    last2 = aggregate.aggregate(aggregate.windowed(records, 2))
    assert last2["sketches"][key]["count"] == 6
    # window wider than history = lifetime
    assert aggregate.aggregate(aggregate.windowed(records, 99))[
        "sketches"][key]["count"] == 14

    with pytest.raises(ValueError, match="window"):
        aggregate.windowed(records, 0)


def test_window_is_per_file(aggregate, tmp_path):
    """Each FILE keeps its own last-N segments (hosts flush on their
    own cadence; one busy host must not evict another's only
    segment)."""
    sketch_mod = aggregate.load_sketch_module()
    a = tmp_path / "a.jsonl"
    a.write_text(
        _segment(sketch_mod, "serving.ttft_ms", [1.0] * 4, 4.0)
        + _segment(sketch_mod, "serving.ttft_ms", [10.0] * 3, 3.0))
    b = tmp_path / "b.jsonl"
    b.write_text(_segment(sketch_mod, "serving.ttft_ms", [10.0] * 5,
                          5.0))
    agg = aggregate.aggregate(aggregate.windowed(
        aggregate.load_records([str(a), str(b)]), 1))
    key = "serving.ttft_ms{slo_class=interactive}"
    # a's last segment (3) + b's only segment (5)
    assert agg["sketches"][key]["count"] == 8
    assert agg["goodput"]["interactive"]["met"] == 8.0


def test_window_cli_flag(aggregate, tmp_path, capsys):
    sketch_mod = aggregate.load_sketch_module()
    f = tmp_path / "h.jsonl"
    f.write_text(
        _segment(sketch_mod, "serving.ttft_ms", [1.0] * 8, 8.0)
        + _segment(sketch_mod, "serving.ttft_ms", [5.0] * 2, 2.0))
    out_json = tmp_path / "agg.json"
    rc = aggregate.main(["--window", "1", "--json", str(out_json),
                         str(f)])
    assert rc == 0
    import json

    agg = json.loads(out_json.read_text())
    assert agg["window"] == 1
    assert agg["sketches"][
        "serving.ttft_ms{slo_class=interactive}"]["count"] == 2


def test_moe_summary_from_stream(report, tmp_path):
    """The ISSUE-10 MoE view: wire-vs-raw dispatch ratio, the
    hops == (ep-1) x calls ring check with the implied ep, and the
    expert-load imbalance from the bench-probe gauges."""
    f = tmp_path / "moe.jsonl"
    f.write_text(
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"moe.dispatch_bytes","value":72000}\n'
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"moe.dispatch_raw_bytes","value":256000}\n'
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"moe.ring_calls","value":6}\n'
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"moe.ring_hops","value":42}\n'
        '{"schema_version":3,"t":2,"type":"gauge",'
        '"name":"moe.expert_load_max","value":24}\n'
        '{"schema_version":3,"t":2,"type":"gauge",'
        '"name":"moe.expert_load_mean","value":16}\n')
    summ = report.summarize(report.load_records([str(f)]))
    moe = report.moe_summary(summ)
    assert moe is not None
    assert moe["wire_over_raw"] == pytest.approx(72000 / 256000)
    assert moe["hops_per_call"] == pytest.approx(7.0)
    assert moe["ep"] == 8                    # hops/call + 1
    assert moe["load_imbalance"] == pytest.approx(1.5)
    import io
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "expert-parallel MoE" in text
    assert "ep 8" in text
    assert "imbalance 1.5" in text


def test_moe_summary_absent_for_dense_streams(report, tmp_path):
    f = tmp_path / "dense.jsonl"
    f.write_text('{"schema_version":3,"t":1,"type":"counter",'
                 '"name":"collectives.ring.calls","value":2}\n')
    summ = report.summarize(report.load_records([str(f)]))
    assert report.moe_summary(summ) is None


def test_checkpoint_summary_from_stream(report, tmp_path):
    """The ISSUE-11 checkpoint view: save/restore ms p50/p95 from the
    span series, bytes + rollback counters, and the overlap-ratio
    gauge — plus the printed section with the rollback callout."""
    f = tmp_path / "ckpt.jsonl"
    lines = []
    for v in (0.10, 0.12, 0.14, 0.40):       # save seconds -> ms
        lines.append('{"schema_version":3,"t":1,"type":"span",'
                     f'"name":"checkpoint.save","value":{v}}}')
    lines.append('{"schema_version":3,"t":1,"type":"span",'
                 '"name":"checkpoint.blocking","value":0.002}')
    lines.append('{"schema_version":3,"t":2,"type":"span",'
                 '"name":"checkpoint.restore","value":0.25}')
    for name, v in (("checkpoint.saves", 4), ("checkpoint.bytes", 8192),
                    ("checkpoint.restores", 1),
                    ("checkpoint.rollbacks", 1)):
        lines.append('{"schema_version":3,"t":3,"type":"counter",'
                     f'"name":"{name}","value":{v}}}')
    lines.append('{"schema_version":3,"t":3,"type":"gauge",'
                 '"name":"checkpoint.overlap_ratio","value":0.996}')
    f.write_text("\n".join(lines) + "\n")
    summ = report.summarize(report.load_records([str(f)]))
    ck = report.checkpoint_summary(summ)
    assert ck is not None
    assert ck["saves"] == 4 and ck["bytes"] == 8192
    assert ck["rollbacks"] == 1 and ck["restores"] == 1
    # nearest-rank on 4 samples: p50 -> index round(1.5) = 2
    assert ck["save_ms"]["p50"] == pytest.approx(140.0)
    assert ck["save_ms"]["p95"] == pytest.approx(400.0)
    assert ck["restore_ms"]["p50"] == pytest.approx(250.0)
    assert ck["blocking_ms"]["p50"] == pytest.approx(2.0)
    assert ck["overlap_ratio"] == pytest.approx(0.996)
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "checkpointing (checkpoint.*)" in text
    assert "overlap ratio 0.996" in text
    assert "ROLLBACKS 1" in text
    assert "health_report" in text


def test_checkpoint_summary_absent_without_series(report, tmp_path):
    f = tmp_path / "nock.jsonl"
    f.write_text('{"schema_version":3,"t":1,"type":"counter",'
                 '"name":"train.overflow_count","value":2}\n')
    summ = report.summarize(report.load_records([str(f)]))
    assert report.checkpoint_summary(summ) is None


def test_audit_summary_from_stream(report, tmp_path):
    """The ISSUE-12 jaxpr-audit view: per-entry census-vs-counter
    deltas.  Agreement renders 'ok'; census > counted flags the entry
    as accounting drift (the uncounted-collective direction the
    static_audit gate fails on); counted > census annotates the benign
    custom_vjp re-trace direction."""
    import io

    f = tmp_path / "audit.jsonl"
    f.write_text(
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"audit.census.all_to_all","value":3,'
        '"tags":{"entry":"moe_ragged"}}\n'
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"audit.counted.all_to_all","value":3,'
        '"tags":{"entry":"moe_ragged"}}\n'
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"audit.census.ppermute","value":14,'
        '"tags":{"entry":"tp_ring_overlap"}}\n'
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"audit.counted.ppermute","value":12,'
        '"tags":{"entry":"tp_ring_overlap"}}\n')
    summ = report.summarize(report.load_records([str(f)]))
    audit = report.audit_summary(summ["counters"])
    assert audit is not None
    moe = audit["moe_ragged"]
    assert moe["drift"] is False
    assert moe["kinds"]["all_to_all"]["delta"] == 0
    ring = audit["tp_ring_overlap"]
    assert ring["drift"] is True
    assert ring["kinds"]["ppermute"]["delta"] == pytest.approx(2.0)
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "jaxpr audit (audit.*)" in text
    assert "moe_ragged: ok" in text
    assert "ACCOUNTING DRIFT" in text
    assert "uncounted collective" in text


def test_audit_summary_absent_without_series(report, tmp_path):
    f = tmp_path / "noaudit.jsonl"
    f.write_text('{"schema_version":3,"t":1,"type":"counter",'
                 '"name":"collectives.psum.calls","value":2}\n')
    summ = report.summarize(report.load_records([str(f)]))
    assert report.audit_summary(summ["counters"]) is None


def test_audit_summary_tier_c_row(report, tmp_path):
    """The ISSUE-13 tier-C row: audit.tierc.* counters from the
    concurrency_audit stress smoke render under the reserved 'tier_c'
    key with the zero-underflow / zero-new-findings gates deriving
    'clean'; the print section carries the row next to the jaxpr
    entries."""
    import io

    def stream(underflows, sketch_count=1600):
        return (
            '{"schema_version":3,"t":1,"type":"counter",'
            '"name":"audit.tierc.scrapes","value":120}\n'
            '{"schema_version":3,"t":1,"type":"counter",'
            '"name":"audit.tierc.flushes","value":90}\n'
            '{"schema_version":3,"t":1,"type":"counter",'
            '"name":"audit.tierc.saves","value":4}\n'
            '{"schema_version":3,"t":1,"type":"counter",'
            '"name":"audit.tierc.admits","value":388}\n'
            '{"schema_version":3,"t":1,"type":"counter",'
            f'"name":"audit.tierc.sketch_count","value":{sketch_count}}}\n'
            '{"schema_version":3,"t":1,"type":"counter",'
            '"name":"audit.tierc.sketch_expected","value":1600}\n'
            '{"schema_version":3,"t":1,"type":"counter",'
            '"name":"audit.tierc.scrape_parse_failures","value":0}\n'
            '{"schema_version":3,"t":1,"type":"counter",'
            '"name":"audit.tierc.refcount_underflows",'
            f'"value":{underflows}}}\n'
            '{"schema_version":3,"t":1,"type":"counter",'
            '"name":"audit.tierc.new_findings","value":0}\n')

    f = tmp_path / "tierc.jsonl"
    f.write_text(stream(underflows=0))
    summ = report.summarize(report.load_records([str(f)]))
    audit = report.audit_summary(summ["counters"])
    assert audit is not None
    tc = audit["tier_c"]
    assert tc["clean"] is True
    assert tc["stress"]["scrapes"] == 120
    assert tc["stress"]["admits"] == 388
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "tier C (concurrency stress): ok" in text
    assert "scrapes 120" in text

    # an underflow flips the row to FAILED — the report mirrors the
    # gate, it never launders a red smoke into an 'ok' line
    f.write_text(stream(underflows=2))
    summ = report.summarize(report.load_records([str(f)]))
    audit = report.audit_summary(summ["counters"])
    assert audit["tier_c"]["clean"] is False
    out = io.StringIO()
    report.print_report(summ, out=out)
    assert "FAILED — see the concurrency_audit gate" in out.getvalue()

    # a torn sketch (realized count != expected) also flips it — the
    # stream carries the REALIZED count, not the expected product
    f.write_text(stream(underflows=0, sketch_count=1599))
    summ = report.summarize(report.load_records([str(f)]))
    audit = report.audit_summary(summ["counters"])
    assert audit["tier_c"]["clean"] is False

    # tier-C counters alone (no jaxpr entries) still produce a report
    assert "moe_ragged" not in audit


def test_quantized_cache_summary_from_stream(report, tmp_path):
    """ISSUE 14 satellite: the dtype-tagged serving.cache_* gauges fold
    into bytes-per-resident-token per dtype, pool high-water, and —
    with both ablation dtypes in one stream — the implied admission
    multiple at matched pool bytes."""
    f = tmp_path / "quant.jsonl"
    rows = []
    for dtype, cb, cap, hw in (("bfloat16", 393216, 3072, 20),
                               ("int8", 391680, 5760, 38)):
        tags = '"tags":{"dtype":"%s"}' % dtype
        rows += [
            '{"schema_version":3,"t":1,"type":"gauge",'
            '"name":"serving.cache_bytes","value":%d,%s}' % (cb, tags),
            '{"schema_version":3,"t":2,"type":"gauge",'
            '"name":"serving.cache_capacity_tokens","value":%d,%s}'
            % (cap, tags),
            '{"schema_version":3,"t":3,"type":"gauge",'
            '"name":"serving.cache_blocks_hw","value":%d,%s}'
            % (hw, tags),
        ]
    f.write_text("\n".join(rows) + "\n")
    summ = report.summarize(report.load_records([str(f)]))
    # tagged gauges keep their tag suffix as distinct series
    assert "serving.cache_bytes{dtype=int8}" in summ["gauges"]
    q = report.quantized_cache_summary(summ)
    bf = q["dtypes"]["bfloat16"]
    i8 = q["dtypes"]["int8"]
    assert bf["bytes_per_token"] == 393216 / 3072   # 128 B/token
    assert i8["bytes_per_token"] == 391680 / 5760   # 68 B/token
    assert i8["pool_high_water_blocks"] == 38
    assert q["cheapest"] == "int8" and q["dearest"] == "bfloat16"
    assert abs(q["admission_multiple"] - 128 / 68) < 1e-9
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "quantized KV cache" in text
    assert "admission multiple at matched bytes" in text
    assert "1.88x" in text

    # one dtype only: per-dtype rows, no multiple
    single = report.quantized_cache_summary({
        "gauges": {"serving.cache_bytes{dtype=int8}": [100.0],
                   "serving.cache_capacity_tokens{dtype=int8}": [50.0]}})
    assert single["dtypes"]["int8"]["bytes_per_token"] == 2.0
    assert single["admission_multiple"] is None

    # a pre-ISSUE-14 stream -> no section
    assert report.quantized_cache_summary(
        {"gauges": {"serving.blocks_in_use": [1.0]}}) is None


def test_compile_cache_summary_from_stream(report, tmp_path):
    """ISSUE 17 satellite: the persistent compile-cache ledger gets a
    derived view — hit rate over load_or_compile calls, load-wall
    p50/p95 against the cumulative compile.ms ledger, warmup-ladder
    runs, and the worker READY wall — and an absent stream hides the
    section."""
    f = tmp_path / "cc.jsonl"
    f.write_text(
        '{"schema_version":3,"t":1,"type":"counter",'
        '"name":"serving.compile_cache.hits","value":9}\n'
        '{"schema_version":3,"t":2,"type":"counter",'
        '"name":"serving.compile_cache.misses","value":3}\n'
        '{"schema_version":3,"t":3,"type":"observe",'
        '"name":"serving.compile_cache.load_ms","value":4.0}\n'
        '{"schema_version":3,"t":4,"type":"observe",'
        '"name":"serving.compile_cache.load_ms","value":6.0}\n'
        '{"schema_version":3,"t":5,"type":"observe",'
        '"name":"serving.compile_cache.load_ms","value":20.0}\n'
        '{"schema_version":3,"t":6,"type":"counter",'
        '"name":"compile.count","value":3}\n'
        '{"schema_version":3,"t":7,"type":"counter",'
        '"name":"compile.ms","value":5400.0}\n'
        '{"schema_version":3,"t":8,"type":"event",'
        '"name":"serving.compile_cache.warmup","value":1}\n'
        '{"schema_version":3,"t":9,"type":"gauge",'
        '"name":"worker.ready_ms","value":6200.0}\n'
        '{"schema_version":3,"t":10,"type":"gauge",'
        '"name":"worker.ready_ms","value":1800.0}\n')
    summ = report.summarize(report.load_records([str(f)]))
    cc = report.compile_cache_summary(summ)
    assert cc["hits"] == 9 and cc["misses"] == 3
    assert abs(cc["hit_rate"] - 0.75) < 1e-9
    # nearest-rank over [4, 6, 20]: p50 = 6, p95 = 20
    assert cc["load_ms"] == {"p50": 6.0, "p95": 20.0, "count": 3}
    assert cc["compile_count"] == 3
    assert cc["compile_ms_total"] == 5400.0
    assert cc["warmups"] == 1
    assert cc["ready_ms"]["count"] == 2
    assert cc["ready_ms"]["last"] == 1800.0
    assert cc["ready_ms"]["max"] == 6200.0
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "compile cache (serving.compile_cache.*)" in text
    assert "hit rate 0.75" in text
    assert "warmup ladders 1" in text
    assert "load ms p50 6" in text
    assert "XLA compiles 3" in text
    assert "worker READY ms last 1800" in text


def test_compile_cache_summary_ready_only_and_absent(report):
    """A stream holding only worker.ready_ms (no-cache worker) still
    gets the READY row; a cache-free, READY-free stream hides the
    section entirely."""
    ready_only = report.compile_cache_summary({
        "counters": {}, "spans": {}, "events": {},
        "gauges": {"worker.ready_ms": [2500.0]}})
    assert ready_only["hit_rate"] is None
    assert ready_only["load_ms"] is None
    assert ready_only["ready_ms"]["last"] == 2500.0
    assert report.compile_cache_summary(
        {"counters": {"serving.requests": 4.0}, "spans": {},
         "events": {}, "gauges": {}}) is None


def test_host_tier_summary_from_stream(report, tmp_path):
    """ISSUE 18 satellite: the host-DRAM KV tier gets a derived view —
    take-side hit rate, the resume-vs-replay split of re-admissions,
    parked-bytes/pages high-water, page-in latency from the mergeable
    sketch, and fleet prefix-affinity routing hits."""
    import json

    sk_mod = report._load_sketch_module()
    sk = sk_mod.LogBucketSketch()
    for v in (2.0, 3.0, 9.0):
        sk.observe(v)
    recs = [
        {"type": "counter", "name": "serving.host_tier.hits",
         "value": 6},
        {"type": "counter", "name": "serving.host_tier.misses",
         "value": 2},
        {"type": "counter", "name": "serving.host_tier.evictions",
         "value": 1},
        {"type": "counter", "name": "serving.host_tier.page_ins",
         "value": 8},
        {"type": "counter", "name": "serving.host_tier.resumes",
         "value": 3},
        {"type": "counter", "name": "serving.host_tier.replays",
         "value": 1},
        {"type": "counter", "name": "cluster.prefix_affinity_hits",
         "value": 5},
        {"type": "gauge", "name": "serving.host_tier.bytes",
         "value": 1024.0},
        {"type": "gauge", "name": "serving.host_tier.bytes",
         "value": 4096.0},
        {"type": "gauge", "name": "serving.host_tier.bytes",
         "value": 2048.0},
        {"type": "gauge", "name": "serving.host_tier.pages",
         "value": 4.0},
        {"type": "sketch", "name": "serving.host_tier.page_in_ms",
         "value": sk.to_dict()},
    ]
    f = tmp_path / "ht.jsonl"
    f.write_text("".join(
        json.dumps(dict(r, schema_version=3, t=i)) + "\n"
        for i, r in enumerate(recs)))
    summ = report.summarize(report.load_records([str(f)]))
    ht = report.host_tier_summary(summ)
    assert ht["hits"] == 6 and ht["misses"] == 2
    assert abs(ht["hit_rate"] - 0.75) < 1e-9
    assert ht["resumes"] == 3 and ht["replays"] == 1
    assert abs(ht["resume_ratio"] - 0.75) < 1e-9
    assert ht["bytes_high_water"] == 4096.0
    assert ht["pages_high_water"] == 4.0
    assert ht["page_ins"] == 8 and ht["evictions"] == 1
    assert ht["prefix_affinity_hits"] == 5
    assert ht["page_in_ms"]["count"] == 3
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "host-DRAM KV tier (serving.host_tier.*)" in text
    assert "hit rate 0.75" in text
    assert "resume ratio 0.75" in text
    assert "page-in ms p50" in text
    assert "prefix-affinity routed dispatches 5" in text


def test_host_tier_summary_absent_without_series(report):
    """A stream with no host-tier series (tier off, older writers)
    hides the section entirely."""
    assert report.host_tier_summary(
        {"counters": {"serving.requests": 4.0}, "spans": {},
         "events": {}, "gauges": {}}) is None


def test_adapter_summary_from_stream(report, tmp_path):
    """ISSUE 20 satellite: the multi-tenant adapter pool gets a
    derived view — acquire-side hit rate, evictions, residency
    high-water, per-adapter request counts from the tagged
    serving.adapter.requests series, and fleet adapter-affinity
    routing hits."""
    import json

    recs = [
        {"type": "counter", "name": "serving.adapter.hits",
         "value": 9},
        {"type": "counter", "name": "serving.adapter.misses",
         "value": 3},
        {"type": "counter", "name": "serving.adapter.evictions",
         "value": 2},
        {"type": "counter", "name": "serving.adapter.requests",
         "tags": {"adapter": "1"}, "value": 7},
        {"type": "counter", "name": "serving.adapter.requests",
         "tags": {"adapter": "8"}, "value": 4},
        {"type": "counter", "name": "cluster.adapter_affinity_hits",
         "value": 6},
        {"type": "gauge", "name": "serving.adapter.resident",
         "value": 2.0},
        {"type": "gauge", "name": "serving.adapter.resident",
         "value": 4.0},
        {"type": "gauge", "name": "serving.adapter.resident",
         "value": 3.0},
        {"type": "gauge", "name": "serving.adapter.bytes",
         "value": 8192.0},
    ]
    f = tmp_path / "ad.jsonl"
    f.write_text("".join(
        json.dumps(dict(r, schema_version=3, t=i)) + "\n"
        for i, r in enumerate(recs)))
    summ = report.summarize(report.load_records([str(f)]))
    ad = report.adapter_summary(summ)
    assert ad["hits"] == 9 and ad["misses"] == 3
    assert abs(ad["hit_rate"] - 0.75) < 1e-9
    assert ad["evictions"] == 2
    assert ad["per_adapter"] == {"1": 7.0, "8": 4.0}
    assert ad["requests"] == 11 and ad["distinct_adapters"] == 2
    assert ad["resident_high_water"] == 4.0
    assert ad["bytes_high_water"] == 8192.0
    assert ad["adapter_affinity_hits"] == 6
    out = io.StringIO()
    report.print_report(summ, out=out)
    text = out.getvalue()
    assert "multi-tenant adapters (serving.adapter.*)" in text
    assert "hit rate 0.75" in text
    assert "requests 11 across 2 adapter(s)" in text
    assert "requests by adapter 1:7  8:4" in text
    assert "adapter-affinity routed dispatches 6" in text


def test_adapter_summary_absent_without_series(report):
    """A stream with no adapter series (pool off, older writers)
    hides the section entirely."""
    summ = {"counters": {"serving.requests": 4.0}, "spans": {},
            "events": {}, "gauges": {}}
    assert report.adapter_summary(summ) is None
    out = io.StringIO()
    report.print_report(dict(summ, sketches={}, truncated={},
                             unknown_schema=[], missing_schema=0),
                        out=out)
    assert "multi-tenant adapters" not in out.getvalue()


def test_host_tier_page_in_sketch_merges_across_hosts(
        aggregate, tmp_path):
    """ISSUE 18 satellite: serving.host_tier.page_in_ms rides the
    generic sketch-merge path — two hosts' cumulative flushes fold
    into one exact fleet quantile summary."""
    import json

    sk_mod = aggregate.load_sketch_module()

    def seg(values):
        sk = sk_mod.LogBucketSketch()
        for v in values:
            sk.observe(v)
        return (json.dumps({"type": "meta", "schema_version": 3})
                + "\n"
                + json.dumps({"type": "sketch",
                              "name": "serving.host_tier.page_in_ms",
                              "value": sk.to_dict()}) + "\n")

    a = tmp_path / "host_a.jsonl"
    b = tmp_path / "host_b.jsonl"
    a.write_text(seg([1.0, 2.0, 4.0]))
    b.write_text(seg([8.0, 16.0]))
    agg = aggregate.aggregate(
        aggregate.load_records([str(a), str(b)]))
    s = agg["sketches"]["serving.host_tier.page_in_ms"]
    assert s["count"] == 5
    assert s["max"] >= 16.0
