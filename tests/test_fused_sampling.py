"""Fused sampling parity suite (ISSUE 8).

Three layers of pinning:

- **reference path = the historical sampler, bit for bit**: a local
  reimplementation of the pre-fusion op chain (temperature → lax.top_k
  / sort → nucleus cumsum → ``jax.random.categorical``) is the oracle;
  ``fused_sample(backend="reference")`` (and therefore the
  ``sample_logits`` thin wrapper) must match it exactly under matched
  PRNG keys, every filter combination, fp32 and bf16.
- **kernel path**: greedy rows are exact; the filters select exactly
  the reference support (bisection cutoffs vs ``filter_logits``); the
  draw is distributional — χ² over a tiled batch (the in-kernel
  counter RNG is per-row, so one call yields N independent draws).
  Runs through the Pallas interpret path on the 8-virtual-device CPU
  mesh (conftest), the same route the CI uses for the flash/paged
  kernels.
- **routing**: ``APEX_TPU_FUSED_SAMPLING`` honored, malformed env
  values warn BY NAME and fall back to auto; malformed explicit
  ``backend=`` raises.

Plus the greedy short-circuit satellite: ``temperature == 0`` returns
the argmax under ANY top_k/top_p combination — the filters cannot
change which token is largest.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generate import sample_logits
from apex_tpu.ops.fused_sampling import (
    filter_logits, fused_sample, sample_reference)

_NEG_INF = -1e30


def _naive_sample(logits, key, *, temperature=0.0, top_k=None,
                  top_p=None, vocab_limit=None):
    """The pre-ISSUE-8 ``sample_logits`` op chain, verbatim — the
    bit-compatibility oracle for the reference path."""
    if vocab_limit is not None:
        over = jnp.arange(logits.shape[-1]) >= vocab_limit
        logits = jnp.where(over[None], _NEG_INF, logits)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_p is None:
        if top_k is not None:
            kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits < kth, _NEG_INF, logits)
        return jax.random.categorical(key, logits).astype(jnp.int32)
    sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
    if top_k is not None:
        kth = sorted_l[:, top_k - 1][:, None]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
        rank = jnp.arange(sorted_l.shape[-1])[None]
        sorted_l = jnp.where(rank >= top_k, _NEG_INF, sorted_l)
    probs = jax.nn.softmax(sorted_l, axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    keep = (csum - probs) < top_p
    n_keep = jnp.maximum(jnp.sum(keep, axis=-1), 1)
    cutoff = jnp.take_along_axis(sorted_l, (n_keep - 1)[:, None],
                                 axis=-1)
    logits = jnp.where(logits < cutoff, _NEG_INF, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


FILTERS = [
    dict(),
    dict(top_k=5),
    dict(top_p=0.7),
    dict(top_k=8, top_p=0.8),
    dict(vocab_limit=40),
    dict(top_k=4, top_p=0.9, vocab_limit=50),
]


class TestReferenceBitCompat:
    @pytest.mark.parametrize("kw", FILTERS)
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_matched_key_equality_with_historical_chain(self, kw, dtype):
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(4, 64), dtype) * 2
        for seed in range(5):
            key = jax.random.PRNGKey(seed)
            want = _naive_sample(logits, key, temperature=0.8, **kw)
            got = fused_sample(logits, key, temperature=0.8,
                               backend="reference", **kw)
            np.testing.assert_array_equal(np.asarray(want),
                                          np.asarray(got), err_msg=str(kw))
            # the thin wrapper routes here off-TPU: same bits
            wrapped = sample_logits(logits, key, temperature=0.8, **kw)
            np.testing.assert_array_equal(np.asarray(want),
                                          np.asarray(wrapped))

    def test_vector_temperature_matches_engine_composition(self):
        """The serving engine's mixed-temperature contract: greedy rows
        argmax, sampled rows temperature-1 over pre-scaled logits —
        same key, same bits."""
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(5, 32), jnp.float32)
        temps = jnp.asarray([0.0, 0.5, 0.0, 1.3, 2.0], jnp.float32)
        key = jax.random.PRNGKey(3)
        greedy = _naive_sample(logits, key)
        sampled = _naive_sample(
            logits / jnp.maximum(temps, 1e-6)[:, None], key,
            temperature=1.0, top_k=6)
        want = jnp.where(temps > 0, sampled, greedy)
        got = fused_sample(logits, key, temperature=temps, top_k=6,
                           backend="reference")
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


class TestGreedyShortCircuit:
    @pytest.mark.parametrize("kw", FILTERS)
    @pytest.mark.parametrize("backend", ["reference", "kernel"])
    def test_greedy_is_argmax_under_any_filter_combo(self, kw, backend):
        """The ISSUE 8 satellite: temperature 0 skips the filtering
        work entirely — top-k/top-p cannot change the argmax, so the
        output must equal the bare argmax for EVERY combination."""
        rng = np.random.RandomState(2)
        logits = jnp.asarray(rng.randn(6, 96), jnp.float32)
        want = np.asarray(logits).argmax(-1)
        if kw.get("vocab_limit"):
            want = np.asarray(logits)[:, : kw["vocab_limit"]].argmax(-1)
        got = fused_sample(logits, jax.random.PRNGKey(0),
                           temperature=0.0, backend=backend, **kw)
        np.testing.assert_array_equal(want, np.asarray(got),
                                      err_msg=f"{backend} {kw}")

    def test_sample_logits_greedy_unchanged_by_filters(self):
        rng = np.random.RandomState(3)
        logits = jnp.asarray(rng.randn(3, 50), jnp.float32)
        base = np.asarray(sample_logits(logits, jax.random.PRNGKey(0)))
        for kw in FILTERS:
            got = sample_logits(logits, jax.random.PRNGKey(0), **kw)
            want = base
            if kw.get("vocab_limit"):
                want = np.asarray(logits)[:, : kw["vocab_limit"]
                                          ].argmax(-1)
            np.testing.assert_array_equal(want, np.asarray(got),
                                          err_msg=str(kw))


class TestKernelPath:
    """``backend="kernel"`` — the fused Pallas kernel through the
    interpret route on the virtual-device mesh."""

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_support_matches_reference_filters(self, dtype):
        """Every kernel sample must land inside the EXACT support the
        reference filter chain keeps (bisection cutoff == sorted
        cutoff), for top-k, top-p, and their intersection."""
        rng = np.random.RandomState(4)
        row = jnp.asarray(rng.randn(1, 160), dtype) * 2
        tiled = jnp.tile(row, (256, 1))
        for kw in (dict(top_k=3), dict(top_p=0.6),
                   dict(top_k=7, top_p=0.8)):
            scaled = (row.astype(jnp.float32) / 0.9)
            f = np.asarray(filter_logits(scaled, **kw))[0]
            support = set(np.where(f > _NEG_INF / 2)[0].tolist())
            toks = np.asarray(fused_sample(
                tiled, jax.random.PRNGKey(11), temperature=0.9,
                backend="kernel", **kw))
            assert set(toks.tolist()) <= support, kw

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_chi_squared_distribution_parity(self, dtype):
        """One kernel call over N tiled rows = N independent draws
        (per-row counter RNG); their histogram must match the softmax
        distribution — χ²(v−1) under the 99.9th-percentile bound."""
        rng = np.random.RandomState(5)
        v, n = 8, 8192
        row = rng.randn(1, v).astype(np.float32)
        logits = jnp.asarray(row, dtype)
        p = np.asarray(jax.nn.softmax(
            logits.astype(jnp.float32) / 1.0))[0]
        toks = np.asarray(fused_sample(
            jnp.tile(logits, (n, 1)), jax.random.PRNGKey(9),
            temperature=1.0, backend="kernel"))
        counts = np.bincount(toks, minlength=v)
        chi2 = (((counts - n * p) ** 2) / (n * p)).sum()
        assert chi2 < 24.32, chi2      # chi2(7).ppf(0.999)

    def test_chi_squared_with_topk_filter(self):
        """The same distribution check against the FILTERED target —
        the kernel's cutoff + draw must compose correctly."""
        rng = np.random.RandomState(6)
        v, n, k = 16, 8192, 4
        row = jnp.asarray(rng.randn(1, v), jnp.float32)
        f = filter_logits(row / 0.8, top_k=k)
        p = np.asarray(jax.nn.softmax(f))[0]
        toks = np.asarray(fused_sample(
            jnp.tile(row, (n, 1)), jax.random.PRNGKey(13),
            temperature=0.8, top_k=k, backend="kernel"))
        counts = np.bincount(toks, minlength=v)
        live = p > 0
        assert counts[~live].sum() == 0
        chi2 = (((counts[live] - n * p[live]) ** 2)
                / (n * p[live])).sum()
        assert chi2 < 16.27, chi2      # chi2(3).ppf(0.999)

    def test_vector_temperature_greedy_rows_exact(self):
        rng = np.random.RandomState(7)
        logits = jnp.asarray(rng.randn(6, 200), jnp.float32)
        temps = jnp.asarray([0.0, 1.0, 0.0, 0.7, 0.0, 2.0], jnp.float32)
        got = np.asarray(fused_sample(logits, jax.random.PRNGKey(1),
                                      temperature=temps, top_k=5,
                                      backend="kernel"))
        want = np.asarray(logits).argmax(-1)
        greedy_rows = np.asarray(temps) == 0
        np.testing.assert_array_equal(got[greedy_rows],
                                      want[greedy_rows])

    def test_seeded_determinism_and_key_sensitivity(self):
        rng = np.random.RandomState(8)
        logits = jnp.asarray(rng.randn(64, 128), jnp.float32)
        a = np.asarray(fused_sample(logits, jax.random.PRNGKey(0),
                                    temperature=1.0, backend="kernel"))
        b = np.asarray(fused_sample(logits, jax.random.PRNGKey(0),
                                    temperature=1.0, backend="kernel"))
        c = np.asarray(fused_sample(logits, jax.random.PRNGKey(1),
                                    temperature=1.0, backend="kernel"))
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_unpadded_vocab_and_vocab_limit(self):
        """A non-lane-multiple vocab pads in the wrapper; neither the
        padding nor ids past vocab_limit may ever be sampled."""
        rng = np.random.RandomState(9)
        logits = jnp.asarray(rng.randn(128, 53), jnp.float32)
        toks = np.asarray(fused_sample(logits, jax.random.PRNGKey(2),
                                       temperature=1.5,
                                       backend="kernel"))
        assert toks.max() < 53
        toks = np.asarray(fused_sample(logits, jax.random.PRNGKey(2),
                                       temperature=1.5, vocab_limit=7,
                                       backend="kernel"))
        assert toks.max() < 7


class TestRouting:
    def test_env_override_is_honored(self, monkeypatch):
        """reference vs kernel draw different stochastic streams from
        the same key — that observable difference proves the env var
        actually switched the path."""
        rng = np.random.RandomState(10)
        logits = jnp.asarray(rng.randn(64, 256), jnp.float32)
        key = jax.random.PRNGKey(5)
        ref = np.asarray(fused_sample(logits, key, temperature=1.0,
                                      backend="reference"))
        kern = np.asarray(fused_sample(logits, key, temperature=1.0,
                                       backend="kernel"))
        assert not np.array_equal(ref, kern)
        monkeypatch.setenv("APEX_TPU_FUSED_SAMPLING", "reference")
        np.testing.assert_array_equal(
            ref, np.asarray(fused_sample(logits, key, temperature=1.0)))
        monkeypatch.setenv("APEX_TPU_FUSED_SAMPLING", "kernel")
        np.testing.assert_array_equal(
            kern, np.asarray(fused_sample(logits, key, temperature=1.0)))

    def test_malformed_env_warns_by_name_and_falls_back(
            self, monkeypatch):
        import io
        import logging

        from apex_tpu.utils.logging import get_logger

        rng = np.random.RandomState(11)
        logits = jnp.asarray(rng.randn(2, 32), jnp.float32)
        key = jax.random.PRNGKey(0)
        auto = np.asarray(fused_sample(logits, key, temperature=1.0))
        monkeypatch.setenv("APEX_TPU_FUSED_SAMPLING", "warp-speed")
        # the library logger does not propagate to the root logger, so
        # listen with our own handler instead of caplog/capsys
        stream = io.StringIO()
        handler = logging.StreamHandler(stream)
        logger = get_logger("ops")
        logger.addHandler(handler)
        try:
            got = np.asarray(fused_sample(logits, key, temperature=1.0))
        finally:
            logger.removeHandler(handler)
        np.testing.assert_array_equal(auto, got)   # fell back to auto
        err = stream.getvalue()
        assert "APEX_TPU_FUSED_SAMPLING" in err    # warns BY NAME
        assert "warp-speed" in err

    def test_malformed_backend_argument_raises(self):
        with pytest.raises(ValueError, match="backend"):
            fused_sample(jnp.zeros((1, 8)), jax.random.PRNGKey(0),
                         temperature=1.0, backend="fast")

    def test_invalid_sampling_args_raise(self):
        with pytest.raises(ValueError, match="temperature"):
            fused_sample(jnp.zeros((1, 8)), jax.random.PRNGKey(0),
                         temperature=-1.0)
        with pytest.raises(ValueError, match="top_k"):
            fused_sample(jnp.zeros((1, 8)), jax.random.PRNGKey(0),
                         temperature=1.0, top_k=0)

    def test_sample_reference_export_matches_wrapper(self):
        rng = np.random.RandomState(12)
        logits = jnp.asarray(rng.randn(3, 24), jnp.float32)
        key = jax.random.PRNGKey(4)
        np.testing.assert_array_equal(
            np.asarray(sample_reference(logits, key, temperature=0.6,
                                        top_k=3)),
            np.asarray(fused_sample(logits, key, temperature=0.6,
                                    top_k=3, backend="reference")))
