"""File-backed image pipeline: ImageFolder + Megatron samplers + the
imagenet example end-to-end on real files (reference
examples/imagenet/main_amp.py:188-218 ImageFolder/DataLoader path)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from apex_tpu.data import ImageFolderDataset, make_image_loader
from apex_tpu.transformer._data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _example_env():
    """Subprocess env for the example runs.  PYTHONPATH must be exactly
    the repo: inheriting the driver's axon sitecustomize would re-pin
    the subprocess to the TPU tunnel (and hang when the tunnel is
    unavailable)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO
    return env


@pytest.fixture(scope="module")
def image_tree(tmp_path_factory):
    """12 PNGs in 3 class dirs (odd sizes to exercise crops)."""
    from PIL import Image

    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(0)
    for ci, cls in enumerate(["ants", "bees", "cats"]):
        d = root / cls
        d.mkdir()
        for i in range(4):
            h, w = rng.randint(40, 90), rng.randint(40, 90)
            arr = rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"img{i}.png")
    return str(root)


class TestImageFolderDataset:
    def test_scan_and_decode(self, image_tree):
        ds = ImageFolderDataset(image_tree, image_size=32, train=True)
        assert len(ds) == 12
        assert ds.class_to_idx == {"ants": 0, "bees": 1, "cats": 2}
        img, label = ds[0]
        assert img.shape == (32, 32, 3) and img.dtype == np.float32
        assert label == 0
        assert ds[11][1] == 2

    def test_eval_crop_deterministic(self, image_tree):
        ds = ImageFolderDataset(image_tree, image_size=32, train=False)
        a, _ = ds[3]
        b, _ = ds[3]
        np.testing.assert_array_equal(a, b)

    def test_normalization_applied(self, image_tree):
        ds = ImageFolderDataset(image_tree, image_size=32, train=False)
        img, _ = ds[0]
        # mean/std normalization moves values out of [0, 1]
        assert img.min() < -0.5


class TestLoaderOverSamplers:
    def test_epoch_covers_every_sample_once(self, image_tree):
        ds = ImageFolderDataset(image_tree, image_size=32, train=False)
        sampler = MegatronPretrainingSampler(
            total_samples=len(ds), consumed_samples=0,
            local_minibatch_size=4, data_parallel_rank=0,
            data_parallel_size=1)
        labels = []
        for x, y in make_image_loader(ds, sampler, num_workers=2):
            assert x.shape == (4, 32, 32, 3)
            labels.extend(y.tolist())
        assert sorted(labels) == sorted(
            lb for _, lb in ds.samples)

    def test_random_sampler_resumes(self, image_tree):
        ds = ImageFolderDataset(image_tree, image_size=32, train=False)

        def batches(consumed):
            s = MegatronPretrainingRandomSampler(
                total_samples=len(ds), consumed_samples=consumed,
                local_minibatch_size=4, data_parallel_rank=0,
                data_parallel_size=1)
            return [y.tolist()
                    for _, y in make_image_loader(ds, s, num_workers=2)]

        full = batches(0)
        resumed = batches(4)       # one batch already consumed
        assert full[1:] == resumed  # same epoch shuffle, continued


class TestExampleEndToEnd:
    @pytest.mark.slow   # e2e example; CI slow job
    def test_imagenet_example_trains_on_files(self, image_tree, tmp_path):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "examples",
                                          "imagenet_rn50.py"),
             "--data-dir", image_tree, "--batch", "4", "--steps", "2",
             "--image-size", "32", "--steps-per-epoch", "4",
             "--arch", "resnet18", "--num-classes", "3"],
            env=_example_env(), cwd=REPO, capture_output=True,
            text=True, timeout=900)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "loss" in out.stdout and "prec@1" in out.stdout, out.stdout


class TestGptLmExample:
    @pytest.mark.slow   # e2e example; CI slow job
    def test_trains_on_text_and_samples(self, tmp_path):
        text = (
            "the quick brown fox jumps over the lazy dog. " * 200
        ).encode()
        f = tmp_path / "corpus.txt"
        f.write_bytes(text)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "examples", "gpt_lm.py"),
             "--data", str(f), "--steps", "80", "--batch", "8",
             "--seq", "64", "--layers", "2", "--hidden", "64",
             "--heads", "4", "--sample-tokens", "16", "--lr", "2e-3"],
            env=_example_env(), cwd=REPO, capture_output=True,
            text=True, timeout=900)
        assert out.returncode == 0, out.stderr[-2000:]
        assert ("final loss" in out.stdout
                and "sample" in out.stdout), out.stdout
        # byte-level model on highly repetitive text must learn fast
        loss = float(out.stdout.split("final loss")[1].split()[0])
        assert loss < 3.0, out.stdout


class TestDevicePrefetch:
    def test_order_and_placement(self):
        import jax
        from apex_tpu.data import device_prefetch

        batches = [(np.full((2, 3), i, np.float32), np.array([i]))
                   for i in range(7)]
        out = list(device_prefetch(iter(batches), size=3))
        assert len(out) == 7
        for i, (im, lb) in enumerate(out):
            assert isinstance(im, jax.Array)   # actually on device
            assert float(np.asarray(im)[0, 0]) == i
            assert int(np.asarray(lb)[0]) == i

    def test_sharded_placement_over_mesh(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from apex_tpu.data import device_prefetch
        from apex_tpu.parallel.mesh import create_mesh

        mesh = create_mesh(dp=8)
        sh = NamedSharding(mesh, P("dp"))
        batches = [(np.arange(16, dtype=np.float32).reshape(16, 1),)
                   for _ in range(3)]
        out = list(device_prefetch(iter(batches), size=2, sharding=sh))
        assert len(out) == 3
        (im,) = out[0]
        assert im.sharding == sh
        assert len(im.addressable_shards) == 8
        np.testing.assert_array_equal(
            np.asarray(im), batches[0][0])

    def test_size_validation(self):
        from apex_tpu.data import device_prefetch

        with pytest.raises(ValueError):
            list(device_prefetch(iter([]), size=0))

    def test_abandoned_consumer_releases_producer(self):
        # An early break must unblock the producer thread instead of
        # leaving it parked on q.put for the process lifetime (ADVICE r4).
        import threading

        from apex_tpu.data import device_prefetch

        produced = []

        def source():
            i = 0
            while True:
                produced.append(i)
                yield (np.full((2,), i, np.float32),)
                i += 1

        before = set(threading.enumerate())
        it = device_prefetch(source(), size=2)
        next(it)
        workers = [t for t in threading.enumerate() if t not in before]
        assert len(workers) == 1, workers
        it.close()  # GeneratorExit → finally → stop event + drain
        workers[0].join(timeout=10)
        assert not workers[0].is_alive(), "producer still running after close"
        assert len(produced) <= 6  # bounded: ~size+in-flight, not unbounded
