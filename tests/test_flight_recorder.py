"""ISSUE 4 tier-1 coverage: trace export, flight recorder, detectors,
recompile + HBM accounting, env validation, and the health-report tool.

The acceptance scenarios live here: a run that produces span + step +
serving-request rows in a schema-valid Chrome trace; an injected-NaN
train loop whose flight-recorder post-mortem names the first anomalous
step; and a forced shape-change retrace that increments
``compile.count``.
"""

import contextlib
import importlib.util
import io
import json
import logging
import math
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.observability as obs
from apex_tpu.observability import detectors as det

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    yield
    obs.shutdown()


@contextlib.contextmanager
def _capture_warnings():
    """The apex_tpu logger is propagate=False (its own stderr handler),
    so caplog never sees it — attach a capturing handler directly."""
    records = []

    class _H(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = _H(level=logging.WARNING)
    logger = logging.getLogger("apex_tpu")
    logger.addHandler(h)
    try:
        yield records
    finally:
        logger.removeHandler(h)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# trace export
# ---------------------------------------------------------------------------


# every Chrome trace event must carry these (the schema check the
# acceptance criterion names)
_REQUIRED_BY_PH = {
    "X": ("name", "pid", "tid", "ts", "dur"),
    "C": ("name", "pid", "ts"),
    "M": ("name", "pid"),
    "i": ("name", "pid", "tid", "ts"),
    "b": ("name", "pid", "tid", "ts", "id"),
    "e": ("name", "pid", "tid", "ts", "id"),
}


def _assert_valid_trace(events):
    assert events, "empty trace"
    for ev in events:
        assert isinstance(ev, dict)
        ph = ev.get("ph")
        assert ph in _REQUIRED_BY_PH, f"unknown phase {ph!r}: {ev}"
        for field in _REQUIRED_BY_PH[ph]:
            assert field in ev, f"{ph!r} event missing {field!r}: {ev}"
        if ph == "X":
            assert ev["dur"] >= 0


class TestTraceExport:
    def test_trace_file_is_valid_chrome_trace_json(self, tmp_path):
        path = tmp_path / "trace.json"
        obs.configure(trace_path=str(path))
        with obs.span("train_step"):
            pass
        obs.gauge("train.loss").set(1.5)
        obs.event("amp.loss_scale_change", old=2.0, new=1.0)
        obs.shutdown()
        events = json.load(open(path))     # plain json.load must work
        assert isinstance(events, list)
        _assert_valid_trace(events)
        assert {e["ph"] for e in events} >= {"X", "C", "M", "i"}

    def test_span_step_and_serving_rows(self, tmp_path):
        """The acceptance-criterion row kinds from one run: a span row,
        a StepTimer ``step.*`` row, and serving-request async rows."""
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.transformer_lm import init_gpt_params
        from apex_tpu.serving import ServingEngine

        path = tmp_path / "trace.json"
        obs.configure(trace_path=str(path))
        with obs.span("train_step"):
            jnp.ones((2,)).block_until_ready()
        obs.StepTimer("gpt2", warmup=1, iters=2).time(
            lambda c: (0, jnp.asarray(1.0)))
        cfg = TransformerConfig(
            num_layers=1, hidden_size=32, num_attention_heads=2,
            vocab_size=64, max_position_embeddings=32, remat=False,
            compute_dtype=jnp.float32)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=16,
                               prompt_buckets=(8,))
        engine.run([dict(prompt=np.asarray([1, 2, 3]),
                         max_new_tokens=2) for _ in range(2)])
        obs.shutdown()
        events = obs.load_trace(str(path))
        _assert_valid_trace(events)
        slices = {e["name"] for e in events if e["ph"] == "X"}
        assert "train_step" in slices            # span row
        assert "step.gpt2" in slices             # StepTimer row
        assert "serving.prefill" in slices       # serving span row
        begins = [e for e in events
                  if e["ph"] == "b" and e["name"] == "serving.request"]
        ends = [e for e in events
                if e["ph"] == "e" and e["name"] == "serving.request"]
        assert {e["id"] for e in begins} == {0, 1}   # per-request rows
        assert {e["id"] for e in ends} == {0, 1}
        # counter tracks from the gauges
        counters = {e["name"] for e in events if e["ph"] == "C"}
        assert "serving.queue_depth" in counters

    def test_truncated_trace_still_loads(self, tmp_path):
        """Crash robustness: the array form loads with the tail
        missing (the file of a process that died mid-write)."""
        path = tmp_path / "trace.json"
        obs.configure(trace_path=str(path))
        with obs.span("s1"):
            pass
        with obs.span("s2"):
            pass
        obs.registry().flush()
        # simulate the crash: no close; chop the final line in half
        full = open(path).read().rstrip()
        (tmp_path / "cut.json").write_text(full[: -10])
        events = obs.load_trace(str(tmp_path / "cut.json"))
        assert any(e.get("name") == "s1" for e in events)
        obs.shutdown()

    def test_nonfinite_values_stay_strict_json(self, tmp_path):
        """A NaN loss is the flagship incident: Perfetto's strict
        JSON.parse rejects bare NaN/Infinity tokens, so the trace of
        exactly the run being debugged must never contain them."""
        path = tmp_path / "trace.json"
        obs.configure(trace_path=str(path))
        obs.gauge("train.loss").set(float("nan"))
        obs.gauge("train.grad_norm").set(float("inf"))
        obs.event("anomaly.nan_inf", value=float("nan"))
        obs.shutdown()
        text = open(path).read()
        import re

        assert not re.search(r"\bNaN\b|\bInfinity\b", text), text
        events = json.loads(text)       # and still fully parseable
        assert any(e.get("name") == "train.loss" for e in events)

    def test_user_host_tag_is_not_assumed_numeric(self, tmp_path):
        # tags={"host": hostname} is a natural user tag; it must not
        # kill configure() even though the registry's own rank tag is
        # an int
        path = tmp_path / "trace.json"
        obs.configure(trace_path=str(path), tags={"host": "gpu-node-1"})
        with obs.span("s"):
            pass
        obs.shutdown()
        events = obs.load_trace(str(path))
        assert any(e["ph"] == "X" and e["pid"] == 0 for e in events)

    def test_spans_land_on_family_thread_rows(self, tmp_path):
        path = tmp_path / "trace.json"
        obs.configure(trace_path=str(path))
        with obs.span("serving.prefill"):
            pass
        with obs.span("step.bench"):
            pass
        obs.shutdown()
        events = obs.load_trace(str(path))
        names = {e["args"]["name"]: e["tid"] for e in events
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        tid_of = {e["name"]: e["tid"] for e in events if e["ph"] == "X"}
        assert tid_of["serving.prefill"] == names["serving"]
        assert tid_of["step.bench"] == names["step"]
        assert names["serving"] != names["step"]


@pytest.mark.slow
def test_bench_decode_run_produces_valid_trace(tmp_path, monkeypatch,
                                               capsys):
    """The acceptance criterion end-to-end: one real ``bench.py
    --decode`` run (StepTimer rows + the serving mixes) with
    APEX_TPU_TELEMETRY_TRACE set produces a schema-valid trace
    containing span, step, and serving-request rows, and a BENCH JSON
    line carrying the runtime (compile/hbm) block.  Runs bench.main()
    in-process so the conftest jax-compat shims apply (a subprocess on
    a jax<0.9 container would lose the mesh/typeof shims the decode
    rows need)."""
    trace_path = tmp_path / "bench_trace.json"
    monkeypatch.setenv("APEX_TPU_TELEMETRY_TRACE", str(trace_path))
    monkeypatch.setattr(sys, "argv", ["bench.py", "--decode"])
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    bench_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_mod)
    bench_mod.main()
    obs.shutdown()                       # close/finalize the trace file
    stdout = capsys.readouterr().out
    line = next(ln for ln in stdout.splitlines() if ln.startswith("{"))
    bench = json.loads(line)
    for row in bench["details"].values():
        assert "error" not in row, row
    assert "runtime" in bench and "compile" in bench["runtime"]
    assert bench["runtime"]["compile"]["count"] > 0
    events = obs.load_trace(str(trace_path))
    _assert_valid_trace(events)
    slices = {e["name"] for e in events if e["ph"] == "X"}
    assert any(n.startswith("step.") for n in slices)        # StepTimer
    assert "serving.prefill" in slices                       # span row
    assert any(e["ph"] == "b" and e["name"] == "serving.request"
               for e in events)                              # request rows


# ---------------------------------------------------------------------------
# detectors (unit level)
# ---------------------------------------------------------------------------


class TestDetectors:
    def test_loss_spike_zscore(self):
        d = det.ZScoreDetector("loss", "loss_spike", threshold=6.0,
                               min_points=8)
        for i in range(20):
            assert d.feed(i, {"loss": 1.0 + 0.01 * (i % 3)}) is None
        a = d.feed(20, {"loss": 50.0})
        assert a is not None and a.kind == "loss_spike"
        assert a.step == 20

    def test_zscore_ignores_constant_series(self):
        # std ~ 0 on a constant series must not make 1.0001 a "spike"
        d = det.ZScoreDetector("loss", "loss_spike", min_points=4)
        for i in range(10):
            d.feed(i, {"loss": 1.0})
        assert d.feed(10, {"loss": 1.0001}) is None

    def test_nan_first_seen_fires_once_with_keys(self):
        d = det.NanInfDetector()
        assert d.feed(0, {"loss": 1.0, "grad_norm": 2.0}) is None
        a = d.feed(1, {"loss": 1.0, "grad_norm": float("inf")})
        assert a is not None and a.kind == "nan_inf"
        assert a.detail["keys"] == ["grad_norm"]
        assert a.step == 1
        # poisoned steps after the first do not re-fire
        assert d.feed(2, {"loss": float("nan")}) is None

    def test_scaler_thrash_rate_window_with_hysteresis(self):
        d = det.ScalerThrashDetector(window=16, rate_threshold=0.5,
                                     min_points=8)
        fired = [d.feed(i, i % 2 == 0) for i in range(40)]
        hits = [a for a in fired if a is not None]
        assert len(hits) == 1                      # hysteresis: one incident
        assert hits[0].kind == "scaler_thrash"
        d2 = det.ScalerThrashDetector(window=16, rate_threshold=0.5)
        assert all(d2.feed(i, False) is None for i in range(40))

    def test_throughput_regression(self):
        d = det.ThroughputRegressionDetector(baseline_points=4,
                                             recent=3, ratio=1.5)
        for i in range(6):
            assert d.feed("step.gpt2", 0.100) is None
        fired = [a for a in (d.feed("step.gpt2", 0.300, step=i)
                             for i in range(3)) if a is not None]
        assert len(fired) == 1          # hysteresis: one incident
        assert fired[0].kind == "throughput_regression"
        # an unrelated series keeps its own baseline
        assert d.feed("step.other", 0.300) is None

    def test_queue_stall_detector(self):
        d = det.QueueStallDetector(patience=4)
        fired = [d.feed(queue_depth=3, occupancy=0.5) for _ in range(6)]
        assert any(a is not None
                   and a.kind == "serving_admission_stall"
                   for a in fired)
        d2 = det.QueueStallDetector(patience=4)
        assert all(d2.feed(queue_depth=3, occupancy=1.0) is None
                   for _ in range(6))

    def test_step_time_samples_containing_compiles_are_dropped(self):
        """A timing that contained a backend compile (fresh serving
        bucket, legitimate retrace) is not a steady-state sample: the
        bank must drop it instead of poisoning the baseline or firing
        a false regression — the compile is already first-class signal
        via compile.{count,ms}."""
        from apex_tpu.observability import device as dev

        reg = obs.configure()
        bank = reg.detectors
        tracker = dev.recompile_tracker()
        bank.feed_step_time("serving.prefill", 0.010)   # may be dropped
        for _ in range(6):                              # clean baseline
            bank.feed_step_time("serving.prefill", 0.010)
        # a compile lands inside the next (10x slower) observation:
        tracker.on_compile(0.090, "serving.prefill")
        bank.feed_step_time("serving.prefill", 0.100)
        assert not any(a.kind == "throughput_regression"
                       for a in bank.anomalies)
        # compile-free slowness STILL fires
        for _ in range(3):
            bank.feed_step_time("serving.prefill", 0.100)
        assert any(a.kind == "throughput_regression"
                   for a in bank.anomalies)

    def test_bank_fires_events_and_counter(self, tmp_path):
        path = tmp_path / "t.jsonl"
        reg = obs.configure(jsonl_path=str(path))
        for i in range(10):
            obs.record_step_metrics({"loss": 1.0, "step": i})
        obs.record_step_metrics({"loss": float("nan"), "step": 10})
        assert reg.counter("anomaly.count").value == 1
        obs.shutdown()
        recs = [json.loads(line) for line in open(path)]
        evs = [r for r in recs if r["type"] == "event"
               and r["name"] == "anomaly.nan_inf"]
        assert len(evs) == 1 and evs[0]["data"]["step"] == 10


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_injected_nan_train_loop_postmortem(self, tmp_path):
        """The acceptance scenario: a real amp.frontend train loop, a
        NaN injected mid-run, and a dump that names the first anomalous
        step."""
        from apex_tpu.amp.frontend import initialize, make_train_step
        from apex_tpu.amp.scaler import record_scaler_step
        from apex_tpu.optimizers import fused_adam

        dump_path = tmp_path / "flight.json"
        obs.configure(flight_recorder=str(dump_path), flight_steps=64)
        params = {"w": jnp.ones((8, 8), jnp.float32)}
        x = jnp.ones((2, 8), jnp.float32)
        # static loss scale: no settle-phase overflow skips, so
        # TrainState.step == loop index and the post-mortem step is
        # exact (dynamic-scale skip semantics are pinned elsewhere)
        init, step = make_train_step(
            lambda p, xx: jnp.mean((xx @ p["w"]) ** 2),
            fused_adam(lr=1e-3), initialize("O2", loss_scale=1.0),
            norm_telemetry=True)
        state = init(params)
        for i in range(8):
            if i == 5:
                # poison the params: every later loss/norm is non-finite
                state = state._replace(
                    master_params={"w": state.master_params["w"]
                                   * float("nan")})
            state, metrics = step(state, x)
            record_scaler_step(metrics)
            obs.record_step_metrics(metrics)
        assert dump_path.exists(), "no post-mortem dumped on anomaly"
        # strict JSON: jq / JSON.parse reject bare NaN tokens, and the
        # NaN incident is exactly the dump that must stay readable
        import re

        assert not re.search(r"\bNaN\b|\bInfinity\b",
                             open(dump_path).read())
        dump = json.load(open(dump_path))
        assert dump["reason"].startswith("anomaly:nan_inf")
        assert dump["first_anomaly"]["kind"] == "nan_inf"
        # steps 0..4 were clean; the poisoned step is the 6th (index 5)
        assert dump["first_anomalous_step"] == 5
        bad_keys = dump["first_anomaly"]["detail"]["keys"]
        assert "loss" in bad_keys or "grad_norm" in bad_keys
        steps = dump["steps"]
        assert steps and steps[-1]["step"] == 5
        # the ring holds the healthy history too (non-finite values
        # are stringified for strict-JSON dumps)
        assert any(isinstance(s["loss"], float)
                   and math.isfinite(s["loss"]) for s in steps)
        assert not any(isinstance(s["loss"], float)
                       and math.isnan(s["loss"]) for s in steps)

    def test_ring_buffer_is_bounded(self, tmp_path):
        obs.configure(flight_recorder=str(tmp_path / "f.json"),
                      flight_steps=16)
        for i in range(100):
            obs.record_step_metrics({"loss": 1.0, "step": i})
        rec = obs.registry().recorder
        assert len(rec.steps) == 16
        assert rec.steps[0]["step"] == 84 and rec.steps[-1]["step"] == 99

    def test_on_demand_dump_and_health_report(self, tmp_path):
        dump_path = tmp_path / "f.json"
        obs.configure(flight_recorder=str(dump_path))
        for i in range(4):
            obs.record_step_metrics(
                {"loss": 1.0 + i, "loss_scale": 1024.0, "step": i})
        rec = obs.registry().recorder
        out = rec.dump(reason="unit_test")
        assert out == str(dump_path)
        doc = json.load(open(dump_path))
        assert doc["reason"] == "unit_test"
        assert doc["dump_schema_version"] == 1
        assert [s["step"] for s in doc["steps"]] == [0, 1, 2, 3]
        assert "metrics_summary" in doc

        health = _load_tool("health_report")
        buf = io.StringIO()
        health.render_dump(doc, out=buf)
        text = buf.getvalue()
        assert "incident summary" in text
        assert "no anomalies recorded" in text
        assert "loss" in text

    def test_crash_excepthook_dumps(self, tmp_path):
        dump_path = tmp_path / "f.json"
        obs.configure(flight_recorder=str(dump_path))
        obs.record_step_metrics({"loss": 2.5, "step": 7})
        prev_hook = sys.excepthook
        try:
            sys.excepthook(RuntimeError, RuntimeError("boom"), None)
        finally:
            sys.excepthook = prev_hook
        doc = json.load(open(dump_path))
        assert doc["reason"] == "crash"
        assert doc["error"] == "RuntimeError: boom"
        assert doc["steps"][-1]["loss"] == 2.5
        obs.shutdown()
        # shutdown restores the hook it installed
        assert sys.excepthook is prev_hook or not hasattr(
            sys.excepthook, "__self__")

    def test_shutdown_preserves_the_incident_dump(self, tmp_path):
        """The anomaly-time dump brackets the incident; a run that
        outlives it must not have that window overwritten by the
        shutdown dump — the aftermath goes to a sibling .final file."""
        dump_path = tmp_path / "flight.json"
        obs.configure(flight_recorder=str(dump_path), flight_steps=8)
        for i in range(5):
            obs.record_step_metrics({"loss": 1.0, "step": i})
        obs.record_step_metrics({"loss": float("nan"), "step": 5})
        # the run survives the anomaly far past the ring size
        for i in range(6, 30):
            obs.record_step_metrics({"loss": 1.0, "step": i})
        obs.shutdown()
        incident = json.load(open(dump_path))
        assert incident["reason"] == "anomaly:nan_inf"
        assert incident["steps"][-1]["step"] == 5    # window preserved
        final = json.load(open(tmp_path / "flight.final.json"))
        assert final["reason"] == "shutdown_with_anomalies"
        assert final["steps"][-1]["step"] == 29

    def test_quiet_run_leaves_no_artifact(self, tmp_path):
        dump_path = tmp_path / "f.json"
        obs.configure(flight_recorder=str(dump_path))
        for i in range(5):
            obs.record_step_metrics({"loss": 1.0, "step": i})
        obs.shutdown()
        assert not dump_path.exists()


# ---------------------------------------------------------------------------
# recompilation + HBM accounting
# ---------------------------------------------------------------------------


class TestRuntimeAccounting:
    def test_forced_retrace_increments_compile_count(self):
        """The acceptance scenario: an intentional shape-change retrace
        shows up in compile.{count,ms} under the active label."""
        from apex_tpu.observability import device as dev

        reg = obs.configure()
        tracker = dev.recompile_tracker()
        assert tracker is not None, "configure() must install the tracker"
        f = jax.jit(lambda x: x * 2 + 1)
        # build inputs OUTSIDE the label: jnp.ones itself compiles a
        # tiny fill program and would pollute the labeled count
        a, b = jnp.ones((4,)), jnp.ones((9,))
        base = reg.counter("compile.count").value
        with dev.compile_label("retrace_unit"):
            f(a)
            f(a)      # cache hit: no compile
            f(b)      # shape change: forced retrace
        delta = reg.counter("compile.count").value - base
        assert delta == 2, f"expected 2 compiles (initial+retrace), {delta}"
        assert reg.counter("compile.retrace_unit.count").value == 2
        assert reg.counter("compile.ms").value >= 0
        row = tracker.summary()["by_label"]["retrace_unit"]
        assert row["count"] == 2 and row["ms"] > 0

    def test_compile_labels_nest_and_unlabeled_falls_back(self):
        from apex_tpu.observability import device as dev

        assert dev.current_compile_label() is None
        with dev.compile_label("outer"):
            assert dev.current_compile_label() == "outer"
            with dev.compile_label("inner"):
                assert dev.current_compile_label() == "inner"
            assert dev.current_compile_label() == "outer"
        assert dev.current_compile_label() is None

    def test_steptimer_attributes_warmup_compiles(self):
        from apex_tpu.observability import device as dev

        reg = obs.configure()

        @jax.jit
        def step(x):
            return x + 1

        x = jnp.zeros((3, 3))
        obs.StepTimer("unit_row", warmup=1, iters=2).time_call(step, x)
        assert reg.counter("compile.unit_row.count").value >= 1
        # nothing compiled inside the timed window
        assert reg.counter("compile.unit_row.retrace.count").value == 0
        assert dev.runtime_summary()["compile"]["by_label"][
            "unit_row"]["count"] >= 1

    def test_sample_device_memory_cpu_degrades_to_none(self):
        # CPU backends report no memory_stats: the helper returns None
        # and sets no gauges rather than exploding
        reg = obs.configure()
        out = obs.sample_device_memory()
        if out is None:
            assert reg.gauge("hbm.bytes_in_use").value is None
        else:       # a real accelerator in the loop: gauges must agree
            assert reg.gauge("hbm.bytes_in_use").value == pytest.approx(
                out["bytes_in_use"])

    def test_runtime_summary_shape(self):
        from apex_tpu.observability import device as dev

        dev.install_recompile_tracker()
        out = obs.runtime_summary()
        assert "compile" in out
        assert {"count", "ms", "by_label"} <= set(out["compile"])


# ---------------------------------------------------------------------------
# configure_from_env validation (ISSUE 4 satellite)
# ---------------------------------------------------------------------------


class TestEnvConfiguration:
    def test_all_documented_vars_round_trip(self, tmp_path):
        env = {
            "APEX_TPU_TELEMETRY": str(tmp_path / "t.jsonl"),
            "APEX_TPU_TELEMETRY_TRACE": str(tmp_path / "trace.json"),
            "APEX_TPU_TELEMETRY_FLIGHT": str(tmp_path / "f.json"),
            "APEX_TPU_TELEMETRY_FLIGHT_STEPS": "32",
            "APEX_TPU_TELEMETRY_DETECTORS": "1",
            "APEX_TPU_TELEMETRY_STDERR": "0",
            "APEX_TPU_TELEMETRY_PROFILER": "0",
        }
        reg = obs.configure_from_env(env)
        assert reg is not None
        assert reg.detectors is not None
        assert reg.recorder is not None
        assert reg.recorder.max_steps == 32
        kinds = {type(s).__name__ for s in reg.sinks}
        assert {"JsonlSink", "TraceSink"} <= kinds

    def test_nothing_set_stays_disabled(self):
        assert obs.configure_from_env({}) is None
        assert not obs.enabled()

    def test_malformed_bool_warns_with_var_name(self):
        with _capture_warnings() as warnings:
            reg = obs.configure_from_env(
                {"APEX_TPU_TELEMETRY_STDERR": "maybe"})
        assert reg is None      # malformed value falls back to default
        assert any("APEX_TPU_TELEMETRY_STDERR" in w for w in warnings)

    def test_malformed_int_warns_but_still_configures(self, tmp_path):
        with _capture_warnings() as warnings:
            reg = obs.configure_from_env({
                "APEX_TPU_TELEMETRY_FLIGHT": str(tmp_path / "f.json"),
                "APEX_TPU_TELEMETRY_FLIGHT_STEPS": "lots",
            })
        assert reg is not None          # the typo cost the option,
        assert reg.recorder is not None  # not the whole config
        assert reg.recorder.max_steps == 256
        assert any("APEX_TPU_TELEMETRY_FLIGHT_STEPS" in w
                   for w in warnings)

    def test_unknown_var_warns_with_var_name(self, tmp_path):
        with _capture_warnings() as warnings:
            obs.configure_from_env({
                "APEX_TPU_TELEMETRY": str(tmp_path / "t.jsonl"),
                "APEX_TPU_TELEMETRY_TRACEPATH": "typo.json",
            })
        assert any("APEX_TPU_TELEMETRY_TRACEPATH" in w for w in warnings)

    def test_detectors_can_be_disabled(self, tmp_path):
        reg = obs.configure_from_env({
            "APEX_TPU_TELEMETRY": str(tmp_path / "t.jsonl"),
            "APEX_TPU_TELEMETRY_DETECTORS": "0",
        })
        assert reg is not None and reg.detectors is None

    def test_env_table_documents_every_var(self):
        """docs/observability.md must mention every ENV_VARS entry —
        the 'document in one place' satellite is enforceable."""
        from apex_tpu.observability.metrics import ENV_PREFIX, ENV_VARS

        doc = open(os.path.join(REPO, "docs", "observability.md")).read()
        for suffix in ENV_VARS:
            assert ENV_PREFIX + suffix in doc, (
                f"{ENV_PREFIX + suffix} missing from docs/observability.md")
