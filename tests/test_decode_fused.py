"""ops/decode_step.py: fused decode-layer megakernel (ISSUE 17).

The tentpole acceptance pins: the Pallas rope + quantized-KV paged
attention + output-projection kernel must match the XLA reference
composition at ragged lengths that straddle block boundaries
(``len % block_size ∈ {0, 1, block_size−1}``) across MHA/GQA/MQA and
both ``cache_wire`` forms, fp32 tight and bf16 loose; ``generate()``
routed through the kernel must be greedy token-identical to the
reference route on both cache layouts, composing with speculative
decoding and the serving engine's preempt→resume cycle; and the
``APEX_TPU_DECODE_FUSED`` route must fail loudly by name on a bad
value."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import generate
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.ops.decode_step import (
    decode_layer_reference, fused_decode_layer, route_decode_fused)
from apex_tpu.serving.paged_cache import quantize_kv


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


def _case(rng, *, b, mb, nb, bs, nh, g, dh, lens, h_out=None,
          dtype=jnp.float32, rope=True, quant=False):
    """Random pools + per-row block tables + rope rows + projection —
    the full fused-layer argument set (the paged-attention ``_case``
    plus the layer-level pieces)."""
    h_out = nh * dh if h_out is None else h_out
    kp = jnp.asarray(rng.randn(nb, bs, g, dh), dtype)
    vp = jnp.asarray(rng.randn(nb, bs, g, dh), dtype)
    q = jnp.asarray(rng.randn(b, nh, dh), dtype)
    w = jnp.asarray(rng.randn(nh * dh, h_out) / (nh * dh) ** 0.5, dtype)
    order = rng.permutation(nb)
    tbl = np.full((b, mb), nb + 3, np.int32)      # sentinel past nb
    used = 0
    for i, n in enumerate(lens):
        k = -(-n // bs)
        tbl[i, :k] = order[used: used + k]
        used += k
    assert used <= nb, "test geometry needs more pool blocks"
    kw = dict(k_scale=None, v_scale=None)
    if quant:
        kp, kw["k_scale"] = quantize_kv(kp)
        vp, kw["v_scale"] = quantize_kv(vp)
    if rope:
        theta = rng.uniform(-np.pi, np.pi, (b, dh))
        kw["rope_cos"] = jnp.asarray(np.cos(theta), dtype)
        kw["rope_sin"] = jnp.asarray(np.sin(theta), dtype)
    return (q, kp, vp, jnp.asarray(tbl), jnp.asarray(lens, jnp.int32),
            w), kw


class TestKernelParity:
    """Kernel (interpret path, same as every other Pallas suite here)
    vs the XLA reference at boundary-straddling ragged lengths."""

    @pytest.mark.parametrize("quant", [False, True],
                             ids=["native", "int8"])
    @pytest.mark.parametrize("nh,g", [(4, 4), (8, 2), (4, 1)],
                             ids=["mha", "gqa", "mqa"])
    def test_block_boundary_lengths_fp32(self, nh, g, quant,
                                         monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
        bs = 8
        rng = np.random.RandomState(0)
        args, kw = _case(rng, b=4, mb=4, nb=16, bs=bs, nh=nh, g=g,
                         dh=64, lens=[2 * bs, 2 * bs + 1, 3 * bs - 1, 1],
                         quant=quant)
        ref = decode_layer_reference(*args, **kw)
        ker = fused_decode_layer(*args, backend="kernel", **kw)
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_bf16_parity_loose(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
        bs = 8
        rng = np.random.RandomState(1)
        args, kw = _case(rng, b=3, mb=3, nb=12, bs=bs, nh=4, g=2,
                         dh=64, lens=[bs, bs + 1, 2 * bs - 1],
                         dtype=jnp.bfloat16)
        ref = decode_layer_reference(*args, **kw)
        ker = fused_decode_layer(*args, backend="kernel", **kw)
        assert ker.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(ker, np.float32), np.asarray(ref, np.float32),
            atol=2e-2, rtol=2e-2)

    def test_no_rope_path(self, monkeypatch):
        """rope_cos/sin=None skips rotation in BOTH paths (the
        learned-position configs)."""
        monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
        rng = np.random.RandomState(2)
        args, kw = _case(rng, b=2, mb=2, nb=6, bs=4, nh=4, g=4, dh=64,
                         lens=[5, 8], rope=False)
        ref = decode_layer_reference(*args, **kw)
        ker = fused_decode_layer(*args, backend="kernel", **kw)
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_narrow_projection(self, monkeypatch):
        """h_out != nh*dh — the projection tile is not square."""
        monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
        rng = np.random.RandomState(3)
        args, kw = _case(rng, b=2, mb=2, nb=6, bs=4, nh=4, g=2, dh=64,
                         lens=[4, 7], h_out=96)
        ref = decode_layer_reference(*args, **kw)
        ker = fused_decode_layer(*args, backend="kernel", **kw)
        assert ker.shape == (2, 96)
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestRouting:
    def test_bad_backend_raises_by_name(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_DECODE_FUSED", "nonsense")
        with pytest.raises(ValueError, match="backend"):
            route_decode_fused(None)
        with pytest.raises(ValueError, match="backend"):
            route_decode_fused("fused")

    def test_env_routes(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_DECODE_FUSED", "kernel")
        assert route_decode_fused(None) == "kernel"
        monkeypatch.setenv("APEX_TPU_DECODE_FUSED", "reference")
        assert route_decode_fused(None) == "reference"
        # explicit argument wins over the env
        assert route_decode_fused("kernel") == "kernel"

    def test_auto_follows_interpret(self, monkeypatch):
        monkeypatch.delenv("APEX_TPU_DECODE_FUSED", raising=False)
        monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
        assert route_decode_fused("auto") == "kernel"
        monkeypatch.delenv("APEX_TPU_PALLAS_INTERPRET", raising=False)
        from apex_tpu.ops.decode_step import on_tpu
        if not on_tpu():
            assert route_decode_fused("auto") == "reference"


class TestShapeChecks:
    def _args(self):
        rng = np.random.RandomState(4)
        return _case(rng, b=2, mb=2, nb=6, bs=4, nh=4, g=4, dh=64,
                     lens=[4, 6])

    def test_quantized_weight_slab_rejected(self):
        args, kw = self._args()
        q, kp, vp, tbl, lens, w = args
        slab = {"wire": w, "scales": jnp.ones((1,))}
        with pytest.raises(ValueError, match="quantized weight slab"):
            fused_decode_layer(q, kp, vp, tbl, lens, slab, **kw)

    def test_wrong_projection_shape(self):
        args, kw = self._args()
        q, kp, vp, tbl, lens, w = args
        with pytest.raises(ValueError, match="w_proj"):
            fused_decode_layer(q, kp, vp, tbl, lens, w[:-1], **kw)

    def test_rope_rows_must_pair_and_match(self):
        args, kw = self._args()
        q, kp, vp, tbl, lens, w = args
        with pytest.raises(ValueError, match="together"):
            fused_decode_layer(q, kp, vp, tbl, lens, w,
                               rope_cos=kw["rope_cos"])
        with pytest.raises(ValueError, match="rope rows"):
            fused_decode_layer(q, kp, vp, tbl, lens, w,
                               rope_cos=kw["rope_cos"][:1],
                               rope_sin=kw["rope_sin"][:1])

    def test_odd_rotary_dim(self):
        args, kw = self._args()
        q, kp, vp, tbl, lens, w = args
        with pytest.raises(ValueError, match="rotary dim"):
            fused_decode_layer(q, kp, vp, tbl, lens, w,
                               rope_cos=kw["rope_cos"][:, :3],
                               rope_sin=kw["rope_sin"][:, :3])


class TestGenerateTokenIdentity:
    """The end-to-end acceptance pin: generate() routed through the
    fused kernel is greedy token-identical to the reference route on
    both cache layouts and both cache_wire forms."""

    def _run(self, monkeypatch, route, **gen_kw):
        cfg = _cfg(position_embedding_type="rope", num_query_groups=2)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        lens = [3, 9, 6]
        batch = np.zeros((3, max(lens)), np.int32)
        for i, n in enumerate(lens):
            batch[i, :n] = rng.randint(0, cfg.vocab_size, (n,))
        monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
        monkeypatch.setenv("APEX_TPU_DECODE_FUSED", route)
        return np.asarray(generate(
            params, jnp.asarray(batch), cfg, max_new_tokens=7,
            prompt_lens=jnp.asarray(lens), **gen_kw))

    @pytest.mark.parametrize("gen_kw", [
        dict(cache_layout="paged", block_size=4),
        dict(cache_layout="paged", block_size=4, cache_wire="int8"),
        dict(cache_layout="contiguous"),
    ], ids=["paged-native", "paged-int8", "contiguous"])
    def test_fused_matches_reference(self, monkeypatch, gen_kw):
        want = self._run(monkeypatch, "reference", **gen_kw)
        got = self._run(monkeypatch, "kernel", **gen_kw)
        np.testing.assert_array_equal(got, want)

    def test_spec_decode_composes(self, monkeypatch):
        """Fused route under speculative decoding: the verify forward
        stays unfused (multi-token), the per-token decode fuses —
        greedy output is still token-identical."""
        kw = dict(cache_layout="paged", block_size=4, spec="ngram")
        want = self._run(monkeypatch, "reference", **kw)
        got = self._run(monkeypatch, "kernel", **kw)
        np.testing.assert_array_equal(got, want)


class TestServingComposition:
    def test_preempt_resume_fused_parity(self, monkeypatch):
        """Fused decode inside the serving engine survives a
        preempt→resume cycle token-for-token against solo generate()
        on the SAME route."""
        from apex_tpu.serving import ServingEngine

        monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
        monkeypatch.setenv("APEX_TPU_DECODE_FUSED", "kernel")
        cfg = _cfg(position_embedding_type="rope", num_query_groups=2)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(7)
        p1 = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        p2 = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        # 6 blocks of 4: both admit, both outgrow the pool mid-decode
        # -> the youngest gets preempted and later resumes
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,),
                               cache_layout="paged", block_size=4,
                               num_blocks=6, reserve_blocks=0)
        assert engine.stats()["decode_fused"] == "kernel"
        resps = engine.run([dict(prompt=p1, max_new_tokens=10),
                            dict(prompt=p2, max_new_tokens=10)])
        for r, p in zip(resps, (p1, p2)):
            solo = np.asarray(generate(
                params, jnp.asarray(p[None]), cfg,
                max_new_tokens=10))[0, 6:]
            np.testing.assert_array_equal(
                r.tokens, solo, err_msg=f"request {r.request_id}")
        assert engine.idle
