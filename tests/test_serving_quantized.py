"""int8-at-rest paged serving (ISSUE 14): pool round-trips through
share/CoW/preempt→resume, cross-layout KV handoff, the byte-parity
admission default, the serving.cache_bytes{dtype=} gauges, and the
spec-decode accept-rate gate — the documented accuracy contract
(deterministic, first-token-identical, trajectory MAY diverge) pinned
rather than hidden."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import (
    extract_kv, generate, init_kv_cache, inject_kv, prefill,
    sample_logits)
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.serving import ServingEngine, dequantize_kv, quantize_kv


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestQuantizeKV:
    def test_round_trip_error_bounded(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(3, 8, 2, 16), jnp.float32)
        wire, scale = quantize_kv(x)
        assert wire.dtype == jnp.int8 and scale.shape == (3, 8, 2)
        deq = dequantize_kv(wire, scale)
        bound = np.asarray(scale)[..., None] / 2 + 1e-7
        assert (np.abs(np.asarray(deq - x)) <= bound).all()

    def test_zero_rows_exact(self):
        wire, scale = quantize_kv(jnp.zeros((2, 4, 8)))
        np.testing.assert_array_equal(np.asarray(scale), 1.0)
        np.testing.assert_array_equal(
            np.asarray(dequantize_kv(wire, scale)), 0.0)

    def test_pool_forms(self, model):
        cfg, _ = model
        from apex_tpu.serving import init_paged_pool

        pool = init_paged_pool(cfg, 4, 8, cache_wire="int8")
        assert pool["k"].dtype == jnp.int8
        assert pool["k_scale"].shape == pool["k"].shape[:-1]
        np.testing.assert_array_equal(np.asarray(pool["k_scale"]), 1.0)
        with pytest.raises(ValueError, match="cache_wire"):
            init_paged_pool(cfg, 4, 8, cache_wire="fp8")
        with pytest.raises(ValueError, match="paged-pool form"):
            init_kv_cache(cfg, 2, 16, cache_wire="int8")


class TestEngineLifecycle:
    def test_run_mixed_and_ledger_clean(self, model):
        cfg, params = model
        rng = np.random.RandomState(1)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,), cache_layout="paged",
                               block_size=8, cache_wire="int8")
        resps = engine.run([
            dict(prompt=rng.randint(0, 128, (5,)), max_new_tokens=4),
            dict(prompt=rng.randint(0, 128, (7,)), max_new_tokens=6,
                 temperature=0.8),
            dict(prompt=rng.randint(0, 128, (3,)), max_new_tokens=3),
        ])
        assert [r.request_id for r in resps] == [0, 1, 2]
        assert [r.tokens.size for r in resps] == [4, 6, 3]
        assert engine.idle
        assert engine.stats()["blocks_in_use"] == 0
        assert engine.stats()["cache_wire"] == "int8"

    def test_wire_requires_paged(self, model):
        cfg, params = model
        with pytest.raises(ValueError, match="paged"):
            ServingEngine(params, cfg, max_slots=2, max_len=32,
                          cache_wire="int8")

    def test_byte_parity_default_blocks(self, model):
        """At the default num_blocks the int8 pool costs no more HBM
        than the native pool would, while holding ~itemsize/(1+4/dh)
        times the blocks — the admission multiple's substrate."""
        cfg, params = model
        kw = dict(max_slots=2, max_len=64, cache_layout="paged",
                  block_size=8, cache_dtype=jnp.bfloat16)
        native = ServingEngine(params, cfg, **kw)
        quant = ServingEngine(params, cfg, cache_wire="int8", **kw)
        sn, sq = native.stats(), quant.stats()
        assert sq["cache_bytes"] <= sn["cache_bytes"]
        # dh=16 here: 2 / (1 + 4/16) = 1.6x the blocks
        assert sq["num_blocks"] > int(1.5 * sn["num_blocks"])

    def test_deterministic_and_first_token_matches_native(self, model):
        """The accuracy contract, pinned: two int8 runs are identical
        (quantization is deterministic); the FIRST token equals the
        native pool's (prefill logits precede any quantization); the
        rest of the trajectory is allowed to diverge — documented in
        docs/inference.md, not asserted equal here."""
        cfg, params = model
        rng = np.random.RandomState(2)
        prompt = rng.randint(0, 128, (9,)).astype(np.int32)

        def run(wire):
            eng = ServingEngine(params, cfg, max_slots=1, max_len=32,
                                prompt_buckets=(16,),
                                cache_layout="paged", block_size=8,
                                cache_wire=wire)
            return eng.run([dict(prompt=prompt, max_new_tokens=8)])[0]

        a, b = run("int8"), run("int8")
        np.testing.assert_array_equal(a.tokens, b.tokens)
        native = run(None)
        assert a.tokens[0] == native.tokens[0]

    def test_cache_bytes_gauges_tagged_by_dtype(self, model):
        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        reg = telemetry.configure()
        try:
            engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                                   prompt_buckets=(8,),
                                   cache_layout="paged", block_size=8,
                                   cache_wire="int8")
            engine.run([dict(prompt=np.arange(5), max_new_tokens=2)])
            bytes_g = reg.gauge("serving.cache_bytes",
                                {"dtype": "int8"})
            assert bytes_g.value == engine.stats()["cache_bytes"]
            cap_g = reg.gauge("serving.cache_capacity_tokens",
                              {"dtype": "int8"})
            assert cap_g.value == engine.num_blocks * engine.block_size
            hw_g = reg.gauge("serving.cache_blocks_hw",
                             {"dtype": "int8"})
            assert hw_g.value >= 1
        finally:
            telemetry.shutdown()


class TestPrefixSharingAndCoW:
    def test_identical_prompts_share_quantized_blocks(self, model):
        """Quantization is deterministic, so the chained-digest prefix
        sharing is unchanged on the int8 pool: later identical prompts
        map the SAME wire blocks and all sharers emit the same
        tokens."""
        cfg, params = model
        rng = np.random.RandomState(3)
        sysp = rng.randint(0, 128, (17,)).astype(np.int32)
        engine = ServingEngine(params, cfg, max_slots=3, max_len=32,
                               prompt_buckets=(32,),
                               cache_layout="paged", block_size=8,
                               cache_wire="int8")
        for _ in range(3):
            engine.submit(sysp, max_new_tokens=4)
        engine._admit()
        st = engine.stats()
        assert st["prefix_shared_blocks"] == 4, st
        assert st["blocks_in_use"] == 5, st
        resps = engine.run([])
        for r in resps[1:]:
            np.testing.assert_array_equal(r.tokens, resps[0].tokens)
        assert engine.stats()["blocks_in_use"] == 0

    def test_cow_copy_moves_wire_and_scales_together(self, model):
        """The ensure_private CoW edge on a quantized pool: copying a
        block's payload means copying wire AND scale rows — attention
        over the copy is bitwise what it was over the original."""
        from apex_tpu.ops.paged_attention import ragged_paged_attention
        from apex_tpu.serving import BlockManager, init_paged_pool

        cfg, _ = model
        rng = np.random.RandomState(4)
        pool = init_paged_pool(cfg, 4, 8, cache_wire="int8")
        # fill block 0 with real quantized content
        kf = jnp.asarray(rng.randn(cfg.num_layers, 8, cfg.kv_groups,
                                   cfg.kv_channels), jnp.float32)
        kw_, ks_ = quantize_kv(kf)
        pool["k"] = pool["k"].at[:, 0].set(kw_)
        pool["k_scale"] = pool["k_scale"].at[:, 0].set(ks_)
        pool["v"] = pool["v"].at[:, 0].set(kw_)
        pool["v_scale"] = pool["v_scale"].at[:, 0].set(ks_)
        mgr = BlockManager(4, 8)
        blk = mgr.alloc()
        mgr.incref(blk)                          # shared -> CoW copies
        fresh, copied = mgr.ensure_private(blk)
        assert copied and fresh != blk
        # the CoW device copy: wire + scales move together
        for side in ("k", "v"):
            pool[side] = pool[side].at[:, fresh].set(pool[side][:, blk])
            pool[f"{side}_scale"] = pool[f"{side}_scale"].at[
                :, fresh].set(pool[f"{side}_scale"][:, blk])
        q = jnp.asarray(rng.randn(1, cfg.num_attention_heads,
                                  cfg.kv_channels), jnp.float32)
        lens = jnp.asarray([8], jnp.int32)
        out_orig = ragged_paged_attention(
            q, pool["k"][0], pool["v"][0],
            jnp.asarray([[blk]], jnp.int32), lens,
            k_scale=pool["k_scale"][0], v_scale=pool["v_scale"][0])
        out_copy = ragged_paged_attention(
            q, pool["k"][0], pool["v"][0],
            jnp.asarray([[fresh]], jnp.int32), lens,
            k_scale=pool["k_scale"][0], v_scale=pool["v_scale"][0])
        np.testing.assert_array_equal(np.asarray(out_orig),
                                      np.asarray(out_copy))


class TestPreemptResume:
    def test_preempt_resume_completes_with_clean_ledger(self, model):
        """int8-pool preempt→resume: mechanics pinned (everything
        completes to budget, blocks all return, deterministic across
        runs).  Token-identity with the un-preempted run is NOT
        asserted: resume replays through full-precision prefill where
        decode had read quantized K/V — the documented int8-at-rest
        divergence window (docs/inference.md); the native pool's
        token-identity pin lives in test_serving_paged.py."""
        cfg, params = model
        rng = np.random.RandomState(5)
        p1 = rng.randint(0, 128, (6,)).astype(np.int32)
        p2 = rng.randint(0, 128, (6,)).astype(np.int32)

        def run():
            eng = ServingEngine(params, cfg, max_slots=2, max_len=32,
                                prompt_buckets=(8,),
                                cache_layout="paged", block_size=4,
                                num_blocks=6, reserve_blocks=0,
                                cache_wire="int8")
            out = eng.run([dict(prompt=p1, max_new_tokens=10),
                           dict(prompt=p2, max_new_tokens=10)])
            return eng, out

        eng, resps = run()
        assert eng.stats()["preemptions"] >= 1   # the pool forced it
        assert sorted(r.request_id for r in resps) == [0, 1]
        assert all(r.tokens.size == 10 for r in resps)
        assert eng.stats()["blocks_in_use"] == 0
        _, again = run()
        for a, b in zip(resps, again):
            np.testing.assert_array_equal(a.tokens, b.tokens)


class TestHandoff:
    def test_cross_layout_into_int8_engine(self, model):
        """Remote contiguous-native prefill → extract → inject into an
        int8 paged engine: decodes to completion, token-identical to
        the same engine prefilling locally (injection quantizes the
        same K/V the local prefill would have)."""
        cfg, params = model
        rng = np.random.RandomState(6)
        prompt = rng.randint(0, 128, (6,)).astype(np.int32)
        lg, cache = prefill(params, jnp.asarray(prompt[None]), cfg)
        k, v = extract_kv(cache, 6)
        first = int(np.asarray(
            sample_logits(lg, jax.random.PRNGKey(0)))[0])

        def engine():
            return ServingEngine(params, cfg, max_slots=2, max_len=32,
                                 prompt_buckets=(8,),
                                 cache_layout="paged", block_size=4,
                                 cache_wire="int8")

        eng = engine()
        eng.submit_prefilled(prompt, np.asarray(k), np.asarray(v),
                             first, max_new_tokens=6)
        got = eng.run([])[0]
        want = engine().run(
            [dict(prompt=prompt, max_new_tokens=6)])[0]
        np.testing.assert_array_equal(got.tokens, want.tokens)
        assert eng.stats()["blocks_in_use"] == 0

    def test_int8_pool_extract_dequantizes_float(self, model):
        """extract_kv off the quantized pool ships FLOAT K/V within
        the quantization budget of the native extraction, and the
        inject round-trip through a second int8 cache is near-lossless
        (re-quantizing dequantized values re-derives the scale, so a
        1-ulp wobble is possible — bounded far below the quantization
        step itself)."""
        cfg, params = model
        rng = np.random.RandomState(7)
        prompt = jnp.asarray(rng.randint(0, 128, (1, 9)), jnp.int32)
        cache_n = init_kv_cache(cfg, 1, 16, cache_layout="paged",
                                block_size=4)
        cache_q = init_kv_cache(cfg, 1, 16, cache_layout="paged",
                                block_size=4, cache_wire="int8")
        _, cache_n = prefill(params, prompt, cfg, cache=cache_n)
        _, cache_q = prefill(params, prompt, cfg, cache=cache_q)
        kn, vn = extract_kv(cache_n, 9)
        kq, vq = extract_kv(cache_q, 9)
        assert kq.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(kq), np.asarray(kn),
                                   atol=5e-2, rtol=5e-2)
        cache_q2 = init_kv_cache(cfg, 1, 16, cache_layout="paged",
                                 block_size=4, cache_wire="int8")
        cache_q2 = inject_kv(cache_q2, kq, vq)
        k2, v2 = extract_kv(cache_q2, 9)
        np.testing.assert_allclose(np.asarray(k2), np.asarray(kq),
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(v2), np.asarray(vq),
                                   atol=1e-6, rtol=1e-6)

    def test_int8_cache_to_contiguous_engine(self, model):
        """The reverse direction: extract off an int8 paged cache,
        inject into a contiguous engine — the handoff contract is
        float K/V, so the wire layer never sees the pool form."""
        cfg, params = model
        rng = np.random.RandomState(8)
        prompt = rng.randint(0, 128, (6,)).astype(np.int32)
        cache_q = init_kv_cache(cfg, 1, 16, cache_layout="paged",
                                block_size=4, cache_wire="int8")
        lg, cache_q = prefill(params, jnp.asarray(prompt[None]), cfg,
                              cache=cache_q)
        k, v = extract_kv(cache_q, 6)
        first = int(np.asarray(
            sample_logits(lg, jax.random.PRNGKey(0)))[0])
        eng = ServingEngine(params, cfg, max_slots=2, max_len=32,
                            prompt_buckets=(8,))
        eng.submit_prefilled(prompt, np.asarray(k), np.asarray(v),
                             first, max_new_tokens=5)
        resps = eng.run([])
        assert resps[0].tokens.size == 5


class TestSpecAcceptGate:
    def test_accept_rate_delta_bounded(self, model):
        """The ISSUE 14 quality gate: the n-gram accept rate over the
        int8 pool stays within ACCEPT_RATE_GATE of the native pool —
        the cheap proxy for distribution drift (the same constant
        bench.py --cache-dtype gates on)."""
        from bench import ACCEPT_RATE_GATE
        from apex_tpu.models.speculative import SpecConfig, \
            spec_generate

        cfg, params = model
        rng = np.random.RandomState(9)
        pattern = rng.randint(0, 128, (4,))
        prompt = jnp.asarray(np.tile(pattern, (2, 4)), jnp.int32)
        rates = {}
        for wire in (None, "int8"):
            _, stats = spec_generate(
                params, prompt, cfg, spec=SpecConfig(k=4),
                max_new_tokens=16, cache_layout="paged", block_size=8,
                cache_wire=wire)
            rates[wire] = (stats["accepted_tokens"]
                           / max(stats["draft_tokens"], 1))
        assert abs(rates[None] - rates["int8"]) <= ACCEPT_RATE_GATE, \
            rates

    def test_spec_engine_over_int8_pool(self, model):
        """A spec-enabled engine on the quantized pool: multi-token
        polls, budget-exact completion, clean ledger, deterministic."""
        cfg, params = model
        rng = np.random.RandomState(10)
        prompt = rng.randint(0, 128, (8,)).astype(np.int32)

        def run():
            eng = ServingEngine(params, cfg, max_slots=2, max_len=48,
                                prompt_buckets=(8,),
                                cache_layout="paged", block_size=8,
                                cache_wire="int8", spec="ngram")
            return eng, eng.run([dict(prompt=prompt,
                                      max_new_tokens=10)])

        eng, resps = run()
        assert resps[0].tokens.size == 10
        assert resps[0].decode_steps <= 10   # spec amortization
        assert eng.stats()["blocks_in_use"] == 0
        _, again = run()
        np.testing.assert_array_equal(resps[0].tokens, again[0].tokens)
