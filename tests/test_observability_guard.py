"""Grep-based guard: instrumentation must ride the no-op fast path.

The zero-overhead-when-disabled invariant (ISSUE 1, re-asserted by
ISSUE 4) is structural: every instrumented call site in ``apex_tpu/``
must reach telemetry through one of

- the module-level helpers (``_telemetry.counter(...)`` /
  ``gauge`` / ``histogram`` / ``event`` / ``set_step`` /
  ``record_step_metrics``), which embed the ``is None`` check; or
- an explicit bind-and-check: ``reg = _telemetry.registry()`` then
  ``if reg is None: return`` / ``if reg is not None:``.

What breaks it — and what this test greps for — is the *unconditional
chained* form ``registry().counter(...)`` (an AttributeError when
disabled, an allocation-per-call when enabled-by-accident), direct
``MetricsRegistry(...)`` construction outside the observability
package (a second registry dodges configure/shutdown and the fast
path), reaching into the private ``_REGISTRY`` global, and hot-path
device sampling (``sample_device_memory``) without an ``enabled()``
gate.  Source-text enforcement keeps the invariant reviewable: a new
subsystem cannot silently regress it without editing this test.
"""

import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "apex_tpu")
OBS_DIR = os.path.join(PKG, "observability")

# chained registry().<metric>(...) — bypasses the bind-and-check idiom
_CHAINED = re.compile(
    r"registry\(\)\s*\.\s*"
    r"(counter|gauge|histogram|sketch|event|observe_span|set_step"
    r"|summary|snapshot)\b")
# the live exporter (ISSUE 7) must only ever be imported lazily inside
# configure(export_port=...): a module-level import would load HTTP
# machinery on the unconfigured path (tests/test_exporter.py asserts
# the runtime side — no thread, no module — from a fresh process)
_EXPORTER_IMPORT = re.compile(
    r"^(from\s+apex_tpu\.observability\.exporter\s+import"
    r"|import\s+apex_tpu\.observability\.exporter)\b")
# a second MetricsRegistry outside the observability package
_DIRECT_REGISTRY = re.compile(r"\bMetricsRegistry\s*\(")
# the private module global
_PRIVATE_GLOBAL = re.compile(r"\b_REGISTRY\b")
# device-memory sampling: a real (if cheap) runtime query per call —
# hot paths must gate it
_MEM_SAMPLE = re.compile(r"\bsample_device_memory\s*\(")
_MEM_GATE = re.compile(r"enabled\(\)|is not None|is None|emit=False")
# the speculative-decoding counters (ISSUE 8): any string-literal use
# of a generate.spec.* name must ride the module-level counter helper
# on the same statement — a bare registry hop or a renamed copy would
# fork the accept-rate accounting telemetry_report/serve_dash read
_SPEC_COUNTER = re.compile(r"[\"']generate\.spec\.")
_SPEC_HELPER = re.compile(r"_telemetry\s*\.\s*counter\s*\(")
# the expert-parallel MoE telemetry (ISSUE 10): every moe.* metric
# touch must ride a module-level helper on the same statement — the
# dispatch-byte/ring-hop counters feed telemetry_report's MoE summary
# and the moe_ep dryrun gate's wire-ratio assertion, so a second
# (unguarded) access idiom would fork that accounting
_MOE_METRIC = re.compile(r"[\"']moe\.")
_MOE_HELPER = re.compile(r"_telemetry\s*\.\s*(counter|gauge)\s*\(")
# the checkpoint telemetry (ISSUE 11): every checkpoint counter/gauge
# touch must ride a module-level helper on the same statement — the
# save/byte/rollback accounting feeds telemetry_report's checkpoint
# summary and the bench --ckpt overhead row (span names
# checkpoint.save/restore/blocking go through observe_span under
# bind-and-check and are not name-matched here)
_CKPT_METRIC = re.compile(
    r"[\"']checkpoint\.(saves|bytes|restores|rollbacks|overlap_ratio)")
_CKPT_HELPER = re.compile(r"_telemetry\s*\.\s*(counter|gauge)\s*\(")


def _py_files():
    for root, _dirs, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for fn in files:
            if fn.endswith(".py"):
                yield os.path.join(root, fn)


def _in_obs(path: str) -> bool:
    return os.path.abspath(path).startswith(os.path.abspath(OBS_DIR))


def test_no_unconditional_chained_registry_calls():
    offenders = []
    for path in _py_files():
        if _in_obs(path):
            continue   # the package itself owns the registry internals
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                if _CHAINED.search(line):
                    offenders.append(f"{path}:{lineno}: {line.strip()}")
    assert not offenders, (
        "instrumented call sites must bind-and-check "
        "(reg = registry(); if reg is None: ...) or use the "
        "module-level helpers — unconditional registry().<metric>() "
        "bypasses the no-op fast path:\n" + "\n".join(offenders))


def test_no_direct_metricsregistry_construction():
    offenders = []
    for path in _py_files():
        if _in_obs(path):
            continue
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                if _DIRECT_REGISTRY.search(line) and "import" not in line:
                    offenders.append(f"{path}:{lineno}: {line.strip()}")
    assert not offenders, (
        "construct registries via observability.configure() only — a "
        "direct MetricsRegistry() dodges configure/shutdown and the "
        "module-level fast path:\n" + "\n".join(offenders))


def test_no_private_registry_global_access():
    offenders = []
    for path in _py_files():
        if os.path.basename(path) == "metrics.py" and _in_obs(path):
            continue   # the owner
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                if _PRIVATE_GLOBAL.search(line):
                    offenders.append(f"{path}:{lineno}: {line.strip()}")
    assert not offenders, (
        "_REGISTRY is private to observability.metrics; go through "
        "registry()/enabled():\n" + "\n".join(offenders))


def test_device_memory_sampling_is_gated():
    """``sample_device_memory()`` outside the observability package
    must sit within two lines of an ``enabled()`` / bind-and-check
    gate (or pass ``emit=False``, the caller-owns-it form)."""
    offenders = []
    for path in _py_files():
        if _in_obs(path):
            continue
        with open(path) as f:
            lines = f.readlines()
        for i, line in enumerate(lines):
            if not _MEM_SAMPLE.search(line):
                continue
            if "import" in line:
                continue
            context = "".join(lines[max(0, i - 2): i + 1])
            if not _MEM_GATE.search(context):
                offenders.append(f"{path}:{i + 1}: {line.strip()}")
    assert not offenders, (
        "gate device-memory sampling on enabled() in hot paths:\n"
        + "\n".join(offenders))


def test_exporter_import_is_module_level_nowhere():
    """The exporter module must never be imported at module level
    anywhere in ``apex_tpu/`` (``configure`` imports it lazily, inside
    the ``export_port is not None`` branch): a top-level import would
    pay for the HTTP server machinery — and open the door to a stray
    socket — on every unconfigured ``import apex_tpu``."""
    offenders = []
    for path in _py_files():
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                if _EXPORTER_IMPORT.search(line):   # ^-anchored =
                    offenders.append(                # module level only
                        f"{path}:{lineno}: {line.strip()}")
    assert not offenders, (
        "import the exporter lazily inside configure(export_port=...) "
        "only:\n" + "\n".join(offenders))


def test_unconfigured_engine_starts_no_exporter_thread():
    """ISSUE 7's zero-overhead extension, runtime side: a fresh
    process that imports the observability package AND drives nothing
    through configure() must have no exporter thread and no exporter
    module in sys.modules."""
    import subprocess
    import sys

    snippet = (
        "import sys, threading\n"
        "import apex_tpu.observability as obs\n"
        "assert obs.registry() is None\n"
        "assert 'apex_tpu.observability.exporter' not in sys.modules\n"
        "assert not [t for t in threading.enumerate()\n"
        "            if t.name == 'apex-tpu-telemetry-exporter']\n"
        "print('NO-THREAD')\n")
    out = subprocess.run([sys.executable, "-c", snippet],
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr
    assert "NO-THREAD" in out.stdout


def test_spec_counters_use_the_helper_only():
    """Every ``generate.spec.*`` counter touch in ``apex_tpu/`` must go
    through ``_telemetry.counter(...)`` on the same statement (the
    no-op-fast-path helper): the accept-rate numbers feed
    telemetry_report's spec summary and serve_dash, so a second access
    idiom would be a second (unguarded) accounting path."""
    offenders = []
    for path in _py_files():
        if _in_obs(path):
            continue
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                if not _SPEC_COUNTER.search(line):
                    continue
                if _SPEC_HELPER.search(line):
                    continue
                offenders.append(f"{path}:{lineno}: {line.strip()}")
    assert not offenders, (
        "generate.spec.* counters must be accessed via "
        "_telemetry.counter(...) on the same statement:\n"
        + "\n".join(offenders))


def test_moe_metrics_use_the_helpers_only():
    """Every ``moe.*`` metric touch in ``apex_tpu/`` must go through
    ``_telemetry.counter(...)`` / ``_telemetry.gauge(...)`` on the same
    statement (the no-op-fast-path helpers): the dispatch-byte and
    ring-hop counters are asserted against by the ``moe_ep`` dryrun
    phase and summarized by telemetry_report's MoE view."""
    offenders = []
    for path in _py_files():
        if _in_obs(path):
            continue
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                if not _MOE_METRIC.search(line):
                    continue
                if _MOE_HELPER.search(line):
                    continue
                offenders.append(f"{path}:{lineno}: {line.strip()}")
    assert not offenders, (
        "moe.* metrics must be accessed via _telemetry.counter(...)/"
        "_telemetry.gauge(...) on the same statement:\n"
        + "\n".join(offenders))


def test_checkpoint_metrics_use_the_helpers_only():
    """Every ``checkpoint.*`` counter/gauge touch in ``apex_tpu/`` must
    go through ``_telemetry.counter(...)`` / ``_telemetry.gauge(...)``
    on the same statement: the save/rollback accounting is what
    telemetry_report's checkpoint summary and the ``bench --ckpt``
    overhead row read, so a second access idiom would fork it."""
    offenders = []
    for path in _py_files():
        if _in_obs(path):
            continue
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                if not _CKPT_METRIC.search(line):
                    continue
                if _CKPT_HELPER.search(line):
                    continue
                offenders.append(f"{path}:{lineno}: {line.strip()}")
    assert not offenders, (
        "checkpoint.* metrics must be accessed via "
        "_telemetry.counter(...)/_telemetry.gauge(...) on the same "
        "statement:\n" + "\n".join(offenders))


def test_guard_patterns_actually_match():
    """The guard is only as good as its regexes: each must match its
    own anti-pattern (a regression here silently disables the guard)."""
    assert _CHAINED.search("reg = registry().counter('x')")
    assert _CHAINED.search("metrics.registry().gauge('x').set(1)")
    assert _CHAINED.search("registry().sketch('x').observe(1)")
    assert not _CHAINED.search("reg = _telemetry.registry()")
    assert _DIRECT_REGISTRY.search("r = MetricsRegistry(sinks)")
    assert _SPEC_COUNTER.search(
        'reg.counter("generate.spec.draft_tokens").inc()')
    assert _SPEC_HELPER.search(
        '_telemetry.counter("generate.spec.draft_tokens").inc(2)')
    assert not _SPEC_COUNTER.search(
        "the generate.spec.draft_tokens counter (docs)")
    assert _MOE_METRIC.search(
        'reg.counter("moe.dispatch_bytes").inc(8)')
    assert _MOE_HELPER.search(
        '_telemetry.gauge("moe.dropped_fraction").set(0.0)')
    assert _MOE_HELPER.search(
        '_telemetry.counter("moe.ring_hops").inc(7)')
    assert not _MOE_METRIC.search(
        "the moe.ring_hops invariant (docs)")
    assert _CKPT_METRIC.search(
        'reg.counter("checkpoint.rollbacks").inc()')
    assert _CKPT_HELPER.search(
        '_telemetry.gauge("checkpoint.overlap_ratio").set(r)')
    assert not _CKPT_METRIC.search(
        'reg.observe_span("checkpoint.save", bg_s)')
    assert _PRIVATE_GLOBAL.search("from x import _REGISTRY")
    assert _MEM_SAMPLE.search("sample_device_memory()")
    assert _EXPORTER_IMPORT.search(
        "from apex_tpu.observability.exporter import TelemetryExporter")
    assert not _EXPORTER_IMPORT.search(
        "        from apex_tpu.observability.exporter import "
        "TelemetryExporter")


@pytest.mark.parametrize("helper", [
    "counter", "gauge", "histogram", "sketch", "event", "set_step",
    "record_step_metrics",
])
def test_module_helpers_embed_the_check(helper):
    """Every helper the guard steers call sites toward must itself
    fast-path on the disabled registry (source-level: the function
    body reads _REGISTRY and checks None before doing work)."""
    import inspect

    from apex_tpu.observability import metrics

    src = inspect.getsource(getattr(metrics, helper))
    assert "_REGISTRY" in src and "is None" in src or "is not None" in src
