"""Guard: instrumentation must ride the no-op fast path — now a thin
runner over the Tier-A apexlint rules (ISSUE 12).

The zero-overhead-when-disabled invariant (ISSUE 1, re-asserted by
ISSUE 4) and its younger siblings (lazy exporter import — ISSUE 7;
``generate.spec.*`` / ``moe.*`` / ``checkpoint.*`` accounting through
the module helpers — ISSUEs 8/10/11) were enforced here as source
greps for eleven PRs.  The greps migrated to AST rules in
``apex_tpu/analysis/rules.py`` (single source of truth — the CLI
``tools/lint.py``, the ``static_audit`` dryrun phase and this tier-1
test all run the SAME rule objects); this file keeps its historical
test names so CI history stays comparable, and keeps the self-tests
that prove each rule still catches its own anti-pattern (a regression
there silently disables the guard).

Rule ids: APX101 chained registry, APX102 direct construction, APX103
private global, APX104 module-level exporter import, APX105
metric-prefix helpers, APX106 ungated memory sampling.  Full table:
docs/static_analysis.md.
"""

import os

import pytest

from apex_tpu.analysis import linter
from apex_tpu.analysis.rules import module_from_source, rules_by_id

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_RULES = rules_by_id()
_GUARD_IDS = ("APX101", "APX102", "APX103", "APX104", "APX105",
              "APX106")
# ONE parse+walk of the package for all six guard families (the
# per-rule split below is just bucketing) — keeps this tier-1 file at
# grep-era cost
_ALL = linter.lint(REPO, targets=("apex_tpu",),
                   rules=[_RULES[i] for i in _GUARD_IDS])


def _findings(rule_id: str, message_prefix: str = ""):
    out = [f for f in _ALL if f.rule == rule_id]
    if message_prefix:
        out = [f for f in out if f.message.startswith(message_prefix)]
    return out


def _fmt(findings):
    return "\n".join(f"{f.path}:{f.line}: {f.message}"
                     for f in findings)


def _fixture_findings(rule_id: str, source: str,
                      relpath: str = "apex_tpu/_fixture.py"):
    mod = module_from_source(source, relpath)
    return list(_RULES[rule_id].check(mod))


def test_no_unconditional_chained_registry_calls():
    offenders = _findings("APX101")
    assert not offenders, (
        "instrumented call sites must bind-and-check "
        "(reg = registry(); if reg is None: ...) or use the "
        "module-level helpers — unconditional registry().<metric>() "
        "bypasses the no-op fast path:\n" + _fmt(offenders))


def test_no_direct_metricsregistry_construction():
    offenders = _findings("APX102")
    assert not offenders, (
        "construct registries via observability.configure() only — a "
        "direct MetricsRegistry() dodges configure/shutdown and the "
        "module-level fast path:\n" + _fmt(offenders))


def test_no_private_registry_global_access():
    offenders = _findings("APX103")
    assert not offenders, (
        "_REGISTRY is private to observability.metrics; go through "
        "registry()/enabled():\n" + _fmt(offenders))


def test_device_memory_sampling_is_gated():
    """``sample_device_memory()`` outside the observability package
    must sit within two lines of an ``enabled()`` / bind-and-check
    gate (or pass ``emit=False``, the caller-owns-it form)."""
    offenders = _findings("APX106")
    assert not offenders, (
        "gate device-memory sampling on enabled() in hot paths:\n"
        + _fmt(offenders))


def test_exporter_import_is_module_level_nowhere():
    """The exporter module must never be imported at module level
    anywhere in ``apex_tpu/`` (``configure`` imports it lazily, inside
    the ``export_port is not None`` branch): a top-level import would
    pay for the HTTP server machinery — and open the door to a stray
    socket — on every unconfigured ``import apex_tpu``.  The AST form
    is stricter than the old ^-anchored grep: an import nested in a
    module-level ``if``/``try`` still runs at import time and is
    flagged."""
    offenders = _findings("APX104")
    assert not offenders, (
        "import the exporter lazily inside configure(export_port=...) "
        "only:\n" + _fmt(offenders))


def test_unconfigured_engine_starts_no_exporter_thread():
    """ISSUE 7's zero-overhead extension, runtime side: a fresh
    process that imports the observability package AND drives nothing
    through configure() must have no exporter thread and no exporter
    module in sys.modules."""
    import subprocess
    import sys

    snippet = (
        "import sys, threading\n"
        "import apex_tpu.observability as obs\n"
        "assert obs.registry() is None\n"
        "assert 'apex_tpu.observability.exporter' not in sys.modules\n"
        "assert not [t for t in threading.enumerate()\n"
        "            if t.name == 'apex-tpu-telemetry-exporter']\n"
        "print('NO-THREAD')\n")
    out = subprocess.run([sys.executable, "-c", snippet],
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr
    assert "NO-THREAD" in out.stdout


def test_spec_counters_use_the_helper_only():
    """Every ``generate.spec.*`` counter touch in ``apex_tpu/`` must go
    through ``_telemetry.counter(...)`` on the same statement (the
    no-op-fast-path helper): the accept-rate numbers feed
    telemetry_report's spec summary and serve_dash, so a second access
    idiom would be a second (unguarded) accounting path."""
    offenders = _findings("APX105", "'generate.spec.")
    assert not offenders, (
        "generate.spec.* counters must be accessed via "
        "_telemetry.counter(...) on the same statement:\n"
        + _fmt(offenders))


def test_moe_metrics_use_the_helpers_only():
    """Every ``moe.*`` metric touch in ``apex_tpu/`` must go through
    ``_telemetry.counter(...)`` / ``_telemetry.gauge(...)`` on the same
    statement (the no-op-fast-path helpers): the dispatch-byte and
    ring-hop counters are asserted against by the ``moe_ep`` dryrun
    phase and summarized by telemetry_report's MoE view."""
    offenders = _findings("APX105", "'moe.")
    assert not offenders, (
        "moe.* metrics must be accessed via _telemetry.counter(...)/"
        "_telemetry.gauge(...) on the same statement:\n"
        + _fmt(offenders))


def test_checkpoint_metrics_use_the_helpers_only():
    """Every ``checkpoint.*`` counter/gauge touch in ``apex_tpu/`` must
    go through ``_telemetry.counter(...)`` / ``_telemetry.gauge(...)``
    on the same statement: the save/rollback accounting is what
    telemetry_report's checkpoint summary and the ``bench --ckpt``
    overhead row read, so a second access idiom would fork it."""
    offenders = _findings("APX105", "'checkpoint.")
    assert not offenders, (
        "checkpoint.* metrics must be accessed via "
        "_telemetry.counter(...)/_telemetry.gauge(...) on the same "
        "statement:\n" + _fmt(offenders))


def test_host_tier_metrics_use_the_helpers_only():
    """Every ``serving.host_tier.*`` / ``cluster.prefix_affinity_*``
    metric touch in ``apex_tpu/`` must go through the ``_telemetry``
    helpers on the same statement (ISSUE 18): the hit/miss/eviction
    ledger feeds telemetry_report's host-tier summary and the
    ``kv_tier`` dryrun census, so a second access idiom would fork the
    accounting."""
    offenders = (_findings("APX105", "'serving.host_tier.")
                 + _findings("APX105", "'cluster.prefix_affinity_"))
    assert not offenders, (
        "serving.host_tier.* / cluster.prefix_affinity_* metrics must "
        "be accessed via _telemetry.counter/gauge/sketch(...) on the "
        "same statement:\n" + _fmt(offenders))


def test_guard_patterns_actually_match():
    """The guard is only as good as its rules: each must flag its own
    anti-pattern and pass the clean twin (a regression here silently
    disables the guard).  These are the same fixture semantics the old
    regexes self-tested, now through the real rule objects."""
    # APX101: chained forms fire, bind-and-check does not
    assert _fixture_findings(
        "APX101", "reg = registry().counter('x')\n")
    assert _fixture_findings(
        "APX101", "metrics.registry().gauge('x').set(1)\n")
    assert _fixture_findings(
        "APX101", "registry().sketch('x').observe(1)\n")
    assert not _fixture_findings(
        "APX101", "reg = _telemetry.registry()\n")
    # APX102
    assert _fixture_findings("APX102", "r = MetricsRegistry(sinks)\n")
    assert not _fixture_findings(
        "APX102", "from m import MetricsRegistry\n")
    # APX105: bare registry hop on a guarded prefix fires; the helper
    # on the same statement passes; prose mentions (not string
    # literals) never fire — the AST sees only real strings
    assert _fixture_findings(
        "APX105", 'reg.counter("generate.spec.draft_tokens").inc()\n')
    assert not _fixture_findings(
        "APX105",
        '_telemetry.counter("generate.spec.draft_tokens").inc(2)\n')
    assert _fixture_findings(
        "APX105", 'reg.counter("moe.dispatch_bytes").inc(8)\n')
    assert not _fixture_findings(
        "APX105", '_telemetry.gauge("moe.dropped_fraction").set(0.0)\n')
    assert not _fixture_findings(
        "APX105", '_telemetry.counter("moe.ring_hops").inc(7)\n')
    assert _fixture_findings(
        "APX105", 'reg.counter("checkpoint.rollbacks").inc()\n')
    assert not _fixture_findings(
        "APX105",
        '_telemetry.gauge("checkpoint.overlap_ratio").set(r)\n')
    # span names (checkpoint.save) are not in the guarded set
    assert not _fixture_findings(
        "APX105", 'reg.observe_span("checkpoint.save", bg_s)\n')
    # ISSUE 18: the hierarchical-KV ledger and the router's
    # prefix-affinity counter are guarded the same way
    assert _fixture_findings(
        "APX105", 'reg.counter("serving.host_tier.hits").inc()\n')
    assert not _fixture_findings(
        "APX105", '_telemetry.counter("serving.host_tier.hits").inc()\n')
    assert not _fixture_findings(
        "APX105", '_telemetry.gauge("serving.host_tier.bytes").set(b)\n')
    assert not _fixture_findings(
        "APX105",
        '_telemetry.sketch("serving.host_tier.page_in_ms")'
        '.observe(ms)\n')
    assert _fixture_findings(
        "APX105", 'reg.counter("cluster.prefix_affinity_hits").inc()\n')
    assert not _fixture_findings(
        "APX105",
        '_telemetry.counter("cluster.prefix_affinity_hits").inc()\n')
    # APX103
    assert _fixture_findings("APX103", "from x import _REGISTRY\n")
    assert _fixture_findings("APX103", "v = _REGISTRY\n")
    # APX106: ungated fires, gated/emit=False do not
    assert _fixture_findings("APX106", "sample_device_memory()\n")
    assert not _fixture_findings(
        "APX106",
        "if enabled():\n    sample_device_memory()\n")
    assert not _fixture_findings(
        "APX106", "sample_device_memory(emit=False)\n")
    # APX104: module level fires (even nested in module-level try),
    # function-local does not
    assert _fixture_findings(
        "APX104",
        "from apex_tpu.observability.exporter import TelemetryExporter\n")
    assert _fixture_findings(
        "APX104",
        "try:\n"
        "    from apex_tpu.observability.exporter import "
        "TelemetryExporter\n"
        "except ImportError:\n"
        "    pass\n")
    assert not _fixture_findings(
        "APX104",
        "def configure():\n"
        "    from apex_tpu.observability.exporter import "
        "TelemetryExporter\n")


@pytest.mark.parametrize("helper", [
    "counter", "gauge", "histogram", "sketch", "event", "set_step",
    "record_step_metrics",
])
def test_module_helpers_embed_the_check(helper):
    """Every helper the guard steers call sites toward must itself
    fast-path on the disabled registry (source-level: the function
    body reads _REGISTRY and checks None before doing work)."""
    import inspect

    from apex_tpu.observability import metrics

    src = inspect.getsource(getattr(metrics, helper))
    assert "_REGISTRY" in src and "is None" in src or "is not None" in src
