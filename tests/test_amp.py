"""AMP policy + loss scaler tests.

Reference analogs: tests/L0/run_amp/test_basic_casts.py (per-level dtype
behavior), test_multi_tensor_scale.py (overflow flag semantics), the dynamic
scaler window behavior of apex/amp/scaler.py:206-226, and
test_checkpointing.py (amp state_dict round-trip).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp


class TestPolicy:
    def test_opt_level_tables(self):
        assert amp.O0.param_dtype == jnp.float32
        assert amp.O1.compute_dtype == jnp.float16
        assert amp.O1.loss_scale == "dynamic"
        assert amp.O2.param_dtype == jnp.float16
        assert amp.O2.master_weights
        assert amp.O2.keep_norm_fp32
        assert amp.O3.param_dtype == jnp.float16
        assert not amp.O3.master_weights and amp.O3.loss_scale == 1.0
        assert amp.O4.compute_dtype == jnp.bfloat16
        assert amp.O4.loss_scale == 1.0
        assert amp.O5.param_dtype == jnp.bfloat16
        assert amp.O5.master_weights and amp.O5.loss_scale == 1.0

    def test_unknown_level_raises(self):
        with pytest.raises(ValueError):
            amp.policy_for_opt_level("O9")

    def test_cast_params_keeps_norms_fp32(self):
        params = {
            "dense": {"kernel": jnp.ones((4, 4))},
            "layer_norm_0": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
        }
        cast = amp.O2.cast_params(params)
        assert cast["dense"]["kernel"].dtype == jnp.float16
        assert cast["layer_norm_0"]["scale"].dtype == jnp.float32

    def test_cast_skips_integers(self):
        tree = {"x": jnp.ones((2,)), "i": jnp.arange(3)}
        out = amp.O2.cast_to_compute(tree)
        assert out["x"].dtype == jnp.float16
        assert out["i"].dtype == jnp.int32

    def test_o1_compute_cast_keeps_norms_fp32(self):
        params = {
            "dense": {"kernel": jnp.ones((4, 4))},
            "layer_norm_0": {"scale": jnp.ones((4,))},
        }
        cast = amp.O1.cast_to_compute(params, respect_norms=True)
        assert cast["dense"]["kernel"].dtype == jnp.float16
        assert cast["layer_norm_0"]["scale"].dtype == jnp.float32

    def test_reference_style_override_kwargs(self):
        st = amp.initialize("O2", keep_batchnorm_fp32=False)
        assert not st.policy.keep_norm_fp32
        with pytest.raises(ValueError):
            amp.initialize("O2", not_an_option=True)

    def test_num_losses_returns_list(self):
        states = amp.initialize("O1", num_losses=3)
        assert isinstance(states, list) and len(states) == 3
        assert states[0].loss_scale_state.loss_scale.shape == ()

    def test_properties_rejects_unknown(self):
        props = amp.Properties()
        with pytest.raises(AttributeError):
            props.not_an_option = 1
        with pytest.raises(ValueError):
            props.loss_scale = "bogus"


class TestLossScaler:
    def test_overflow_halves_and_skips(self):
        cfg, state = amp.init_loss_scale("dynamic")
        assert float(state.loss_scale) == 2.0**16
        new, skip = amp.update_loss_scale(cfg, state, jnp.asarray(True))
        assert bool(skip)
        assert float(new.loss_scale) == 2.0**15
        assert int(new.unskipped) == 0

    def test_window_doubling(self):
        cfg, state = amp.init_loss_scale("dynamic", scale_window=3,
                                         init_scale=2.0**10)
        no = jnp.asarray(False)
        for i in range(3):
            state, skip = amp.update_loss_scale(cfg, state, no)
            assert not bool(skip)
        assert float(state.loss_scale) == 2.0**11
        assert int(state.unskipped) == 0

    def test_max_scale_clamped(self):
        cfg, state = amp.init_loss_scale("dynamic", scale_window=1,
                                         init_scale=2.0**24)
        state, _ = amp.update_loss_scale(cfg, state, jnp.asarray(False))
        assert float(state.loss_scale) == 2.0**24

    def test_static_scale_never_skips(self):
        cfg, state = amp.init_loss_scale(128.0)
        new, skip = amp.update_loss_scale(cfg, state, jnp.asarray(True))
        assert not bool(skip)
        assert float(new.loss_scale) == 128.0

    def test_unscale_and_finite_flag(self):
        cfg, state = amp.init_loss_scale(4.0)
        grads = {"w": jnp.asarray([8.0, 4.0])}
        out, finite = amp.unscale_grads(grads, state)
        np.testing.assert_allclose(out["w"], [2.0, 1.0])
        assert bool(finite)
        bad = {"w": jnp.asarray([jnp.inf, 1.0])}
        _, finite = amp.unscale_grads(bad, state)
        assert not bool(finite)

    def test_all_finite_nan(self):
        assert not bool(amp.all_finite({"a": jnp.asarray([jnp.nan])}))
        assert bool(amp.all_finite({"a": jnp.ones(3), "b": jnp.arange(3)}))

    def test_state_dict_roundtrip(self):
        cfg, state = amp.init_loss_scale("dynamic")
        state, _ = amp.update_loss_scale(cfg, state, jnp.asarray(True))
        d = amp.state_dict(state)
        restored = amp.load_state_dict(d)
        assert float(restored.loss_scale) == float(state.loss_scale)
        assert int(restored.unskipped) == int(state.unskipped)


def _toy_loss(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred.astype(jnp.float32) - y) ** 2)


class TestTrainStep:
    def _data(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 4), jnp.float32)
        y = jnp.asarray(rng.randn(8, 2), jnp.float32)
        params = {
            "w": jnp.asarray(rng.randn(4, 2), jnp.float32),
            "b": jnp.zeros((2,), jnp.float32),
        }
        return params, x, y

    @pytest.mark.parametrize("level", ["O0", "O1", "O2", "O3", "O4", "O5"])
    def test_loss_decreases_all_levels(self, level):
        params, x, y = self._data()
        init, step = amp.make_train_step(
            _toy_loss, optax.sgd(0.05), level
        )
        state = init(params)
        step = jax.jit(step)
        _, m0 = step(state, x, y)
        for _ in range(40):
            state, metrics = step(state, x, y)
        assert float(metrics["loss"]) < float(m0["loss"])

    def test_o2_param_dtypes(self):
        params, x, y = self._data()
        init, step = amp.make_train_step(_toy_loss, optax.sgd(0.05), "O2")
        state = init(params)
        assert state.params["w"].dtype == jnp.float16
        assert state.master_params["w"].dtype == jnp.float32
        state, _ = jax.jit(step)(state, x, y)
        assert state.params["w"].dtype == jnp.float16
        assert state.master_params["w"].dtype == jnp.float32

    def test_overflow_skips_step(self):
        params, x, y = self._data()
        init, step = amp.make_train_step(_toy_loss, optax.sgd(0.05), "O2")
        state = init(params)
        bad_x = x.at[0, 0].set(jnp.inf)
        new_state, metrics = jax.jit(step)(state, bad_x, y)
        assert bool(metrics["overflow"])
        np.testing.assert_array_equal(
            np.asarray(new_state.master_params["w"]),
            np.asarray(state.master_params["w"]),
        )
        assert int(new_state.step) == 0
        assert float(new_state.loss_scale_state.loss_scale) == 2.0**15


class TestCastLists:
    def test_decorators(self):
        from apex_tpu.amp import lists

        @lists.float_function
        def f32_fn(x):
            return x.dtype

        @lists.half_function
        def f16_fn(x):
            return x.dtype

        @lists.promote_function
        def promo(x, y):
            return jnp.result_type(x, y)

        assert f32_fn(jnp.ones(2, jnp.float16)) == jnp.float32
        assert f16_fn(jnp.ones(2, jnp.float32)) == jnp.float16
        assert promo(jnp.ones(2, jnp.float16), jnp.ones(2, jnp.float32)) == jnp.float32
