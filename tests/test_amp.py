"""AMP policy + loss scaler tests.

Reference analogs: tests/L0/run_amp/test_basic_casts.py (per-level dtype
behavior), test_multi_tensor_scale.py (overflow flag semantics), the dynamic
scaler window behavior of apex/amp/scaler.py:206-226, and
test_checkpointing.py (amp state_dict round-trip).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp


class TestPolicy:
    def test_opt_level_tables(self):
        assert amp.O0.param_dtype == jnp.float32
        assert amp.O1.compute_dtype == jnp.float16
        assert amp.O1.loss_scale == "dynamic"
        assert amp.O2.param_dtype == jnp.float16
        assert amp.O2.master_weights
        assert amp.O2.keep_norm_fp32
        assert amp.O3.param_dtype == jnp.float16
        assert not amp.O3.master_weights and amp.O3.loss_scale == 1.0
        assert amp.O4.compute_dtype == jnp.bfloat16
        assert amp.O4.loss_scale == 1.0
        assert amp.O5.param_dtype == jnp.bfloat16
        assert amp.O5.master_weights and amp.O5.loss_scale == 1.0

    def test_unknown_level_raises(self):
        with pytest.raises(ValueError):
            amp.policy_for_opt_level("O9")

    def test_cast_params_keeps_norms_fp32(self):
        params = {
            "dense": {"kernel": jnp.ones((4, 4))},
            "layer_norm_0": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
        }
        cast = amp.O2.cast_params(params)
        assert cast["dense"]["kernel"].dtype == jnp.float16
        assert cast["layer_norm_0"]["scale"].dtype == jnp.float32

    def test_cast_skips_integers(self):
        tree = {"x": jnp.ones((2,)), "i": jnp.arange(3)}
        out = amp.O2.cast_to_compute(tree)
        assert out["x"].dtype == jnp.float16
        assert out["i"].dtype == jnp.int32

    def test_o1_compute_cast_keeps_norms_fp32(self):
        params = {
            "dense": {"kernel": jnp.ones((4, 4))},
            "layer_norm_0": {"scale": jnp.ones((4,))},
        }
        cast = amp.O1.cast_to_compute(params, respect_norms=True)
        assert cast["dense"]["kernel"].dtype == jnp.float16
        assert cast["layer_norm_0"]["scale"].dtype == jnp.float32

    def test_reference_style_override_kwargs(self):
        st = amp.initialize("O2", keep_batchnorm_fp32=False)
        assert not st.policy.keep_norm_fp32
        with pytest.raises(ValueError):
            amp.initialize("O2", not_an_option=True)

    def test_num_losses_returns_list(self):
        states = amp.initialize("O1", num_losses=3)
        assert isinstance(states, list) and len(states) == 3
        assert states[0].loss_scale_state.loss_scale.shape == ()

    def test_properties_rejects_unknown(self):
        props = amp.Properties()
        with pytest.raises(AttributeError):
            props.not_an_option = 1
        with pytest.raises(ValueError):
            props.loss_scale = "bogus"


class TestLossScaler:
    def test_overflow_halves_and_skips(self):
        cfg, state = amp.init_loss_scale("dynamic")
        assert float(state.loss_scale) == 2.0**16
        new, skip = amp.update_loss_scale(cfg, state, jnp.asarray(True))
        assert bool(skip)
        assert float(new.loss_scale) == 2.0**15
        assert int(new.unskipped) == 0

    def test_window_doubling(self):
        cfg, state = amp.init_loss_scale("dynamic", scale_window=3,
                                         init_scale=2.0**10)
        no = jnp.asarray(False)
        for i in range(3):
            state, skip = amp.update_loss_scale(cfg, state, no)
            assert not bool(skip)
        assert float(state.loss_scale) == 2.0**11
        assert int(state.unskipped) == 0

    def test_max_scale_clamped(self):
        cfg, state = amp.init_loss_scale("dynamic", scale_window=1,
                                         init_scale=2.0**24)
        state, _ = amp.update_loss_scale(cfg, state, jnp.asarray(False))
        assert float(state.loss_scale) == 2.0**24

    def test_static_scale_never_skips(self):
        cfg, state = amp.init_loss_scale(128.0)
        new, skip = amp.update_loss_scale(cfg, state, jnp.asarray(True))
        assert not bool(skip)
        assert float(new.loss_scale) == 128.0

    def test_unscale_and_finite_flag(self):
        cfg, state = amp.init_loss_scale(4.0)
        grads = {"w": jnp.asarray([8.0, 4.0])}
        out, finite = amp.unscale_grads(grads, state)
        np.testing.assert_allclose(out["w"], [2.0, 1.0])
        assert bool(finite)
        bad = {"w": jnp.asarray([jnp.inf, 1.0])}
        _, finite = amp.unscale_grads(bad, state)
        assert not bool(finite)

    def test_all_finite_nan(self):
        assert not bool(amp.all_finite({"a": jnp.asarray([jnp.nan])}))
        assert bool(amp.all_finite({"a": jnp.ones(3), "b": jnp.arange(3)}))

    def test_state_dict_roundtrip(self):
        cfg, state = amp.init_loss_scale("dynamic")
        state, _ = amp.update_loss_scale(cfg, state, jnp.asarray(True))
        d = amp.state_dict(state)
        restored = amp.load_state_dict(d)
        assert float(restored.loss_scale) == float(state.loss_scale)
        assert int(restored.unskipped) == int(state.unskipped)


def _toy_loss(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred.astype(jnp.float32) - y) ** 2)


class TestTrainStep:
    def _data(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 4), jnp.float32)
        y = jnp.asarray(rng.randn(8, 2), jnp.float32)
        params = {
            "w": jnp.asarray(rng.randn(4, 2), jnp.float32),
            "b": jnp.zeros((2,), jnp.float32),
        }
        return params, x, y

    @pytest.mark.parametrize("level", ["O0", "O1", "O2", "O3", "O4", "O5"])
    def test_loss_decreases_all_levels(self, level):
        params, x, y = self._data()
        init, step = amp.make_train_step(
            _toy_loss, optax.sgd(0.05), level
        )
        state = init(params)
        step = jax.jit(step)
        _, m0 = step(state, x, y)
        for _ in range(40):
            state, metrics = step(state, x, y)
        assert float(metrics["loss"]) < float(m0["loss"])

    def test_o2_param_dtypes(self):
        params, x, y = self._data()
        init, step = amp.make_train_step(_toy_loss, optax.sgd(0.05), "O2")
        state = init(params)
        assert state.params["w"].dtype == jnp.float16
        assert state.master_params["w"].dtype == jnp.float32
        state, _ = jax.jit(step)(state, x, y)
        assert state.params["w"].dtype == jnp.float16
        assert state.master_params["w"].dtype == jnp.float32

    def test_overflow_skips_step(self):
        params, x, y = self._data()
        init, step = amp.make_train_step(_toy_loss, optax.sgd(0.05), "O2")
        state = init(params)
        bad_x = x.at[0, 0].set(jnp.inf)
        new_state, metrics = jax.jit(step)(state, bad_x, y)
        assert bool(metrics["overflow"])
        np.testing.assert_array_equal(
            np.asarray(new_state.master_params["w"]),
            np.asarray(state.master_params["w"]),
        )
        assert int(new_state.step) == 0
        assert float(new_state.loss_scale_state.loss_scale) == 2.0**15


class TestCastLists:
    def test_decorators(self):
        from apex_tpu.amp import lists

        @lists.float_function
        def f32_fn(x):
            return x.dtype

        @lists.half_function
        def f16_fn(x):
            return x.dtype

        @lists.promote_function
        def promo(x, y):
            return jnp.result_type(x, y)

        assert f32_fn(jnp.ones(2, jnp.float16)) == jnp.float32
        assert f16_fn(jnp.ones(2, jnp.float32)) == jnp.float16
        assert promo(jnp.ones(2, jnp.float16), jnp.ones(2, jnp.float32)) == jnp.float32


from apex_tpu.amp.frontend import make_train_step


class TestMainGradAccumulation:
    """fp32 main-grad accumulation (reference
    fused_weight_gradient_dense.cpp wgrad_gemm_accum_fp32 semantics)."""

    def _problem(self, b=16):
        rng = np.random.RandomState(0)
        params = {
            "w": jnp.asarray(rng.randn(12, 8) * 0.3, jnp.float32),
            "b": jnp.zeros((8,), jnp.float32),
        }
        x = jnp.asarray(rng.randn(b, 12), jnp.float32)
        y = jnp.asarray(rng.randn(b, 8), jnp.float32)

        def loss_fn(p, x, y):
            return jnp.mean((x @ p["w"].astype(x.dtype)
                             + p["b"].astype(x.dtype) - y) ** 2)

        return params, loss_fn, x, y

    def test_bf16_accum_matches_fp32_sequential(self):
        from apex_tpu.optimizers import fused_sgd

        params, loss_fn, x, y = self._problem()
        # fp32 oracle: one full-batch step
        init_ref, step_ref = make_train_step(
            loss_fn, fused_sgd(lr=1e-2), "O0")
        sref, _ = step_ref(init_ref(params), x, y)

        # bf16 compute, fp32 main-grad accumulation over 4 microbatches
        init_acc, step_acc = make_train_step(
            loss_fn, fused_sgd(lr=1e-2), "O5", accum_steps=4)
        sacc, macc = step_acc(init_acc(params), x, y)

        for k in params:
            np.testing.assert_allclose(
                np.asarray(sacc.master_params[k]),
                np.asarray(sref.master_params[k]),
                atol=5e-3, rtol=5e-2, err_msg=k)

    def test_accum_equals_manual_fp32_sum(self):
        """The accumulated grad is exactly the fp32 sum of per-microbatch
        bf16-computed grads (no intermediate rounding)."""
        from apex_tpu.optimizers import fused_sgd
        from apex_tpu.amp.policy import policy_for_opt_level

        params, loss_fn, x, y = self._problem()
        policy = policy_for_opt_level("O5")

        def one_grad(mb_x, mb_y):
            def f(p):
                cp = policy.cast_params(p)
                return loss_fn(cp, mb_x, mb_y)
            return jax.grad(f)(params)

        manual = None
        for i in range(4):
            g = one_grad(x[i * 4:(i + 1) * 4], y[i * 4:(i + 1) * 4])
            g32 = jax.tree_util.tree_map(
                lambda v: v.astype(jnp.float32), g)
            manual = g32 if manual is None else jax.tree_util.tree_map(
                jnp.add, manual, g32)
        manual = jax.tree_util.tree_map(lambda v: v / 4.0, manual)

        captured = {}

        def capture(grads):
            captured["g"] = grads
            return grads

        init_acc, step_acc = make_train_step(
            loss_fn, fused_sgd(lr=1e-2), "O5", accum_steps=4,
            grad_postprocess=capture)
        step_acc(init_acc(params), x, y)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(captured["g"][k]), np.asarray(manual[k]),
                atol=1e-6, rtol=1e-5, err_msg=k)

    def test_overflow_skip_with_accum(self):
        from apex_tpu.optimizers import fused_sgd

        params, loss_fn, x, y = self._problem()
        init_acc, step_acc = make_train_step(
            loss_fn, fused_sgd(lr=1e-2), "O2", accum_steps=4)
        s0 = init_acc(params)
        bad = x.at[0, 0].set(jnp.inf)
        s1, m = step_acc(s0, bad, y)
        assert bool(m["overflow"])
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(s1.master_params[k]),
                np.asarray(s0.master_params[k]))


def test_second_init_survives_donated_step():
    """Regression (round 3): init_fn must not alias the factory-shared
    loss-scale buffers — a donated step would delete them out from under
    every later init() from the same factory."""
    import numpy as np

    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(16, 16) * 0.1, jnp.float32)}
    x = jnp.asarray(rs.randn(8, 16), jnp.float32)

    def loss_fn(p, x):
        return jnp.mean((x @ p["w"].astype(x.dtype)) ** 2)

    from apex_tpu.optimizers import fused_adam

    init, step = make_train_step(loss_fn, fused_adam(lr=1e-3), "O2")
    step = jax.jit(step, donate_argnums=0)
    s1 = init(params)
    s1, _ = step(s1, x)                   # donates s1's buffers
    s2 = init(params)                     # must be fully fresh
    s2, m = step(s2, x)
    assert np.isfinite(float(m["loss"]))
