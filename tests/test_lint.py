"""apexlint unit tests (ISSUE 12): every Tier-A rule must catch its
fixture and pass its clean twin; the linter machinery (suppressions,
baseline diff, fingerprints, env registry) is pinned; and the Tier-B
auditor unit plants a monolithic psum inside an overlap scope and
asserts the census flags it.

Fixture style: in-memory modules via ``rules.module_from_source`` —
the same ModuleInfo path the real linter walks, minus the filesystem.
The full-matrix Tier-B audit is exercised by the ``static_audit``
dryrun phase and a slow-marked test here; the default-run tests only
*trace* tiny functions (no compiles), keeping this file cheap inside
the tier-1 window.
"""

import json
import os

import pytest

from apex_tpu.analysis import env_registry, linter
from apex_tpu.analysis.rules import module_from_source, rules_by_id

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RULES = rules_by_id()


@pytest.fixture(scope="module")
def repo_findings():
    """ONE full-repo lint shared by every at-head assertion in this
    file (the parse+call-graph+donation pass is the expensive part)."""
    return linter.lint(REPO)


def run_rule(rule_id, source, relpath="apex_tpu/_fixture.py"):
    return list(RULES[rule_id].check(
        module_from_source(source, relpath)))


# ---------------------------------------------------------------------------
# APX2xx — env-var discipline
# ---------------------------------------------------------------------------


class TestEnvRules:
    def test_unregistered_env_read_fires(self):
        fs = run_rule(
            "APX201",
            'import os\nv = os.environ.get("APEX_TPU_NOT_A_THING")\n')
        assert len(fs) == 1 and "APEX_TPU_NOT_A_THING" in fs[0].message

    def test_registered_env_read_clean(self):
        assert not run_rule(
            "APX201",
            'import os\nv = os.environ.get("APEX_TPU_LN_BWD")\n')

    def test_subscript_read_fires(self):
        assert run_rule(
            "APX201", 'import os\nv = os.environ["APEX_TPU_BOGUS"]\n')

    def test_dynamic_family_prefix_resolves(self):
        # f"APEX_TPU_DISABLE_{name}" matches the registered
        # APEX_TPU_DISABLE_* family via its static prefix
        assert not run_rule(
            "APX201",
            'import os\n'
            'v = os.environ.get(f"APEX_TPU_DISABLE_{name}")\n')
        assert run_rule(
            "APX201",
            'import os\n'
            'v = os.environ.get(f"APEX_TPU_BOGUS_{name}")\n')

    def test_non_apex_names_ignored(self):
        assert not run_rule(
            "APX201", 'import os\nv = os.environ.get("HOME")\n')

    def test_lookup_prefers_exact_over_family(self):
        row = env_registry.lookup("APEX_TPU_DISABLE_NATIVE")
        assert row is not None and row.name == "APEX_TPU_DISABLE_NATIVE"
        fam = env_registry.lookup("APEX_TPU_DISABLE_FLASH_ATTENTION")
        assert fam is not None and fam.name == "APEX_TPU_DISABLE_*"
        assert env_registry.lookup("APEX_TPU_NOPE") is None

    def test_docs_sync_clean_at_head(self):
        fs = list(RULES["APX202"].check_repo([], REPO))
        assert not fs, "\n".join(f.message for f in fs)

    def test_docs_sync_catches_undocumented_row(self, monkeypatch):
        bogus = dict(env_registry.ENV_REGISTRY)
        bogus["APEX_TPU_PHANTOM_KNOB"] = env_registry.EnvVar(
            "APEX_TPU_PHANTOM_KNOB", "nowhere",
            "docs/static_analysis.md", "not actually documented")
        monkeypatch.setattr(env_registry, "ENV_REGISTRY", bogus)
        fs = list(RULES["APX202"].check_repo([], REPO))
        assert len(fs) == 1 and "APEX_TPU_PHANTOM_KNOB" in fs[0].message

    def test_private_global_owner_file_exempt(self):
        # metrics.py owns _REGISTRY; the same source elsewhere fires
        src = "def shutdown():\n    global _REGISTRY\n    x = _REGISTRY\n"
        assert not run_rule("APX103", src,
                            "apex_tpu/observability/metrics.py")
        assert run_rule("APX103", src, "apex_tpu/comm/reduce.py")

    def test_env_table_sync_clean_at_head(self):
        mods = linter._parse_modules(
            REPO, ("apex_tpu/observability/metrics.py",))
        fs = list(RULES["APX203"].check_repo(mods, REPO))
        assert not fs, "\n".join(f.message for f in fs)

    def test_env_table_sync_catches_drift(self):
        # a doctored metrics.py with an extra telemetry var must trip
        # the statically-parsed sync check
        fake = module_from_source(
            'ENV_PREFIX = "APEX_TPU_TELEMETRY"\n'
            'ENV_VARS = {"": 1, "_STDERR": 1, "_NEWVAR": 1}\n',
            "apex_tpu/observability/metrics.py")
        fs = list(RULES["APX203"].check_repo([fake], REPO))
        assert fs and "_NEWVAR" in fs[0].message


# ---------------------------------------------------------------------------
# APX3xx — host sync / nondeterminism under a trace
# ---------------------------------------------------------------------------

_JIT_HEADER = "import jax\nimport numpy as np\nimport time\n"


class TestHostSyncRule:
    def test_item_in_jitted_fn_fires(self):
        fs = run_rule("APX301", _JIT_HEADER +
                      "@jax.jit\ndef f(x):\n    return x.item()\n")
        assert len(fs) == 1 and ".item()" in fs[0].message

    def test_item_in_host_fn_clean(self):
        assert not run_rule(
            "APX301", _JIT_HEADER + "def f(x):\n    return x.item()\n")

    def test_float_on_param_in_while_body_fires(self):
        src = _JIT_HEADER + (
            "def loop(x):\n"
            "    def body(c):\n"
            "        return c + float(c)\n"
            "    return jax.lax.while_loop(lambda c: True, body, x)\n")
        fs = run_rule("APX301", src)
        assert fs and "float(" in fs[0].message

    def test_float_on_shape_is_static(self):
        src = _JIT_HEADER + (
            "@jax.jit\ndef f(x):\n    return x * int(x.shape[0])\n")
        assert not run_rule("APX301", src)

    def test_int_annotated_param_is_static(self):
        src = _JIT_HEADER + (
            "@jax.jit\ndef f(n: int):\n    return int(n) + 1\n")
        assert not run_rule("APX301", src)

    def test_np_asarray_on_traced_value_fires(self):
        src = _JIT_HEADER + (
            "@jax.jit\ndef f(x):\n    return np.asarray(x) + 1\n")
        assert run_rule("APX301", src)

    def test_transitive_callee_fires(self):
        # f is jitted, g is plain — but reachable from f, so g's sync
        # is inside the trace
        src = _JIT_HEADER + (
            "def g(x):\n    return x.item()\n"
            "@jax.jit\ndef f(x):\n    return g(x)\n")
        fs = run_rule("APX301", src)
        assert fs and "g" in fs[0].message

    def test_suppression_comment_respected(self):
        # suppression is applied by the linter layer, so drive lint()
        # over a temp module
        import tempfile

        src = _JIT_HEADER + (
            "@jax.jit\ndef f(x):\n"
            "    return x.item()   # apexlint: disable=APX301\n")
        with tempfile.TemporaryDirectory() as d:
            pkg = os.path.join(d, "apex_tpu")
            os.makedirs(pkg)
            with open(os.path.join(pkg, "m.py"), "w") as f:
                f.write(src)
            assert not linter.lint(d, targets=("apex_tpu",),
                                   rules=[RULES["APX301"]])
            with open(os.path.join(pkg, "m.py"), "w") as f:
                f.write(src.replace("   # apexlint: disable=APX301",
                                    ""))
            assert linter.lint(d, targets=("apex_tpu",),
                               rules=[RULES["APX301"]])


class TestNondeterminismRule:
    def test_time_in_scan_body_fires(self):
        src = _JIT_HEADER + (
            "def step(c, x):\n    return c, time.time()\n"
            "def run(xs):\n    return jax.lax.scan(step, 0, xs)\n")
        fs = run_rule("APX302", src)
        assert fs and "host clock" in fs[0].message

    def test_np_random_in_jit_fires(self):
        src = _JIT_HEADER + (
            "@jax.jit\ndef f(x):\n    return x + np.random.randn()\n")
        fs = run_rule("APX302", src)
        assert fs and "numpy RNG" in fs[0].message

    def test_jax_random_is_clean(self):
        src = _JIT_HEADER + (
            "@jax.jit\ndef f(key, x):\n"
            "    return x + jax.random.normal(key, x.shape)\n")
        assert not run_rule("APX302", src)

    def test_time_on_host_clean(self):
        assert not run_rule(
            "APX302",
            _JIT_HEADER + "def poll():\n    return time.time()\n")


class TestReviewRegressions:
    """Pins for the review-pass fixes: each of these was an executed
    counterexample before the fix."""

    def test_suppression_comma_space_list(self, tmp_path):
        # '# apexlint: disable=APX301, APX302' (space after comma)
        # must suppress BOTH ids
        pkg = tmp_path / "apex_tpu"
        pkg.mkdir()
        (pkg / "m.py").write_text(
            _JIT_HEADER +
            "@jax.jit\ndef f(x):\n"
            "    return x.item() + time.time()"
            "   # apexlint: disable=APX301, APX302\n")
        fs = linter.lint(str(tmp_path), targets=("apex_tpu",),
                         rules=[RULES["APX301"], RULES["APX302"]])
        assert not fs, [f.message for f in fs]

    def test_fstring_metric_violation_reports_once(self):
        fs = run_rule(
            "APX105", 'reg.counter(f"moe.{name}_bytes").inc(1)\n')
        assert len(fs) == 1

    def test_math_exemption_is_subtree_scoped(self):
        # the math call's own subtree is exempt; a traced param
        # ELSEWHERE in the expression still flags, in either operand
        # order
        for expr in ("float(x * math.sqrt(2.0))",
                     "float(math.sqrt(2.0) * x)"):
            src = ("import jax, math\n"
                   f"@jax.jit\ndef f(x):\n    return {expr}\n")
            assert run_rule("APX301", src), expr
        assert not run_rule(
            "APX301",
            "import jax, math\n"
            "@jax.jit\ndef f(x):\n"
            "    return x * math.prod(x.shape)\n")

    def test_kind_tallies_shared_by_gate_and_emission(self):
        from apex_tpu.analysis.jaxpr_audit import kind_tallies

        t = kind_tallies(
            {"psum": 2, "reduce_scatter": 1},
            {"collectives.psum.calls": 1.0,
             "collectives.pmean.calls": 1.0,
             "collectives.psum_scatter.calls": 1.0},
            ("psum", "psum_scatter"))
        assert t["psum"] == (2, 2.0)          # pmean folds into psum
        assert t["psum_scatter"] == (1, 1.0)  # reduce_scatter prim


# ---------------------------------------------------------------------------
# APX401 — donation safety
# ---------------------------------------------------------------------------


def run_donation(source, relpath="apex_tpu/_fixture.py"):
    mod = module_from_source(source, relpath)
    return list(RULES["APX401"].check_repo([mod], REPO))


class TestDonationRule:
    def test_use_after_donation_fires(self):
        src = (
            "import jax\n"
            "def make(f, state, x):\n"
            "    step = jax.jit(f, donate_argnums=(0,))\n"
            "    new = step(state, x)\n"
            "    return new, state.sum()\n")
        fs = run_donation(src)
        assert len(fs) == 1 and "'state'" in fs[0].message

    def test_rebinding_through_the_call_is_clean(self):
        src = (
            "import jax\n"
            "def make(f, state, xs):\n"
            "    step = jax.jit(f, donate_argnums=(0,))\n"
            "    for x in xs:\n"
            "        state = step(state, x)\n"
            "    return state\n")
        assert not run_donation(src)

    def test_prefix_rebind_kills_the_path(self):
        # self.cache = {...} rebinds self.cache["k"] — the engine's
        # real idiom (a regression here re-flags serving/engine.py)
        src = (
            "import jax, functools\n"
            "@functools.partial(jax.jit, donate_argnames=('pool',))\n"
            "def insert(pool, ks):\n"
            "    return pool\n"
            "class E:\n"
            "    def write(self, ks):\n"
            "        k = insert(self.cache['k'], ks)\n"
            "        self.cache = {'k': k}\n"
            "        return self.cache['k'].shape\n")
        assert not run_donation(src)

    def test_donate_argnames_decorator_maps_positions(self):
        src = (
            "import jax, functools\n"
            "@functools.partial(jax.jit, donate_argnames=('pool',))\n"
            "def insert(pool, ks):\n"
            "    return pool\n"
            "def caller(pool, ks):\n"
            "    out = insert(pool, ks)\n"
            "    return out, pool.shape\n")
        fs = run_donation(src)
        assert len(fs) == 1 and "'pool'" in fs[0].message

    def test_repo_clean_at_head(self, repo_findings):
        fs = [f for f in repo_findings if f.rule == "APX401"]
        assert not fs, "\n".join(f"{f.path}:{f.line} {f.message}"
                                 for f in fs)


# ---------------------------------------------------------------------------
# linter machinery: baseline diff, fingerprints, skip-file, --changed
# ---------------------------------------------------------------------------


class TestLinterMachinery:
    def _temp_repo(self, d, body):
        pkg = os.path.join(d, "apex_tpu")
        os.makedirs(pkg, exist_ok=True)
        with open(os.path.join(pkg, "m.py"), "w") as f:
            f.write(body)
        return d

    def test_fingerprints_are_line_number_free(self, tmp_path):
        body = "r = MetricsRegistry(s)\n"
        d = self._temp_repo(str(tmp_path), body)
        fs1 = linter.lint(d, targets=("apex_tpu",),
                          rules=[RULES["APX102"]])
        (fp1, _), = linter.fingerprints(fs1)
        # shift the finding down two lines: fingerprint must not move
        self._temp_repo(d, "import x\nimport y\n" + body)
        fs2 = linter.lint(d, targets=("apex_tpu",),
                          rules=[RULES["APX102"]])
        (fp2, f2), = linter.fingerprints(fs2)
        assert fp1 == fp2 and f2.line == 3

    def test_identical_snippets_get_ordinals(self, tmp_path):
        body = "r = MetricsRegistry(s)\nr = MetricsRegistry(s)\n"
        d = self._temp_repo(str(tmp_path), body)
        fs = linter.lint(d, targets=("apex_tpu",),
                         rules=[RULES["APX102"]])
        fps = [fp for fp, _ in linter.fingerprints(fs)]
        assert len(fps) == 2 and len(set(fps)) == 2
        assert fps[0].endswith(":0") and fps[1].endswith(":1")

    def test_baseline_roundtrip_and_diff(self, tmp_path):
        d = self._temp_repo(str(tmp_path),
                            "r = MetricsRegistry(s)\n")
        fs = linter.lint(d, targets=("apex_tpu",),
                         rules=[RULES["APX102"]])
        linter.write_baseline(d, fs)
        new, stale = linter.diff_baseline(d, fs)
        assert not new and not stale
        with open(os.path.join(d, linter.BASELINE_FILE)) as f:
            doc = json.load(f)
        assert doc["entries"][0]["justification"].startswith(
            "FILL-ME-IN")
        # fix the finding: the entry goes stale
        new, stale = linter.diff_baseline(d, [])
        assert not new and len(stale) == 1
        # a different finding is NEW even with a baseline present
        self._temp_repo(d, "r2 = MetricsRegistry(t)\n")
        fs2 = linter.lint(d, targets=("apex_tpu",),
                          rules=[RULES["APX102"]])
        new, _ = linter.diff_baseline(d, fs2)
        assert len(new) == 1

    def test_skip_file_header(self, tmp_path):
        d = self._temp_repo(
            str(tmp_path),
            "# apexlint: skip-file\nr = MetricsRegistry(s)\n")
        assert not linter.lint(d, targets=("apex_tpu",),
                               rules=[RULES["APX102"]])

    def test_repo_lint_is_clean_or_baselined(self, repo_findings):
        """THE enforcement pin: the real repo must stay clean against
        its committed baseline (currently empty — keep it so)."""
        new, stale = linter.diff_baseline(REPO, repo_findings)
        assert not new, "new apexlint findings:\n" + "\n".join(
            f"  {fp} {f.path}:{f.line} {f.message}" for fp, f in new)
        assert not stale, (
            "stale baseline entries (delete them):\n" + "\n".join(
                e["fingerprint"] for e in stale))


# ---------------------------------------------------------------------------
# Tier B — jaxpr auditor units
# ---------------------------------------------------------------------------


class TestJaxprAudit:
    def test_planted_psum_in_overlap_scope_is_flagged(self):
        """THE acceptance unit: a monolithic psum planted inside an
        overlap scope must show up in the census and fail the
        ring-only check."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.analysis import jaxpr_audit

        n = min(8, len(jax.devices()))
        mesh = Mesh(np.array(jax.devices()[:n]), ("tp",))
        planted = jax.shard_map(
            lambda x: jax.lax.psum(x, "tp"), mesh=mesh,
            in_specs=P("tp"), out_specs=P())
        rep = jaxpr_audit.audit_overlap_trace(
            planted, jnp.ones((n, 4)))
        assert not rep.ok
        assert rep.census.get("psum") == 1
        assert any("monolithic psum" in f for f in rep.findings)

    def test_ring_trace_is_clean_and_counted(self):
        """The real ring decomposition under the same helper: ppermute
        only, and the census agrees with collectives.ppermute.calls."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.analysis import jaxpr_audit
        from apex_tpu.ops.collective_matmul import ring_all_gather

        n = min(8, len(jax.devices()))
        mesh = Mesh(np.array(jax.devices()[:n]), ("tp",))
        ring = jax.shard_map(
            lambda x: ring_all_gather(x, "tp"), mesh=mesh,
            in_specs=P("tp"), out_specs=P("tp"))
        rep = jaxpr_audit.audit_overlap_trace(ring, jnp.ones((n, 4)))
        assert rep.ok, rep.findings
        assert rep.census.get("ppermute", 0) == n - 1
        assert rep.counted.get("collectives.ppermute.calls") == n - 1
        assert rep.counted.get("collectives.ring.hops") == n - 1

    def test_census_vs_counters_drift_detector(self):
        from apex_tpu.analysis.jaxpr_audit import \
            check_census_vs_counters

        # census > counters: always a finding (uncounted collective)
        fs = check_census_vs_counters(
            {"all_gather": 3}, {"collectives.all_gather.calls": 2.0},
            ("all_gather",))
        assert fs and "drift" in fs[0]
        # counters > census: only under exact policy
        assert not check_census_vs_counters(
            {"all_gather": 1}, {"collectives.all_gather.calls": 2.0},
            ("all_gather",))
        assert check_census_vs_counters(
            {"all_gather": 1}, {"collectives.all_gather.calls": 2.0},
            ("all_gather",), policy="exact")
        # agreement is quiet
        assert not check_census_vs_counters(
            {"all_gather": 2}, {"collectives.all_gather.calls": 2.0},
            ("all_gather",), policy="exact")

    def test_dead_expensive_eqn_flagged_cheap_noted(self):
        import jax
        import jax.numpy as jnp

        from apex_tpu.analysis.jaxpr_audit import check_dead_eqns

        def f(x, w):
            dead = x @ w          # dropped matmul: real lost compute
            cheap = x + 1.0       # dropped elementwise: trace noise
            return x.sum()

        jaxpr = jax.make_jaxpr(f)(jnp.ones((4, 4)), jnp.ones((4, 4)))
        findings, notes = check_dead_eqns(jaxpr)
        assert len(findings) == 1 and "dot_general" in findings[0]
        assert notes and "cheap dead" in notes[0]

    def test_upcast_detector_and_allowlist(self):
        import jax
        import jax.numpy as jnp

        from apex_tpu.analysis.jaxpr_audit import check_upcasts

        def suspicious_mixer(x):
            h = x.astype(jnp.bfloat16)
            return (h.astype(jnp.float32) * 2.0).sum()

        jaxpr = jax.make_jaxpr(suspicious_mixer)(jnp.ones((8,)))
        findings, _ = check_upcasts(jaxpr)
        assert findings and "suspicious_mixer" in findings[0]
        # the same convert under an allowlisted name passes
        findings, _ = check_upcasts(
            jaxpr, allowlist=("suspicious_mixer",))
        assert not findings

    def test_donation_check_detects_lowered_alias(self):
        import jax
        import jax.numpy as jnp

        from apex_tpu.analysis.jaxpr_audit import check_donation

        def step(s, x):
            return s + x

        donated = jax.jit(step, donate_argnums=0)
        plain = jax.jit(step)
        args = (jnp.ones((4,)), jnp.ones((4,)))
        assert not check_donation(donated, args)
        assert check_donation(plain, args)

    @pytest.mark.slow
    def test_full_entry_matrix_is_green(self):
        """The whole Tier-B matrix (also gated by the static_audit
        dryrun phase; slow-marked here to stay out of the tier-1
        window — tracing only, ~15 s)."""
        from apex_tpu.analysis import jaxpr_audit

        reports = jaxpr_audit.run_audit()
        bad = {r.name: r.findings for r in reports if not r.ok}
        assert not bad, bad
        names = {r.name for r in reports}
        assert names == set(jaxpr_audit.ENTRY_POINTS)
