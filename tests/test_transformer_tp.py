"""Tensor-parallel toolkit tests on the 8-device CPU mesh.

Reference analogs: tests/L0/run_transformer/test_parallel_state.py,
test_mapping.py, test_layers.py, test_cross_entropy.py, test_random.py.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel as tp

shard_map = jax.shard_map


@pytest.fixture()
def tp8_mesh():
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=8
    )
    yield mesh
    parallel_state.destroy_model_parallel()


class TestParallelState:
    def test_sizes_and_errors(self, tp8_mesh):
        assert parallel_state.get_tensor_model_parallel_world_size() == 8
        assert parallel_state.get_data_parallel_world_size() == 1
        assert parallel_state.get_pipeline_model_parallel_world_size() == 1
        assert parallel_state.model_parallel_is_initialized()
        assert "tp=8" in parallel_state.get_rank_info()

    def test_uninitialized_raises(self):
        parallel_state.destroy_model_parallel()
        with pytest.raises(RuntimeError):
            parallel_state.get_mesh()

    def test_virtual_pp_state(self):
        parallel_state.initialize_model_parallel(
            1, 2, virtual_pipeline_model_parallel_size_=4
        )
        assert parallel_state.get_virtual_pipeline_model_parallel_world_size() == 4
        assert parallel_state.get_virtual_pipeline_model_parallel_rank() == 0
        parallel_state.set_virtual_pipeline_model_parallel_rank(2)
        assert parallel_state.get_virtual_pipeline_model_parallel_rank() == 2
        parallel_state.destroy_model_parallel()


class TestMappings:
    def _run(self, mesh, fn, *args, in_specs, out_specs):
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)(*args)

    def test_copy_fwd_identity_bwd_allreduce(self, tp8_mesh):
        x = jnp.arange(8.0)

        def f(x_):
            # forward: every rank sees the full x
            y = tp.copy_to_tensor_model_parallel_region(x_)
            return jnp.sum(y * (jax.lax.axis_index("tp") + 1.0))

        @functools.partial(shard_map, mesh=tp8_mesh, in_specs=P(),
                           out_specs=P())
        def grads(x_):
            return jax.grad(f)(x_)

        g = grads(x)
        # bwd allreduce: sum of rank+1 over 8 ranks = 36
        np.testing.assert_allclose(np.asarray(g), np.full(8, 36.0))

    def test_reduce_fwd_allreduce(self, tp8_mesh):
        x = jnp.arange(8.0)

        @functools.partial(shard_map, mesh=tp8_mesh, in_specs=P("tp"),
                           out_specs=P("tp"))
        def f(x_):
            return tp.reduce_from_tensor_model_parallel_region(x_)

        out = f(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_scatter_gather_last_dim_roundtrip(self, tp8_mesh):
        x = jnp.arange(16.0).reshape(2, 8)

        @functools.partial(shard_map, mesh=tp8_mesh, in_specs=P(),
                           out_specs=P("tp"))
        def f(x_):
            local = tp.scatter_to_tensor_model_parallel_region(x_)
            assert local.shape == (2, 1)
            return tp.gather_from_tensor_model_parallel_region(local)[None]

        out = f(x)   # (8, 2, 8): every shard reconstructed the full x
        for i in range(8):
            np.testing.assert_allclose(np.asarray(out[i]), np.asarray(x))

    def test_sequence_parallel_roundtrip_and_reduce_scatter(self, tp8_mesh):
        x = jnp.arange(16.0).reshape(8, 2)

        @functools.partial(shard_map, mesh=tp8_mesh, in_specs=P(),
                           out_specs=P("tp"))
        def f(x_):
            local = tp.scatter_to_sequence_parallel_region(x_)
            assert local.shape == (1, 2)
            return tp.gather_from_sequence_parallel_region(local)[None]

        out = f(x)
        for i in range(8):
            np.testing.assert_allclose(np.asarray(out[i]), np.asarray(x))

        @functools.partial(shard_map, mesh=tp8_mesh, in_specs=P(),
                           out_specs=P("tp"))
        def rs(x_):
            y = tp.copy_to_tensor_model_parallel_region(x_)
            return tp.reduce_scatter_to_sequence_parallel_region(y)

        out = rs(x)   # each shard's row = sum over 8 replicas of its row
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 8)

    def test_gather_seq_parallel_bwd_reduce_scatter(self, tp8_mesh):
        x = jnp.ones((1, 2))

        @functools.partial(shard_map, mesh=tp8_mesh, in_specs=P("tp"),
                           out_specs=P("tp"))
        def grads(x_):
            def f(x__):
                full = tp.gather_from_sequence_parallel_region(x__)
                w = jax.lax.axis_index("tp") + 1.0
                return jnp.sum(full) * w

            return jax.grad(f)(x_)

        g = grads(jnp.ones((8, 2)))
        # cotangent of full = rank+1 everywhere; reduce-scatter sums over
        # ranks for this shard's row: Σ(rank+1) = 36
        np.testing.assert_allclose(np.asarray(g), np.full((8, 2), 36.0))


class TestVocabParallelCE:
    def test_matches_single_device(self, tp8_mesh):
        from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

        rng = np.random.RandomState(0)
        logits = rng.randn(6, 64).astype(np.float32) * 2
        labels = rng.randint(0, 64, size=(6,))
        ref = softmax_cross_entropy_loss(
            jnp.asarray(logits), jnp.asarray(labels), padding_idx=-1
        )

        @functools.partial(shard_map, mesh=tp8_mesh,
                           in_specs=(P(None, "tp"), P()), out_specs=P())
        def f(lg, lb):
            return tp.vocab_parallel_cross_entropy(lg, lb)

        loss = f(jnp.asarray(logits), jnp.asarray(labels))
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                                   atol=1e-5)

    def test_gradients_match(self, tp8_mesh):
        from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

        rng = np.random.RandomState(1)
        logits = rng.randn(4, 32).astype(np.float32)
        labels = rng.randint(0, 32, size=(4,))
        g_ref = jax.grad(
            lambda l: jnp.sum(
                softmax_cross_entropy_loss(l, jnp.asarray(labels),
                                           padding_idx=-1)
            )
        )(jnp.asarray(logits))

        @functools.partial(shard_map, mesh=tp8_mesh,
                           in_specs=(P(None, "tp"), P()),
                           out_specs=P(None, "tp"))
        def grads(lg, lb):
            return jax.grad(
                lambda l: jnp.sum(tp.vocab_parallel_cross_entropy(l, lb))
            )(lg)

        g = grads(jnp.asarray(logits), jnp.asarray(labels))
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   atol=1e-5)


class TestGSPMDLayers:
    def test_column_row_mlp_matches_dense(self, tp8_mesh):
        """Column→Row parallel MLP under GSPMD == single-device math."""
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(4, 16), jnp.float32)

        import flax.linen as nn

        class TwoLayer(nn.Module):
            @nn.compact
            def __call__(self, x_):
                h, _ = tp.ColumnParallelLinear(
                    input_size=16, output_size=32, gather_output=False
                )(x_)
                h = jax.nn.gelu(h)
                y, _ = tp.RowParallelLinear(
                    input_size=32, output_size=16, input_is_parallel=True
                )(h)
                return y

        model = TwoLayer()
        variables = model.init(jax.random.PRNGKey(0), x)

        # params carry partitioning metadata
        import flax

        col_kernel = variables["params"]["ColumnParallelLinear_0"]["kernel"]
        assert isinstance(col_kernel, nn.Partitioned)
        assert col_kernel.names == (None, "tp")

        # single-device reference from unboxed params
        unboxed = flax.core.meta.unbox(variables)
        k1 = np.asarray(unboxed["params"]["ColumnParallelLinear_0"]["kernel"])
        b1 = np.asarray(unboxed["params"]["ColumnParallelLinear_0"]["bias"])
        k2 = np.asarray(unboxed["params"]["RowParallelLinear_0"]["kernel"])
        b2 = np.asarray(unboxed["params"]["RowParallelLinear_0"]["bias"])
        expect = np.asarray(jax.nn.gelu(np.asarray(x) @ k1 + b1)) @ k2 + b2

        # run under the mesh with sharded params
        with jax.sharding.set_mesh(tp8_mesh):
            shardings = nn.get_sharding(variables, tp8_mesh)
            sharded_vars = jax.device_put(unboxed, shardings)
            y = jax.jit(lambda v, x_: model.apply(v, x_))(sharded_vars, x)
        np.testing.assert_allclose(np.asarray(y), expect, atol=1e-5)

    def test_vocab_parallel_embedding(self, tp8_mesh):
        import flax
        import flax.linen as nn

        emb = tp.VocabParallelEmbedding(num_embeddings=64, embedding_dim=16)
        ids = jnp.asarray([[1, 5, 63], [0, 32, 7]])
        variables = emb.init(jax.random.PRNGKey(0), ids)
        table = variables["params"]["embedding"]
        assert isinstance(table, nn.Partitioned)
        assert table.names == ("tp", None)

        unboxed = flax.core.meta.unbox(variables)
        expect = np.asarray(unboxed["params"]["embedding"])[np.asarray(ids)]
        with jax.sharding.set_mesh(tp8_mesh):
            shardings = nn.get_sharding(variables, tp8_mesh)
            sharded = jax.device_put(unboxed, shardings)
            y = jax.jit(lambda v, i: emb.apply(v, i))(sharded, ids)
        np.testing.assert_allclose(np.asarray(y), expect, atol=1e-6)


@pytest.mark.skipif(not hasattr(jax, "set_mesh"),
                    reason="jax.set_mesh (jax>=0.9 GSPMD surface) required")
class TestSequenceParallelParity:
    """ISSUE 5 satellite: the ``sequence_parallel_enabled`` Column/Row
    layers vs their non-SP counterparts, forward AND backward, on the
    virtual mesh — the mappings.py fwd/bwd table asserted directly
    instead of only through the gspmd dryrun.  SP only moves the
    shardings (gather → matmul → reduce-scatter vs replicated matmul +
    all-reduce); the global values must not move."""

    def _run_mlp(self, mesh, x, sp_enabled, overlap=False):
        import flax
        import flax.linen as nn

        class Mlp(nn.Module):
            @nn.compact
            def __call__(self, x_):
                h, _ = tp.ColumnParallelLinear(
                    input_size=32, output_size=64, gather_output=False,
                    sequence_parallel_enabled=sp_enabled,
                    overlap_comm=overlap)(x_)
                h = jax.nn.gelu(h)
                y, _ = tp.RowParallelLinear(
                    input_size=64, output_size=32,
                    input_is_parallel=True,
                    sequence_parallel_enabled=sp_enabled,
                    overlap_comm=overlap)(h)
                return y

        model = Mlp()
        variables = flax.core.meta.unbox(
            model.init(jax.random.PRNGKey(0), x))

        def loss(v, x_):
            return jnp.sum(model.apply(v, x_).astype(jnp.float32) ** 2)

        with jax.set_mesh(mesh):
            y = jax.jit(lambda v, x_: model.apply(v, x_))(variables, x)
            l, g = jax.jit(jax.value_and_grad(loss))(variables, x)
        return np.asarray(y), float(l), g

    def test_sp_matches_non_sp_fwd_bwd(self, tp8_mesh):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(16, 2, 32), jnp.float32)  # [s, b, h]
        y_sp, l_sp, g_sp = self._run_mlp(tp8_mesh, x, sp_enabled=True)
        y_no, l_no, g_no = self._run_mlp(tp8_mesh, x, sp_enabled=False)
        np.testing.assert_allclose(y_sp, y_no, atol=1e-5)
        np.testing.assert_allclose(l_sp, l_no, rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g_sp),
                        jax.tree_util.tree_leaves(g_no)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_sp_overlap_matches_monolithic(self, tp8_mesh):
        """overlap_comm rides the ring collective-matmul through the
        same layers; fwd+bwd must agree with the monolithic SP path."""
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(16, 2, 32), jnp.float32)
        y_on, l_on, g_on = self._run_mlp(tp8_mesh, x, sp_enabled=True,
                                         overlap=True)
        y_off, l_off, g_off = self._run_mlp(tp8_mesh, x, sp_enabled=True,
                                            overlap=False)
        np.testing.assert_allclose(y_on, y_off, atol=1e-5)
        np.testing.assert_allclose(l_on, l_off, rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g_on),
                        jax.tree_util.tree_leaves(g_off)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestSequenceParallelMappingTable:
    """The mappings.py fwd/bwd table, asserted pair-by-pair under
    shard_map (runs on any toolchain): gather fwd == all-gather with
    bwd reduce-scatter (to_model_parallel) or split; reduce-scatter fwd
    with bwd all-gather — and the overlap_comm ring forms match the
    monolithic collectives in BOTH directions."""

    def test_scatter_bwd_is_gather(self, tp8_mesh):
        # scatter fwd: rank r keeps rows [r]; bwd: all-gather of cots
        x = jnp.arange(16.0).reshape(8, 2)

        @functools.partial(shard_map, mesh=tp8_mesh, in_specs=P(),
                           out_specs=P("tp"))
        def grads(x_):
            def f(x__):
                local = tp.scatter_to_sequence_parallel_region(x__)
                w = jax.lax.axis_index("tp") + 1.0
                return jnp.sum(local) * w

            return jax.grad(f)(x_)[
                jax.lax.axis_index("tp")][None]

        g = grads(x)
        # each row's cotangent is its owner rank's weight (rank+1)
        np.testing.assert_allclose(
            np.asarray(g)[:, 0], np.arange(1.0, 9.0))

    @pytest.mark.parametrize("overlap", [False, True])
    def test_gather_not_to_model_parallel_bwd_splits(self, tp8_mesh,
                                                     overlap):
        x = jnp.ones((8, 2))

        @functools.partial(shard_map, mesh=tp8_mesh, in_specs=P("tp"),
                           out_specs=P("tp"))
        def grads(x_):
            def f(x__):
                full = tp.gather_from_sequence_parallel_region(
                    x__, False, "tp", overlap)
                w = jax.lax.axis_index("tp") + 1.0
                return jnp.sum(full) * w

            return jax.grad(f)(x_)

        g = grads(x)
        # bwd is a plain split: each shard keeps ITS row of the
        # cotangent (rank+1), no cross-rank sum
        np.testing.assert_allclose(
            np.asarray(g)[:, 0], np.arange(1.0, 9.0))

    def test_overlap_scope_inherited_by_mappings(self, tp8_mesh):
        """overlap_comm=None (the default) reads the innermost
        overlap_scope at trace time — how make_train_step(overlap_comm=)
        reaches mappings it never sees.  The ring form under scope must
        match the monolithic form traced outside it."""
        from apex_tpu.ops.collective_matmul import overlap_scope

        import apex_tpu.observability as obs

        reg = obs.configure(stderr_summary=False)
        try:
            x = jnp.arange(16.0).reshape(8, 2)

            @functools.partial(shard_map, mesh=tp8_mesh,
                               in_specs=P("tp"), out_specs=P())
            def fwd(x_):
                return tp.gather_from_sequence_parallel_region(x_)

            base = reg.counter("collectives.ring.calls").value
            out_mono = fwd(x)
            assert reg.counter("collectives.ring.calls").value == base

            @functools.partial(shard_map, mesh=tp8_mesh,
                               in_specs=P("tp"), out_specs=P())
            def fwd2(x_):
                return tp.gather_from_sequence_parallel_region(x_)

            with overlap_scope(True):
                out_ring = fwd2(x)
            assert reg.counter("collectives.ring.calls").value > base
            np.testing.assert_allclose(np.asarray(out_ring),
                                       np.asarray(out_mono))
        finally:
            obs.shutdown()


class TestRNG:
    def test_tracker_fork_streams(self):
        from apex_tpu.transformer.tensor_parallel import (
            get_rng_tracker,
            model_parallel_seed,
        )

        model_parallel_seed(1234)
        tracker = get_rng_tracker()
        with tracker.fork() as k1:
            a = jax.random.normal(k1, (4,))
        with tracker.fork() as k2:
            b = jax.random.normal(k2, (4,))
        assert not np.allclose(np.asarray(a), np.asarray(b))
        with pytest.raises(KeyError):
            with tracker.fork("nope"):
                pass

    def test_checkpoint_reexport(self):
        from apex_tpu.transformer.tensor_parallel import checkpoint

        f = checkpoint(lambda x: jnp.sin(x) * x)
        g = jax.grad(f)(1.5)
        expect = float(jnp.sin(1.5) + 1.5 * jnp.cos(1.5))
        np.testing.assert_allclose(float(g), expect, rtol=1e-6)
