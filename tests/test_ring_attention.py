"""Ring attention (context parallelism) vs single-device attention.

The reference has no long-context path to mirror (SURVEY.md §5: 'No ring
attention / context parallel / blockwise / Ulysses anywhere'), so the
oracle is our own single-device flash/materialized attention on the
gathered sequence.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.flash_attention import mha_reference
from apex_tpu.parallel.mesh import create_mesh
from apex_tpu.parallel.ring_attention import ring_attention

shard_map = jax.shard_map


def data(b, s, n, d, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, s, n, d), jnp.float32) * 0.5
    k = jnp.asarray(rng.randn(b, s, n, d), jnp.float32) * 0.5
    v = jnp.asarray(rng.randn(b, s, n, d), jnp.float32) * 0.5
    return q, k, v


def ring_fn(mesh, causal):
    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"))
    def f(q, k, v):
        return ring_attention(q, k, v, "sp", causal=causal)
    return f


class TestRingForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device(self, causal):
        b, s, n, d = 2, 256, 2, 64
        q, k, v = data(b, s, n, d)
        mesh = create_mesh(sp=4)
        got = ring_fn(mesh, causal)(q, k, v)
        want = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_unaligned_local_len(self):
        # s_local = 48 → internal padding inside each shard
        b, s, n, d = 1, 192, 2, 32
        q, k, v = data(b, s, n, d, seed=1)
        mesh = create_mesh(sp=4)
        got = ring_fn(mesh, True)(q, k, v)
        want = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_eight_way(self):
        b, s, n, d = 1, 256, 2, 32
        q, k, v = data(b, s, n, d, seed=2)
        mesh = create_mesh(sp=8)
        got = ring_fn(mesh, True)(q, k, v)
        want = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def ring_grads_fn(mesh, causal):
    """Shared shard_map grad harness: grads of a psum'd nonlinear loss
    through the ring, one definition for the MHA and grouped tests."""
    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")))
    def ring_grads(q, k, v):
        def loss(q, k, v):
            o = ring_attention(q, k, v, "sp", causal=causal)
            # local loss; total = psum over shards happens implicitly
            # through the cotangent of each shard being identical
            return jnp.sum(o * (1.0 + 0.1 * o))
        return jax.grad(
            lambda *a: jax.lax.psum(loss(*a), "sp"), argnums=(0, 1, 2))(
                q, k, v)
    return ring_grads


def ref_grads(q, k, v, causal):
    return jax.grad(
        lambda *a: jnp.sum(
            mha_reference(*a, causal=causal)
            * (1.0 + 0.1 * mha_reference(*a, causal=causal))),
        argnums=(0, 1, 2))(q, k, v)


class TestRingBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_single_device(self, causal):
        b, s, n, d = 1, 256, 2, 32
        q, k, v = data(b, s, n, d, seed=3)
        mesh = create_mesh(sp=4)
        g_ring = ring_grads_fn(mesh, causal)(q, k, v)
        g_ref = ref_grads(q, k, v, causal)
        for a, b_, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=1e-4, rtol=1e-4,
                err_msg=f"d{name}")


class TestRingGroupedKV:
    """Grouped K/V ride the ring at group width (round-5 GQA-aware
    flash): ppermute messages shrink by n/g, dK/dV come back grouped."""

    def _grouped(self, b=1, s=256, n=8, g=2, d=32, seed=31):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(b, s, n, d), jnp.float32) * 0.5
        k = jnp.asarray(rng.randn(b, s, g, d), jnp.float32) * 0.5
        v = jnp.asarray(rng.randn(b, s, g, d), jnp.float32) * 0.5
        return q, k, v

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_reference(self, causal):
        q, k, v = self._grouped()
        mesh = create_mesh(sp=4)
        got = ring_fn(mesh, causal)(q, k, v)
        want = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_grads_match_reference(self):
        q, k, v = self._grouped(seed=32)
        mesh = create_mesh(sp=4)
        g_ring = ring_grads_fn(mesh, True)(q, k, v)
        g_ref = ref_grads(q, k, v, True)
        assert g_ring[1].shape == k.shape   # grouped dk, not full-width
        for a, b_, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=1e-4, rtol=1e-4,
                err_msg=f"grouped ring d{name}")

    def test_invalid_group_ratio_rejected(self):
        q, k, v = self._grouped(n=8, g=3)
        mesh = create_mesh(sp=4)
        with pytest.raises(ValueError, match="multiple"):
            ring_fn(mesh, True)(q, k, v)


def test_ring_kernel_call_signature_interpret():
    """Regression (round-3 review): the ring path calls the flash
    _fwd_pallas/_bwd_pallas wrappers positionally; run those exact call
    shapes in interpret mode so a signature change breaks here on CPU
    instead of only at TPU trace time."""
    import jax.numpy as jnp
    import numpy as np
    from apex_tpu.ops.flash_attention import _bwd_pallas, _fwd_pallas

    rng = np.random.RandomState(0)
    bh, s, d = 2, 128, 32
    q3 = jnp.asarray(rng.randn(bh, s, d), jnp.float32)
    o, lse = _fwd_pallas(q3, q3, q3, None, None, None, 0.125, True,
                         s, 128, 128, 0.0, True, out_dtype=jnp.float32)
    assert o.shape == q3.shape
    delta = jnp.sum(o * o, axis=-1)
    dq, dk, dv = _bwd_pallas(
        q3, q3, q3, o, lse, delta, None, None, None, 0.125, True,
        s, s, 128, 128, 0.0, True, out_dtype=jnp.float32)
    assert dq.shape == q3.shape and dk.shape == q3.shape

    # the grouped (gqa=) call shapes the ring uses for GQA: b=1, n=2
    # query-head rows against g=1 kv rows, run through the actual
    # kernels in interpret mode — a grouped-specific signature or grid
    # mismatch must break here on CPU, not at TPU trace time
    k3 = jnp.asarray(rng.randn(1, s, d), jnp.float32)
    o_g, lse_g = _fwd_pallas(q3, k3, k3, None, None, None, 0.125, True,
                             s, 128, 128, 0.0, True,
                             out_dtype=jnp.float32, gqa=(2, 1))
    assert o_g.shape == q3.shape
    delta_g = jnp.sum(o_g * o_g, axis=-1)
    dq_g, dk_g, dv_g = _bwd_pallas(
        q3, k3, k3, o_g, lse_g, delta_g, None, None, None, 0.125, True,
        s, s, 128, 128, 0.0, True, out_dtype=jnp.float32, gqa=(2, 1))
    assert dq_g.shape == q3.shape
    assert dk_g.shape == k3.shape and dv_g.shape == k3.shape


def test_long_context_memory_scaling():
    """The O(s_local) per-device memory claim (ring_attention.py:11),
    demonstrated with XLA's own compiled-memory analysis at a sequence
    length where the dense path's score matrix alone is multiple GB.

    Dense attention at s=32768 materializes the s x s probs (>= 4.3 GB
    fp32); ring attention sharded 8-way touches only per-chunk buffers.
    Both are compiled abstractly (no data, nothing executed) so the
    comparison is XLA's allocation plan, not a fragile OOM probe.
    """
    b, s, n, d = 1, 32768, 1, 64
    mesh = create_mesh(sp=8)
    spec = jax.ShapeDtypeStruct((b, s, n, d), jnp.float32)

    ring_c = ring_fn(mesh, True).lower(spec, spec, spec).compile()
    dense_c = jax.jit(
        lambda q, k, v: mha_reference(q, k, v, causal=True)).lower(
            spec, spec, spec).compile()
    ring_ma = ring_c.memory_analysis()
    dense_ma = dense_c.memory_analysis()
    if ring_ma is None or dense_ma is None:
        pytest.skip("backend does not expose memory_analysis")

    dense_temp = dense_ma.temp_size_in_bytes
    ring_temp = ring_ma.temp_size_in_bytes
    # the dense plan really contains the s^2 scores...
    assert dense_temp >= s * s * 4, (dense_temp, s * s * 4)
    # ...and the ring plan is at least an order of magnitude below it
    # (per-device buffers scale with s_local = s/8, not s; the CPU
    # fallback kernel materializes s_local^2 chunk scores, the TPU
    # Pallas kernel not even that)
    assert ring_temp * 8 <= dense_temp, (ring_temp, dense_temp)


class TestContextParallelGPT:
    """Ring attention as the flagship model's core attention
    (gspmd_ctx(context_parallel=True)): loss and grads must match the
    single-device run of the same params — the long-context mode is not
    allowed to change the math."""

    def _cfg(self):
        from apex_tpu.models.config import TransformerConfig

        return TransformerConfig(
            num_layers=2, hidden_size=64, num_attention_heads=4,
            vocab_size=128, max_position_embeddings=64,
            compute_dtype=jnp.float32)

    @pytest.mark.slow   # dryrun gspmd-cp phase asserts the same fp32 parity
    def test_loss_and_grads_match_single_device(self):
        from apex_tpu.models.transformer_lm import (
            gpt_loss, gspmd_ctx, init_gpt_params)

        cfg = self._cfg()
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, 128, (2, 64)), jnp.int32)
        labels = jnp.asarray(rng.randint(0, 128, (2, 64)), jnp.int32)

        ref_l, ref_g = jax.value_and_grad(gpt_loss)(
            params, tokens, labels, cfg)

        mesh = create_mesh(dp=2, sp=4)
        ctx = gspmd_ctx(seq_axis="sp", context_parallel=True)
        with jax.set_mesh(mesh):
            got_l, got_g = jax.jit(jax.value_and_grad(
                lambda p: gpt_loss(p, tokens, labels, cfg, ctx)))(params)

        np.testing.assert_allclose(float(got_l), float(ref_l), rtol=2e-5)
        la = jax.tree_util.tree_leaves(got_g)
        lb = jax.tree_util.tree_leaves(ref_g)
        for a, b, in zip(la, lb):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4)

    @pytest.mark.slow   # gate asserts ring parity every driver run
    def test_train_step_context_parallel(self):
        from apex_tpu.models.gpt import make_gpt_train_step
        from apex_tpu.optimizers import fused_adam

        cfg = self._cfg()
        mesh = create_mesh(dp=2, sp=4)
        init, step = make_gpt_train_step(
            cfg, fused_adam(lr=1e-3), "O2", mesh, seq_axis="sp",
            context_parallel=True)
        state = init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(1)
        tokens = jnp.asarray(rng.randint(0, 128, (2, 64)), jnp.int32)
        labels = jnp.asarray(rng.randint(0, 128, (2, 64)), jnp.int32)
        losses = []
        for _ in range(3):
            state, m = step(state, tokens, labels)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses

    @pytest.mark.slow   # compile-heavy; CI slow job
    def test_cp_composes_with_remat_and_scan(self):
        """The long-context production shape uses remat + scanned
        layers (the bench s8192 config): both cp modes must compose
        with them (shard_map inside a remat'd lax.scan body)."""
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.gpt import make_gpt_train_step
        from apex_tpu.optimizers import fused_adam

        cfg = TransformerConfig(
            num_layers=2, hidden_size=64, num_attention_heads=4,
            vocab_size=128, max_position_embeddings=64,
            compute_dtype=jnp.bfloat16, remat=True, scan_layers=True)
        mesh = create_mesh(dp=2, sp=4)
        rng = np.random.RandomState(9)
        tokens = jnp.asarray(rng.randint(0, 128, (2, 64)), jnp.int32)
        labels = jnp.asarray(rng.randint(0, 128, (2, 64)), jnp.int32)
        for mode in ("ring", "ulysses"):
            init, step = make_gpt_train_step(
                cfg, fused_adam(lr=1e-3), "O2", mesh, seq_axis="sp",
                context_parallel=mode)
            state = init(jax.random.PRNGKey(0))
            state, m = step(state, tokens, labels)
            assert np.isfinite(float(m["loss"])), mode

    def test_requires_seq_axis(self):
        from apex_tpu.models.transformer_lm import gspmd_ctx

        with pytest.raises(ValueError, match="requires seq_axis"):
            gspmd_ctx(context_parallel=True)

    def test_degraded_fallback_warns_once(self, monkeypatch):
        """A cp-configured forward whose pattern forces the gathered
        dense path (mask / attention dropout) must say so loudly: the
        all-gathered K/V is the memory blowup cp exists to avoid, and
        at s8192 the silent version is an unexplained OOM."""
        import warnings

        import apex_tpu.models.transformer_lm as tlm

        monkeypatch.delenv("APEX_TPU_CP_STRICT", raising=False)
        monkeypatch.setattr(tlm, "_cp_fallback_warned", False)
        ctx = tlm.gspmd_ctx(seq_axis="sp", context_parallel=True)
        q = jnp.zeros((2, 8, 4, 8), jnp.float32)
        mask = jnp.zeros((2, 1, 8, 8), bool)
        # no active mesh (single-device debug run of the cp config):
        # the dense path gathers nothing, so no alarm may fire
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            assert tlm._cp_core_attention(
                ctx, q, q, q, True, 1.0, mask, False) is None
        with jax.set_mesh(create_mesh(dp=2, sp=4)):
            with pytest.warns(RuntimeWarning, match="DEGRADED"):
                out = tlm._cp_core_attention(
                    ctx, q, q, q, True, 1.0, mask, False)
            assert out is None  # caller takes the dense path
            with warnings.catch_warnings():  # once per process, not per call
                warnings.simplefilter("error")
                assert tlm._cp_core_attention(
                    ctx, q, q, q, True, 1.0, mask, False) is None

    def test_degraded_fallback_strict_raises(self, monkeypatch):
        import apex_tpu.models.transformer_lm as tlm

        monkeypatch.setenv("APEX_TPU_CP_STRICT", "1")
        monkeypatch.setattr(tlm, "_cp_fallback_warned", False)
        ctx = tlm.gspmd_ctx(seq_axis="sp", context_parallel=True)
        q = jnp.zeros((2, 8, 4, 8), jnp.float32)
        with jax.set_mesh(create_mesh(dp=2, sp=4)):
            with pytest.raises(ValueError, match="DEGRADED"):
                # attention dropout active → the kernels don't cover it
                tlm._cp_core_attention(ctx, q, q, q, True, 1.0, None, True)

    def test_clean_cp_path_does_not_warn(self, monkeypatch):
        """The supported pattern (causal, no mask, no attention dropout)
        must stay warning-free — the fallback alarm may not cry wolf."""
        import warnings

        import apex_tpu.models.transformer_lm as tlm

        monkeypatch.delenv("APEX_TPU_CP_STRICT", raising=False)
        monkeypatch.setattr(tlm, "_cp_fallback_warned", False)
        ctx = tlm.gspmd_ctx(seq_axis="sp", context_parallel=True)
        mesh = create_mesh(dp=2, sp=4)
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(2, 16, 4, 8), jnp.float32)
        with jax.set_mesh(mesh):
            with warnings.catch_warnings():
                warnings.simplefilter("error", RuntimeWarning)
                out = jax.jit(lambda q: tlm._cp_core_attention(
                    ctx, q, q, q, True, 1.0, None, False))(q)
        assert out is not None and out.shape == q.shape

    def test_rejects_unsupported_configs(self):
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.gpt import make_gpt_train_step
        from apex_tpu.optimizers import fused_adam

        mesh = create_mesh(dp=2, sp=4)
        bad = [
            TransformerConfig(
                num_layers=2, hidden_size=64, num_attention_heads=4,
                vocab_size=128, max_position_embeddings=64,
                attn_mask_type="padding"),
            TransformerConfig(
                num_layers=2, hidden_size=64, num_attention_heads=4,
                vocab_size=128, max_position_embeddings=64,
                attention_dropout=0.1),
        ]
        for cfg in bad:
            with pytest.raises(ValueError, match="context_parallel"):
                make_gpt_train_step(
                    cfg, fused_adam(lr=1e-3), "O2", mesh, seq_axis="sp",
                    context_parallel=True)


class TestUlysses:
    """All-to-all sequence parallelism (the second long-context mode)."""

    def test_matches_single_device(self):
        import functools

        from apex_tpu.parallel.ulysses import ulysses_attention

        b, s, n, d = 2, 256, 8, 32
        q, k, v = data(b, s, n, d, seed=21)
        mesh = create_mesh(sp=4)
        for causal in (False, True):
            f = jax.jit(jax.shard_map(
                functools.partial(ulysses_attention, axis_name="sp",
                                  causal=causal),
                mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                out_specs=P(None, "sp")))
            got = f(q, k, v)
            want = mha_reference(q, k, v, causal=causal)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5,
                err_msg=f"causal={causal}")

    def test_grads_match_single_device(self):
        import functools

        from apex_tpu.parallel.ulysses import ulysses_attention

        b, s, n, d = 1, 128, 4, 32
        q, k, v = data(b, s, n, d, seed=22)
        mesh = create_mesh(sp=4)

        def shard_loss(*a):
            f = jax.shard_map(
                functools.partial(ulysses_attention, axis_name="sp",
                                  causal=True),
                mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                out_specs=P(None, "sp"))
            o = f(*a)
            return jnp.sum(o * (1.0 + 0.1 * o))

        g = jax.jit(jax.grad(shard_loss, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(
            lambda *a: (lambda o: jnp.sum(o * (1.0 + 0.1 * o)))(
                mha_reference(*a, causal=True)),
            argnums=(0, 1, 2))(q, k, v)
        for a, r, nm in zip(g, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(r), atol=1e-4, rtol=1e-4,
                err_msg=f"d{nm}")

    def test_head_divisibility_error(self):
        import functools

        from apex_tpu.parallel.ulysses import ulysses_attention

        q, k, v = data(1, 64, 3, 16, seed=23)   # 3 heads, sp=4
        mesh = create_mesh(sp=4)
        f = jax.shard_map(
            functools.partial(ulysses_attention, axis_name="sp"),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"))
        with pytest.raises(ValueError, match="divisible"):
            f(q, k, v)

    def test_gpt_ulysses_head_check_up_front(self):
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.gpt import make_gpt_train_step
        from apex_tpu.optimizers import fused_adam

        cfg = TransformerConfig(
            num_layers=2, hidden_size=64, num_attention_heads=4,
            vocab_size=128, max_position_embeddings=64)
        mesh = create_mesh(sp=8)
        with pytest.raises(ValueError, match="divisible"):
            make_gpt_train_step(
                cfg, fused_adam(lr=1e-3), "O2", mesh, seq_axis="sp",
                context_parallel="ulysses")

    @pytest.mark.slow   # gate asserts ulysses parity every driver run
    def test_gpt_train_step_ulysses(self):
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.gpt import make_gpt_train_step
        from apex_tpu.optimizers import fused_adam

        cfg = TransformerConfig(
            num_layers=2, hidden_size=64, num_attention_heads=4,
            vocab_size=128, max_position_embeddings=64,
            compute_dtype=jnp.float32)
        mesh = create_mesh(dp=2, sp=4)
        init, step = make_gpt_train_step(
            cfg, fused_adam(lr=1e-3), "O2", mesh, seq_axis="sp",
            context_parallel="ulysses")
        state = init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(3)
        tokens = jnp.asarray(rng.randint(0, 128, (2, 64)), jnp.int32)
        labels = jnp.asarray(rng.randint(0, 128, (2, 64)), jnp.int32)
        losses = []
        for _ in range(3):
            state, m = step(state, tokens, labels)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses
