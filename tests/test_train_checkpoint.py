"""Elastic fault-tolerant training (ISSUE 11): full-state sharded
checkpoint round-trips, the async saver, manifest semantics, and
detector-driven rollback.

The acceptance bar everywhere is **bitwise**: a restored TrainState —
including the ``comm_state`` error-feedback residuals and the loss
scaler's mid-doubling window — must continue with a loss trajectory
identical bit-for-bit to an uninterrupted run, across fp32/bf16/int8
``grad_comm`` configs and the distributed_fused_adam ZeRO sharded
path.  (The kill -9 subprocess gate lives in ``__graft_entry__``'s
``ckpt_recovery`` dryrun phase; these tests cover the library
surface.)
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.checkpoint import (
    AsyncCheckpointer,
    CheckpointError,
    RecoveryGivingUp,
    RecoveryManager,
    RollbackConfig,
    all_steps,
    latest_step,
    load_manifest,
    restore_sharded,
    save_sharded,
)
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel.mesh import create_mesh


def _mlp_params(seed=7):
    r = np.random.RandomState(seed)
    return {
        "w1": jnp.asarray(r.randn(8, 16) * 0.3, jnp.float32),
        "b1": jnp.zeros((16,), jnp.float32),
        "w2": jnp.asarray(r.randn(16, 4) * 0.3, jnp.float32),
    }


def _mlp_loss(p, x, y):
    h = jnp.tanh(x @ p["w1"] + p["b1"])
    return jnp.mean((h @ p["w2"] - y) ** 2)


def _batch(i, b=16, din=8, dout=4):
    r = np.random.RandomState(50_000 + i)
    return (jnp.asarray(r.randn(b, din), jnp.float32),
            jnp.asarray(r.randn(b, dout), jnp.float32))


def _bits(x):
    return np.asarray(x).tobytes()


def _assert_tree_bitwise(a, b):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert len(la) == len(lb)
    for (ka, va), (_, vb) in zip(la, lb):
        if jax.dtypes.issubdtype(getattr(va, "dtype", None),
                                 jax.dtypes.prng_key):
            va, vb = jax.random.key_data(va), jax.random.key_data(vb)
        assert _bits(va) == _bits(vb), f"{jax.tree_util.keystr(ka)}"


@pytest.fixture(scope="module")
def mesh():
    return create_mesh()   # dp=8 on the conftest virtual devices


# ---------------------------------------------------------------------------
# full-state round-trips
# ---------------------------------------------------------------------------


class TestTrainStateRoundTrip:
    @pytest.mark.parametrize("grad_comm", [None, "fp32", "bf16", "int8"])
    def test_bitwise_trajectory_across_grad_comm(self, tmp_path, mesh,
                                                 grad_comm):
        """save → restore is bitwise and the continued loss trajectory
        is identical to the unkilled run — through the plain step and
        every compressed-collective wire dtype (int8 carries live
        error-feedback residuals in ``comm_state``)."""
        from apex_tpu.parallel.distributed import make_ddp_train_step

        init, step = make_ddp_train_step(
            _mlp_loss, fused_adam(lr=1e-2), "O2", mesh, batch_axes=2,
            grad_comm=grad_comm)
        state = init(_mlp_params())
        ref_losses = []
        for i in range(1, 7):
            x, y = _batch(i)
            state, m = step(state, x, y)
            ref_losses.append(_bits(m["loss"]))
            if i == 3:
                if grad_comm == "int8":
                    res = sum(float(jnp.sum(jnp.abs(l))) for l in
                              jax.tree_util.tree_leaves(state.comm_state))
                    assert res > 0.0, "int8 EF residuals all zero"
                save_sharded(tmp_path, 3, state)
                snapshot = state
        resumed = restore_sharded(tmp_path, init(_mlp_params()))
        _assert_tree_bitwise(snapshot, resumed)
        for i in range(4, 7):
            x, y = _batch(i)
            resumed, m = step(resumed, x, y)
            assert _bits(m["loss"]) == ref_losses[i - 1], (
                f"loss at step {i} diverged after restore "
                f"(grad_comm={grad_comm})")

    def test_scaler_mid_doubling_window(self, tmp_path):
        """The scaler's ``unskipped`` counter survives the round-trip:
        a restore 1 step before a window doubling doubles at exactly
        the same step as the unkilled run (same scale bits)."""
        from apex_tpu.amp import scaler as scaler_lib
        from apex_tpu.amp.frontend import AmpState, make_train_step
        from apex_tpu.amp.policy import policy_for_opt_level

        cfg, st0 = scaler_lib.init_loss_scale("dynamic", scale_window=4)
        amp_state = AmpState(policy_for_opt_level("O2"), cfg, st0)
        init, step = make_train_step(
            _mlp_loss, fused_adam(lr=1e-2), amp_state)
        state = init(_mlp_params())
        scales = []
        for i in range(1, 7):
            x, y = _batch(i)
            state, m = step(state, x, y)
            scales.append(_bits(state.loss_scale_state.loss_scale))
            if i == 3:
                assert int(state.loss_scale_state.unskipped) == 3, (
                    "fixture: expected a mid-window counter")
                save_sharded(tmp_path, 3, state)
        resumed = restore_sharded(tmp_path, init(_mlp_params()))
        assert int(resumed.loss_scale_state.unskipped) == 3
        for i in range(4, 7):
            x, y = _batch(i)
            resumed, m = step(resumed, x, y)
            assert _bits(resumed.loss_scale_state.loss_scale) == \
                scales[i - 1], f"scale diverged at step {i}"

    def test_distributed_fused_adam_sharded_path(self, tmp_path, mesh):
        """ZeroTrainState (flat dp-sharded master/m/v + the full-size
        rank-local int8 residual) round-trips bitwise; the manifest
        records one shard per rank slice via ``zero_state_specs``'s
        placements."""
        from apex_tpu.contrib.optimizers.distributed_fused_adam import (
            make_distributed_adam_train_step, zero_state_specs)

        init, step = make_distributed_adam_train_step(
            _mlp_loss, mesh, grad_comm="int8")
        state = init(_mlp_params())
        for i in range(1, 4):
            x, y = _batch(i)
            state, m = step(state, x, y)
        specs = zero_state_specs(state)
        assert specs.master_shard == P("dp")
        assert specs.comm_residual == P("dp")
        save_sharded(tmp_path, 3, state)
        manifest = load_manifest(tmp_path, 3)
        by_key = {l["key"]: l for l in manifest["leaves"]}
        assert len(by_key[".master_shard"]["shards"]) == 8
        assert len(by_key[".comm_residual"]["shards"]) == 8
        resumed = restore_sharded(tmp_path, init(_mlp_params()))
        _assert_tree_bitwise(state, resumed)
        x, y = _batch(4)
        _, m1 = step(state, x, y)
        _, m2 = step(resumed, x, y)
        assert _bits(m1["loss"]) == _bits(m2["loss"])

    def test_frontend_hooks(self, tmp_path):
        """amp.frontend.save_train_state / restore_train_state are the
        TrainState-level surface of the same machinery."""
        from apex_tpu.amp.frontend import (
            make_train_step, restore_train_state, save_train_state)

        init, step = make_train_step(_mlp_loss, fused_adam(lr=1e-2), "O2")
        state = init(_mlp_params())
        x, y = _batch(1)
        state, _ = step(state, x, y)
        save_train_state(tmp_path, 1, state, keep=2)
        restored = restore_train_state(tmp_path, init(_mlp_params()))
        _assert_tree_bitwise(state, restored)

    def test_typed_prng_key_and_mixed_leaves(self, tmp_path):
        key = jax.random.key(42)
        tree = {"key": key, "raw": jax.random.PRNGKey(1),
                "bf16": jnp.asarray([1.5, -2.25, 3.0], jnp.bfloat16),
                "i8": jnp.asarray([-4, 7], jnp.int8),
                "np": np.arange(6, dtype=np.float32).reshape(2, 3)}
        save_sharded(tmp_path, 1, tree)
        like = {"key": jax.random.key(0), "raw": jax.random.PRNGKey(0),
                "bf16": jnp.zeros(3, jnp.bfloat16),
                "i8": jnp.zeros(2, jnp.int8),
                "np": np.zeros((2, 3), np.float32)}
        r = restore_sharded(tmp_path, like, step=1)
        _assert_tree_bitwise(tree, r)
        # the same key stream continues identically
        assert _bits(jax.random.normal(r["key"], (3,))) == \
            _bits(jax.random.normal(key, (3,)))


# ---------------------------------------------------------------------------
# manifest semantics: atomic commit, digests, retention, validation
# ---------------------------------------------------------------------------


class TestManifest:
    def test_torn_snapshot_is_invisible(self, tmp_path):
        state = {"a": jnp.arange(4.0)}
        save_sharded(tmp_path, 1, state)
        save_sharded(tmp_path, 2, state)
        # simulate a crash between shard write and manifest commit
        os.remove(tmp_path / "step_00000002" / "MANIFEST.json")
        assert all_steps(tmp_path) == [1]
        assert latest_step(tmp_path) == 1
        restored = restore_sharded(tmp_path, {"a": jnp.zeros(4)})
        assert _bits(restored["a"]) == _bits(state["a"])

    def test_corrupt_manifest_is_invisible(self, tmp_path):
        save_sharded(tmp_path, 1, {"a": jnp.arange(4.0)})
        save_sharded(tmp_path, 2, {"a": jnp.arange(4.0)})
        with open(tmp_path / "step_00000002" / "MANIFEST.json", "w") as f:
            f.write('{"manifest_schema_version": 1, "truncated')
        assert all_steps(tmp_path) == [1]

    def test_digest_detects_corruption(self, tmp_path):
        save_sharded(tmp_path, 1, {"a": jnp.arange(64.0)})
        shard = tmp_path / "step_00000001" / "shard_p0.bin"
        raw = bytearray(shard.read_bytes())
        raw[7] ^= 0xFF
        shard.write_bytes(bytes(raw))
        with pytest.raises(CheckpointError, match="digest"):
            restore_sharded(tmp_path, {"a": jnp.zeros(64)})
        # verify_digests=False loads the (corrupt) bytes — caller's call
        restore_sharded(tmp_path, {"a": jnp.zeros(64)},
                        verify_digests=False)

    def test_retention_policy(self, tmp_path):
        state = {"a": jnp.arange(8.0)}
        for s in (1, 2, 3, 4):
            save_sharded(tmp_path, s, state, keep=2)
        assert all_steps(tmp_path) == [3, 4]
        # a torn attempt older than the newest committed step is swept
        torn = tmp_path / "step_00000002"
        torn.mkdir()
        (torn / "shard_p0.bin").write_bytes(b"junk")
        save_sharded(tmp_path, 5, state, keep=2)
        assert all_steps(tmp_path) == [4, 5]
        assert not torn.exists()

    def test_structure_shape_dtype_validation(self, tmp_path):
        save_sharded(tmp_path, 1, {"a": jnp.zeros((4, 4), jnp.float32),
                                   "b": jnp.zeros(3, jnp.int32)})
        with pytest.raises(CheckpointError, match="structure"):
            restore_sharded(tmp_path, {"a": jnp.zeros((4, 4))})
        with pytest.raises(CheckpointError, match="shape"):
            restore_sharded(tmp_path, {"a": jnp.zeros((4, 2)),
                                       "b": jnp.zeros(3, jnp.int32)})
        with pytest.raises(CheckpointError, match="dtype"):
            restore_sharded(tmp_path, {"a": jnp.zeros((4, 4)),
                                       "b": jnp.zeros(3, jnp.float32)})

    def test_extra_payload(self, tmp_path):
        save_sharded(tmp_path, 7, {"a": jnp.zeros(2)},
                     extra={"data_position": 1234})
        assert load_manifest(tmp_path)["extra"]["data_position"] == 1234

    def test_recommit_same_step(self, tmp_path):
        save_sharded(tmp_path, 1, {"a": jnp.zeros(4)})
        save_sharded(tmp_path, 1, {"a": jnp.ones(4)})
        r = restore_sharded(tmp_path, {"a": jnp.zeros(4)})
        assert _bits(r["a"]) == _bits(jnp.ones(4))

    def test_multi_process_fragment_merge(self, tmp_path):
        """The multi-host commit protocol: non-zero ranks write shard
        + fragment only (NOT visible as a checkpoint), process 0
        merges every fragment into the single committed manifest —
        replicated-leaf duplicates deduplicated, per-process byte
        totals summed."""
        tree = {"a": jnp.arange(16.0)}
        save_sharded(tmp_path, 1, tree, process_index=1,
                     expected_processes=2)
        # no commit yet: only a fragment exists
        assert latest_step(tmp_path) is None
        assert (tmp_path / "step_00000001"
                / "MANIFEST.p1.json").exists()
        save_sharded(tmp_path, 1, tree, process_index=0,
                     expected_processes=2)
        assert latest_step(tmp_path) == 1
        manifest = load_manifest(tmp_path, 1)
        assert manifest["process_count"] == 2
        assert manifest["total_bytes"] == 128   # 64 bytes per process
        (leaf,) = manifest["leaves"]
        # both processes hold the same (replicated) full slice: dedup
        # keeps one shard entry
        assert len(leaf["shards"]) == 1
        # fragments are cleaned up after the merge
        assert not (tmp_path / "step_00000001"
                    / "MANIFEST.p0.json").exists()
        r = restore_sharded(tmp_path, {"a": jnp.zeros(16)})
        assert _bits(r["a"]) == _bits(tree["a"])

    def test_merge_times_out_on_missing_peer(self, tmp_path):
        with pytest.raises(CheckpointError, match="fragments"):
            save_sharded(tmp_path, 1, {"a": jnp.zeros(4)},
                         process_index=0, expected_processes=2,
                         merge_timeout_s=0.3)
        assert latest_step(tmp_path) is None   # stays uncommitted


# ---------------------------------------------------------------------------
# elastic resume (the manifest's per-leaf layout metadata)
# ---------------------------------------------------------------------------


class TestElasticResume:
    def test_restore_onto_different_dp_degree(self, tmp_path, mesh):
        arr = jnp.asarray(np.arange(64, dtype=np.float32).reshape(8, 8))
        sharded = jax.device_put(arr, NamedSharding(mesh, P("dp")))
        save_sharded(tmp_path, 1, {"a": sharded})
        mesh4 = create_mesh(dp=4, devices=jax.devices()[:4])
        tmpl = jax.device_put(jnp.zeros((8, 8)),
                              NamedSharding(mesh4, P("dp")))
        with pytest.raises(CheckpointError, match="mesh geometry"):
            restore_sharded(tmp_path, {"a": tmpl})
        r = restore_sharded(tmp_path, {"a": tmpl}, reshard=True)
        assert _bits(r["a"]) == _bits(arr)
        assert len(r["a"].addressable_shards) == 4


# ---------------------------------------------------------------------------
# async saver
# ---------------------------------------------------------------------------


class TestAsyncSaver:
    def test_durable_after_wait_and_bounded_in_flight(self, tmp_path):
        state = {"a": jnp.arange(1024.0)}
        with AsyncCheckpointer(tmp_path, keep=2) as ck:
            ck.save(1, state)
            ck.save(2, state)   # waits out save 1 first
            res = ck.wait()
        assert res.step == 2 and res.bytes == 4096
        assert 0.0 <= res.overlap_ratio <= 1.0
        assert all_steps(tmp_path) == [1, 2]

    def test_donation_safety(self, tmp_path):
        """The saver snapshots on-device BEFORE returning: donating the
        state to the next step must not corrupt the in-flight save."""
        double = jax.jit(lambda t: jax.tree_util.tree_map(
            lambda x: x * 2, t), donate_argnums=0)
        state = {"a": jnp.arange(4096.0)}
        expect = _bits(state["a"])
        with AsyncCheckpointer(tmp_path) as ck:
            ck.save(1, state)
            state = double(state)   # deletes the original buffers
        r = restore_sharded(tmp_path, {"a": jnp.zeros(4096)})
        assert _bits(r["a"]) == expect

    def test_background_failure_surfaces_on_next_call(self, tmp_path):
        target = tmp_path / "not_a_dir"
        target.write_text("occupied")
        ck = AsyncCheckpointer(str(target))
        ck.save(1, {"a": jnp.zeros(4)})
        with pytest.raises(CheckpointError, match="background"):
            ck.wait()
        ck.close()   # error was consumed; close is clean

    def test_save_telemetry(self, tmp_path):
        from apex_tpu.observability import configure, shutdown
        from apex_tpu.observability import metrics as _telemetry

        configure(stderr_summary=False)
        try:
            reg = _telemetry.registry()
            with AsyncCheckpointer(tmp_path) as ck:
                ck.save(1, {"a": jnp.arange(256.0)})
            assert reg.counter("checkpoint.saves").value == 1
            assert reg.counter("checkpoint.bytes").value == 1024
            assert reg.gauge("checkpoint.overlap_ratio").value is not None
            restore_sharded(tmp_path, {"a": jnp.zeros(256)})
            assert reg.counter("checkpoint.restores").value == 1
        finally:
            shutdown()


# ---------------------------------------------------------------------------
# detector-driven recovery
# ---------------------------------------------------------------------------


def _recovery_loop(tmp_path, nan_at=(7,), steps=10, config=None,
                   telemetry=True):
    from apex_tpu.amp.frontend import make_train_step
    from apex_tpu.observability.metrics import record_step_metrics

    init, step = make_train_step(_mlp_loss, fused_adam(lr=1e-2), "O2")
    kw = {"config": config} if config is not None else {}
    mgr = RecoveryManager(tmp_path, save_every=2, keep=3, **kw)
    state = init(_mlp_params())
    rolled_steps = []
    for i in range(1, steps + 1):
        x, y = _batch(i)
        if i in nan_at:
            x = x * np.nan
        state, m = step(state, x, y)
        if telemetry:
            record_step_metrics(m)
        state, rolled = mgr.after_step(state, m)
        if rolled:
            rolled_steps.append(i)
    mgr.saver.close()
    return mgr, state, m, rolled_steps


class TestRecovery:
    def test_nan_triggers_rollback_rewarm_and_incident(self, tmp_path):
        from apex_tpu.observability import configure, shutdown
        from apex_tpu.observability import metrics as _telemetry

        flight = tmp_path / "flight.json"
        configure(stderr_summary=False, flight_recorder=str(flight))
        try:
            reg = _telemetry.registry()
            mgr, state, m, rolled = _recovery_loop(tmp_path / "ck")
            assert rolled == [7]
            # the NaN step was skipped (counter stayed 6); the newest
            # committed snapshot at rollback time was the step-6 one
            assert mgr.last_rollback_step == 6
            assert np.isfinite(float(m["loss"]))
            assert reg.counter("checkpoint.rollbacks").value == 1
            kinds = [a.kind for a in reg.detectors.anomalies]
            assert "nan_inf" in kinds and "rollback" in kinds
            # re-warm window open, ramping toward 1
            assert 0.1 <= mgr.lr_scale() < 1.0
            sched = mgr.rewarm_schedule(1e-3)
            anchor = mgr.last_rollback_step
            assert float(sched(anchor)) == pytest.approx(1e-4)
            assert float(sched(anchor + 100)) == pytest.approx(1e-3)
        finally:
            shutdown()
        # the incident dump exists and the health report renders the
        # rollback with its re-warm schedule (ISSUE 11 satellite)
        assert flight.exists()
        import importlib.util
        import io

        spec = importlib.util.spec_from_file_location(
            "health_report", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "tools", "health_report.py"))
        health = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(health)
        final = tmp_path / "flight.final.json"
        with open(final if final.exists() else flight) as f:
            doc = json.load(f)
        out = io.StringIO()
        health.render_dump(doc, out=out)
        text = out.getvalue()
        assert "rollback" in text
        assert "resumed from checkpoint step" in text
        assert "LR re-warm" in text

    def test_recovery_without_telemetry(self, tmp_path):
        """Telemetry off: the manager's own non-finite-loss check still
        recovers the run (no detectors exist to feed)."""
        from apex_tpu.observability import metrics as _telemetry

        assert _telemetry.registry() is None
        mgr, state, m, rolled = _recovery_loop(
            tmp_path, telemetry=False)
        assert rolled == [7]
        assert np.isfinite(float(m["loss"]))

    def test_gives_up_after_max_rollbacks(self, tmp_path):
        cfg = RollbackConfig(max_rollbacks=2)
        with pytest.raises(RecoveryGivingUp):
            _recovery_loop(tmp_path, nan_at=(5, 6, 7, 8), steps=10,
                           config=cfg, telemetry=False)

    def test_no_checkpoint_to_roll_back_to(self, tmp_path):
        with pytest.raises(CheckpointError, match="no committed"):
            _recovery_loop(tmp_path, nan_at=(1,), steps=2,
                           telemetry=False)

    def test_recovery_survives_full_anomaly_log(self, tmp_path):
        """The bank's in-memory anomaly list is bounded (MAX_KEPT);
        recovery reads the MONOTONIC fired_counts, so a long run whose
        diagnostic log filled up still rolls back on a fresh NaN."""
        from apex_tpu.observability import configure, shutdown
        from apex_tpu.observability import metrics as _telemetry
        from apex_tpu.observability.detectors import Anomaly

        configure(stderr_summary=False)
        try:
            bank = _telemetry.registry().detectors
            for i in range(bank.MAX_KEPT):
                bank._fire(Anomaly("scaler_thrash", i, "diagnostic"))
            assert len(bank.anomalies) == bank.MAX_KEPT
            mgr, state, m, rolled = _recovery_loop(tmp_path)
            assert rolled == [7]
        finally:
            shutdown()

    def test_preexisting_anomalies_are_not_triggers(self, tmp_path):
        """Anomalies fired BEFORE the manager existed (a warmup
        phase's spike) must not roll back — or kill — a healthy run
        on its first step."""
        from apex_tpu.observability import configure, shutdown
        from apex_tpu.observability import metrics as _telemetry
        from apex_tpu.observability.detectors import Anomaly

        configure(stderr_summary=False)
        try:
            bank = _telemetry.registry().detectors
            bank._fire(Anomaly("nan_inf", 3, "historical incident"))
            bank.nan_inf.fired = False   # latch belongs to the past run
            mgr, state, m, rolled = _recovery_loop(
                tmp_path, nan_at=())
            assert rolled == []
            assert mgr.rollbacks == 0
        finally:
            shutdown()

    def test_no_resave_while_counter_stalls(self, tmp_path):
        """A scaler-overflow streak stalls the state's counter; if it
        stalls ON a save_every multiple, after_step must not re-save
        (de-commit + rewrite) the same step every iteration."""

        class _Stuck:
            step = jnp.asarray(4, jnp.int32)

        saves = []

        class _Saver:
            last_result = None

            def save(self, step, state, extra=None):
                saves.append(step)

            def wait(self):
                return None

            def close(self):
                return None

        mgr = RecoveryManager(tmp_path, save_every=4)
        mgr.saver = _Saver()
        for _ in range(5):
            mgr.after_step(_Stuck(), {"loss": 1.0})
        assert saves == [4]

    def test_second_divergence_after_recovery_is_detected(self, tmp_path):
        """The NaN first-seen latch re-arms on rollback: a second NaN
        after recovery triggers a second rollback, not silence."""
        from apex_tpu.observability import configure, shutdown

        configure(stderr_summary=False)
        try:
            mgr, state, m, rolled = _recovery_loop(
                tmp_path, nan_at=(5, 9), steps=12)
            assert rolled == [5, 9]
            assert mgr.rollbacks == 2
        finally:
            shutdown()
