"""Pipeline-parallel schedule tests.

Reference analogs: tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py
(pipeline loss vs analytically-derived sequential target), test_p2p_comm.py,
test_microbatches.py.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.mesh import create_mesh
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    pipeline_forward,
    send_backward_recv_backward,
    send_forward_recv_forward,
    split_batch_into_microbatches,
)

shard_map = jax.shard_map

PP = 4
N_MICRO = 8
H = 16
MB = 2


def _pp_mesh():
    # 8 devices → pp=4, dp=2; pipeline tests map over 'pp' only by
    # replicating across dp.
    return create_mesh(pp=PP, dp=2)


def _stage_params(rng, n_stages):
    return {
        "w": jnp.asarray(rng.randn(n_stages, H, H) * 0.3, jnp.float32),
        "b": jnp.asarray(rng.randn(n_stages, H) * 0.1, jnp.float32),
    }


def _stage_fn(p, x):
    # params arrive [1, H, H] per device (leading pp shard dim)
    w = p["w"].reshape(H, H)
    b = p["b"].reshape(H)
    return jnp.tanh(x @ w + b)


def _sequential_loss_and_grads(params, mbs, targets):
    def loss_fn(p):
        losses = []
        for i in range(N_MICRO):
            h = mbs[i]
            for s in range(PP):
                h = jnp.tanh(h @ p["w"][s] + p["b"][s])
            losses.append(jnp.mean((h - targets[i]) ** 2))
        return jnp.mean(jnp.stack(losses))

    return jax.value_and_grad(loss_fn)(params)


class TestP2P:
    def test_forward_backward_shift(self):
        mesh = _pp_mesh()

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("pp"), out_specs=P("pp")
        )
        def fwd(x):
            return send_forward_recv_forward(x)

        x = jnp.arange(4.0).reshape(4, 1)
        out = fwd(x)
        np.testing.assert_allclose(np.asarray(out).ravel(), [0, 0, 1, 2])

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("pp"), out_specs=P("pp")
        )
        def bwd(x):
            return send_backward_recv_backward(x)

        out = bwd(x)
        np.testing.assert_allclose(np.asarray(out).ravel(), [1, 2, 3, 0])


class TestPipelineMatchesSequential:
    def setup_method(self, method):
        rng = np.random.RandomState(0)
        self.params = _stage_params(rng, PP)
        self.mbs = jnp.asarray(rng.randn(N_MICRO, MB, H), jnp.float32)
        self.targets = jnp.asarray(rng.randn(N_MICRO, MB, H), jnp.float32)

    def test_pipeline_forward_outputs(self):
        mesh = _pp_mesh()

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P("pp"), P()), out_specs=P("pp"),
        )
        def run(params, mbs):
            outs = pipeline_forward(
                _stage_fn, params, mbs, n_micro=N_MICRO
            )
            return jax.tree_util.tree_map(lambda v: v[None], outs)

        outs = run(self.params, self.mbs)   # [pp, n_micro, MB, H]
        # sequential forward
        expect = []
        for i in range(N_MICRO):
            h = self.mbs[i]
            for s in range(PP):
                h = jnp.tanh(h @ self.params["w"][s] + self.params["b"][s])
            expect.append(h)
        expect = np.stack(expect)
        # outputs are only banked on the last stage
        np.testing.assert_allclose(np.asarray(outs[-1]), expect, atol=1e-5)

    @pytest.mark.parametrize("remat", [True, False])
    def test_1f1b_loss_and_grads_match_sequential(self, remat):
        mesh = _pp_mesh()
        loss_ref, grads_ref = _sequential_loss_and_grads(
            self.params, self.mbs, self.targets
        )

        def loss_fn(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P("pp"), P(), P()), out_specs=(P("pp"), P("pp")),
        )
        def run(params, mbs, tgts):
            loss, grads = forward_backward_pipelining_without_interleaving(
                _stage_fn, mbs, params,
                n_micro=N_MICRO, loss_fn=loss_fn, loss_batch=tgts,
                remat=remat,
            )
            return jnp.reshape(loss, (1,)), grads

        loss, grads = run(self.params, self.mbs, self.targets)
        np.testing.assert_allclose(np.asarray(loss),
                                   np.full(PP, float(loss_ref)), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grads["w"]),
                                   np.asarray(grads_ref["w"]), atol=1e-5)
        np.testing.assert_allclose(np.asarray(grads["b"]),
                                   np.asarray(grads_ref["b"]), atol=1e-5)

    @pytest.mark.slow   # dryrun vpp phase covers interleaved parity on the GPT model
    def test_interleaved_loss_and_grads_match_sequential(self):
        """vpp=2 on pp=2: 4 chunks total, chunk c on device c%2, slot c//2.
        Model = same 4 stages; sequential reference unchanged."""
        mesh = create_mesh(pp=2, dp=4)
        loss_ref, grads_ref = _sequential_loss_and_grads(
            self.params, self.mbs, self.targets
        )

        # re-stack params: device d slot j holds chunk c = d + 2*j
        # → stacked_per_device[d] = params for chunks [d, d+2]
        w = np.asarray(self.params["w"])
        b = np.asarray(self.params["b"])
        w_dev = np.stack([w[[d, d + 2]] for d in range(2)])  # [2, 2, H, H]
        b_dev = np.stack([b[[d, d + 2]] for d in range(2)])
        stacked = {"w": jnp.asarray(w_dev), "b": jnp.asarray(b_dev)}

        def chunk_fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        def loss_fn(out, tgt):
            return jnp.mean((out - tgt) ** 2)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P("pp"), P(), P()), out_specs=(P("pp"), P("pp")),
        )
        def run(params, mbs, tgts):
            params = jax.tree_util.tree_map(lambda v: v[0], params)
            loss, grads = forward_backward_pipelining_with_interleaving(
                chunk_fn, mbs, params,
                n_micro=N_MICRO, num_model_chunks=2,
                loss_fn=loss_fn, loss_batch=tgts,
            )
            return (
                jnp.reshape(loss, (1,)),
                jax.tree_util.tree_map(lambda v: v[None], grads),
            )

        loss, grads = run(stacked, self.mbs, self.targets)
        np.testing.assert_allclose(np.asarray(loss), float(loss_ref),
                                   rtol=1e-5)
        gw = np.asarray(grads["w"])    # [2, 2, H, H] device-major
        gb = np.asarray(grads["b"])
        for c in range(4):
            d, j = c % 2, c // 2
            np.testing.assert_allclose(
                gw[d, j], np.asarray(grads_ref["w"])[c], atol=1e-5
            )
            np.testing.assert_allclose(
                gb[d, j], np.asarray(grads_ref["b"])[c], atol=1e-5
            )


class TestNoPipelining:
    def test_accumulated_grads(self):
        rng = np.random.RandomState(1)
        params = {"w": jnp.asarray(rng.randn(H, H) * 0.2, jnp.float32)}
        batch = jnp.asarray(rng.randn(4, 2, H), jnp.float32)

        def step(p, mb):
            return jnp.mean((mb @ p["w"]) ** 2)

        loss, grads = forward_backward_no_pipelining(step, batch, params)
        # reference: average of per-microbatch losses/grads
        ref_loss, ref_grads = jax.value_and_grad(
            lambda p: jnp.mean(jnp.stack([
                step(p, batch[i]) for i in range(4)
            ]))
        )(params)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(grads["w"]),
                                   np.asarray(ref_grads["w"]), atol=1e-6)

    def test_selector(self):
        assert (
            get_forward_backward_func(None, 1)
            is forward_backward_no_pipelining
        )
        assert (
            get_forward_backward_func(None, 4)
            is forward_backward_pipelining_without_interleaving
        )
        assert (
            get_forward_backward_func(2, 4)
            is forward_backward_pipelining_with_interleaving
        )


class TestMicrobatches:
    def test_constant_calculator(self):
        from apex_tpu.transformer.pipeline_parallel import (
            get_num_microbatches,
            setup_microbatch_calculator,
        )

        setup_microbatch_calculator(0, None, 64, 4, 2)
        assert get_num_microbatches() == 8
        with pytest.raises(ValueError):
            setup_microbatch_calculator(0, None, 63, 4, 2)

    def test_rampup_calculator(self):
        from apex_tpu.transformer.microbatches import (
            RampupBatchsizeNumMicroBatches,
        )

        calc = RampupBatchsizeNumMicroBatches(
            start_batch_size=8, batch_size_increment=8, ramup_samples=80,
            global_batch_size=32, micro_batch_size=2, data_parallel_size=2,
        )
        assert calc.get_current_global_batch_size() == 8
        calc.update(40, False)
        assert calc.get_current_global_batch_size() == 16
        calc.update(200, False)
        assert calc.get_current_global_batch_size() == 32
        assert calc.get() == 8

    def test_split_batch(self):
        b = {"x": jnp.ones((8, 3))}
        mbs = split_batch_into_microbatches(b, 4)
        assert mbs["x"].shape == (4, 2, 3)
        with pytest.raises(ValueError):
            split_batch_into_microbatches({"x": jnp.ones((7, 3))}, 4)
