"""Paged KV-cache decoding (models/generate.py cache_layout="paged"):
layout equivalence against the contiguous stripe cache, prefill-vs-
stepwise page equivalence, and the removed scalar-pos path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import (
    decode_step, generate, init_kv_cache, prefill)
from apex_tpu.models.transformer_lm import gpt_forward, init_gpt_params


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


def _ragged_batch(rng, vocab, lens):
    prompts = [rng.randint(0, vocab, (n,)).astype(np.int32) for n in lens]
    batch = np.zeros((len(lens), max(lens)), np.int32)
    for i, p in enumerate(prompts):
        batch[i, : len(p)] = p
    return jnp.asarray(batch), prompts


class TestPagedCacheInit:
    def test_paged_shapes_and_linear_tables(self):
        cfg = _cfg()
        cache = init_kv_cache(cfg, 3, 20, cache_layout="paged",
                              block_size=8)
        mb = 3                                   # ceil(20/8)
        assert cache["k"].shape == (2, 9, 8, 4, 16)   # [L, nb, bs, g, dh]
        assert cache["block_tables"].shape == (3, mb)
        np.testing.assert_array_equal(
            np.asarray(cache["block_tables"]),
            np.arange(9).reshape(3, 3))
        assert cache["pos"].shape == (3,)

    def test_bad_layout_raises(self):
        cfg = _cfg()
        with pytest.raises(ValueError, match="cache_layout"):
            init_kv_cache(cfg, 1, 8, cache_layout="slabbed")
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        with pytest.raises(ValueError, match="cache_layout"):
            generate(params, jnp.asarray([[1, 2]], jnp.int32), cfg,
                     max_new_tokens=2, cache_layout="slabbed")

    def test_cache_dtype_override(self):
        cfg = _cfg()
        cache = init_kv_cache(cfg, 2, 16, cache_dtype=jnp.bfloat16,
                              cache_layout="paged", block_size=8)
        assert cache["k"].dtype == jnp.bfloat16


class TestScalarPosRemoved:
    def test_scalar_pos_cache_raises(self):
        """PR 6 satellite: the legacy scalar-counter broadcast path is
        gone — a scalar pos is a stale-caller bug and must fail loudly,
        not silently broadcast."""
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        cache = init_kv_cache(cfg, 2, 8)
        cache["pos"] = jnp.int32(0)              # legacy scalar form
        with pytest.raises(ValueError, match="scalar-counter"):
            decode_step(params, jnp.asarray([1, 2], jnp.int32), cache,
                        cfg)


# the equivalence suites run every case under both layouts; paged adds
# a deliberately awkward block_size (prompt lengths straddle blocks)
LAYOUTS = [("contiguous", None), ("paged", 4), ("paged", 8)]


class TestLayoutEquivalence:
    @pytest.mark.parametrize("variant", [
        {},
        {"position_embedding_type": "rope", "num_query_groups": 2},
        pytest.param({"activation": "swiglu", "normalization": "rmsnorm"},
                     marks=pytest.mark.slow),
    ])
    def test_paged_greedy_matches_contiguous(self, variant):
        """The tentpole acceptance pin: paged decode must be
        token-for-token identical to contiguous decode under greedy
        sampling, ragged batch included."""
        cfg = _cfg(**variant)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        lens = [3, 9, 6]                          # straddle bs=4 and 8
        batch, _ = _ragged_batch(rng, cfg.vocab_size, lens)
        new = 7
        want = np.asarray(generate(
            params, batch, cfg, max_new_tokens=new,
            prompt_lens=jnp.asarray(lens)))
        for bs in (4, 8):
            got = np.asarray(generate(
                params, batch, cfg, max_new_tokens=new,
                prompt_lens=jnp.asarray(lens), cache_layout="paged",
                block_size=bs))
            np.testing.assert_array_equal(got, want,
                                          err_msg=f"block_size={bs}")

    def test_eos_early_exit_matches(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(2), cfg)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        ref = np.asarray(generate(params, prompt, cfg, max_new_tokens=8))
        eos = int(ref[0, 4])
        a = np.asarray(generate(params, prompt, cfg, max_new_tokens=8,
                                eos_token_id=eos))
        b = np.asarray(generate(params, prompt, cfg, max_new_tokens=8,
                                eos_token_id=eos, cache_layout="paged",
                                block_size=4))
        np.testing.assert_array_equal(a, b)

    def test_sampling_seeded_identical_across_layouts(self):
        """Same rng + same logits ⇒ the sampled trajectory must agree
        across layouts too (the sampler sees identical inputs)."""
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(3), cfg)
        prompt = jnp.asarray([[5, 6, 7]], jnp.int32)
        a = generate(params, prompt, cfg, max_new_tokens=6,
                     temperature=0.9, top_k=8,
                     rng=jax.random.PRNGKey(11))
        b = generate(params, prompt, cfg, max_new_tokens=6,
                     temperature=0.9, top_k=8,
                     rng=jax.random.PRNGKey(11), cache_layout="paged",
                     block_size=4)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestPagedPrefill:
    @pytest.mark.parametrize("variant,n", [
        ({}, 8),                                   # n % bs == 0
        ({}, 9),                                   # n % bs == 1
        ({"position_embedding_type": "rope", "num_query_groups": 2}, 7),
    ])
    def test_prefill_pages_match_stepwise_decode(self, variant, n):
        """Filling the pool by whole-page prefill scatter and by
        feeding tokens one-by-one through the paged decode must land
        the same K/V in the same physical cells — the cache-equivalence
        contract, paged edition, at block-boundary lengths."""
        cfg = _cfg(**variant)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        b = 2
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, n)),
                             jnp.int32)
        step = init_kv_cache(cfg, b, n + 2, cache_layout="paged",
                             block_size=4)
        for i in range(n):
            _, step = decode_step(params, tokens[:, i], step, cfg)
        pre = init_kv_cache(cfg, b, n + 2, cache_layout="paged",
                            block_size=4)
        logits, pre = prefill(params, tokens, cfg, cache=pre)
        np.testing.assert_allclose(
            np.asarray(pre["k"]), np.asarray(step["k"]),
            atol=2e-4, rtol=2e-4, err_msg=f"{variant} n={n} k")
        np.testing.assert_allclose(
            np.asarray(pre["v"]), np.asarray(step["v"]),
            atol=2e-4, rtol=2e-4, err_msg=f"{variant} n={n} v")
        np.testing.assert_array_equal(np.asarray(pre["pos"]),
                                      np.full((b,), n))
        want = np.asarray(gpt_forward(params, tokens, cfg))[:, -1]
        np.testing.assert_allclose(np.asarray(logits), want,
                                   atol=2e-4, rtol=2e-4)

    def test_prefill_then_decode_seam(self):
        """Teacher-forcing across the prefill/decode seam on the paged
        cache: decode logits must match the full forward at every
        position past the prefill."""
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(1), cfg)
        rng = np.random.RandomState(1)
        b, s, tail = 2, 11, 4
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)),
                             jnp.int32)
        want = np.asarray(gpt_forward(params, tokens, cfg))
        head = s - tail
        cache = init_kv_cache(cfg, b, s, cache_layout="paged",
                              block_size=4)
        logits, cache = prefill(params, tokens[:, :head], cfg,
                                cache=cache)
        np.testing.assert_allclose(np.asarray(logits), want[:, head - 1],
                                   atol=2e-4, rtol=2e-4)
        for i in range(head, s):
            logits, cache = decode_step(params, tokens[:, i], cache, cfg)
            np.testing.assert_allclose(
                np.asarray(logits), want[:, i], atol=2e-4, rtol=2e-4,
                err_msg=f"position {i}")

    def test_ragged_prefill_never_writes_other_rows_blocks(self):
        """Row padding must DROP, not spill into pool blocks owned by
        other rows: prefill a ragged pair, then check every block not
        in row 0's table is bit-identical to a solo prefill of row 1."""
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(2), cfg)
        rng = np.random.RandomState(2)
        lens = [3, 10]
        batch, prompts = _ragged_batch(rng, cfg.vocab_size, lens)
        cache = init_kv_cache(cfg, 2, 12, cache_layout="paged",
                              block_size=4)
        _, cache = prefill(params, batch, cfg,
                           prompt_lens=jnp.asarray(lens), cache=cache)
        solo = init_kv_cache(cfg, 1, 12, cache_layout="paged",
                             block_size=4)
        _, solo = prefill(params, jnp.asarray(prompts[1][None]), cfg,
                          cache=solo)
        # row 1 owns blocks [3, 6) of the shared pool; solo's row owns
        # [0, 3) of its own — same logical content either way
        np.testing.assert_allclose(
            np.asarray(cache["k"])[:, 3:6], np.asarray(solo["k"])[:, :3],
            atol=2e-4, rtol=2e-4)
