"""groupbn BatchNorm2d_NHWC shim + testing decorators."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.testing import skipFlakyTest, skipIfNoTPU, skipIfTPU


class TestBatchNorm2dNHWC:
    def _x(self, b=8, hw=4, c=16, seed=0):
        rs = np.random.RandomState(seed)
        return jnp.asarray(rs.randn(b, hw, hw, c) * 2 + 1, jnp.float32)

    def test_normalizes_like_reference_bn(self):
        x = self._x()
        mod = BatchNorm2d_NHWC(num_features=16, bn_group=1)
        vars_ = mod.init(jax.random.PRNGKey(0), x, train=False)
        y, _ = mod.apply(vars_, x, train=True, mutable=["batch_stats"])
        y = np.asarray(y)
        np.testing.assert_allclose(
            y.reshape(-1, 16).mean(0), 0.0, atol=1e-5)
        np.testing.assert_allclose(
            y.reshape(-1, 16).std(0), 1.0, atol=1e-3)

    def test_fused_add_relu(self):
        x = self._x(seed=1)
        z = jnp.asarray(
            np.random.RandomState(2).randn(*x.shape), jnp.float32)
        mod = BatchNorm2d_NHWC(num_features=16, fuse_relu=True)
        vars_ = mod.init(jax.random.PRNGKey(0), x, train=False)
        y, _ = mod.apply(vars_, x, z, train=True,
                         mutable=["batch_stats"])
        plain = BatchNorm2d_NHWC(num_features=16)
        yp, _ = plain.apply(
            plain.init(jax.random.PRNGKey(0), x, train=False), x,
            train=True, mutable=["batch_stats"])
        np.testing.assert_allclose(
            np.asarray(y), np.maximum(np.asarray(yp) + np.asarray(z), 0),
            atol=1e-5)

    def test_bn_group_stats_over_axis(self):
        """bn_group>1 = cross-device stats (the CUDA-IPC group analog):
        the per-device shard normalized with GLOBAL batch stats."""
        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        x = self._x(b=8, seed=3)
        mod = BatchNorm2d_NHWC(num_features=16, bn_group=2,
                               axis_name="dp")
        vars_ = mod.init(jax.random.PRNGKey(0), x[:4], train=False)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P("dp")),
            out_specs=P("dp"))
        def run(v, xloc):
            y, _ = mod.apply(v, xloc, train=True,
                             mutable=["batch_stats"])
            return y

        y = np.asarray(run(vars_, x))
        # global-batch normalization: all 8 samples together are ~N(0,1)
        np.testing.assert_allclose(y.reshape(-1, 16).mean(0), 0.0,
                                   atol=1e-5)
        np.testing.assert_allclose(y.reshape(-1, 16).std(0), 1.0,
                                   atol=1e-3)


class TestSkipDecorators:
    @skipIfNoTPU
    def test_only_on_tpu(self):
        assert any(d.platform == "tpu" for d in jax.devices())

    @skipIfTPU
    def test_only_on_cpu_mesh(self):
        assert not any(d.platform == "tpu" for d in jax.devices())

    @skipFlakyTest
    def test_flaky_runs_unless_env_set(self):
        assert True
