"""MHA module tests.

Mirrors reference apex/contrib/test/multihead_attn/: the fused module vs
a PyTorch-composed (here: jnp-composed) reference at dropout=0, plus
norm-add residual behavior, additive masks, and dropout statistics.
"""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.multihead_attn import (
    SelfMultiheadAttn,
    EncdecMultiheadAttn,
    fast_mask_softmax_dropout_func,
)

E, H = 64, 4
T, B = 32, 2


def _composed_self_attn(params, x, key_padding_mask=None, causal=False):
    """Plain jnp composition of the same math (the torch F.multi_head_
    attention_forward analog used by the reference tests)."""
    t, b, e = x.shape
    h = H
    d = e // h
    w = params["in_proj_weight"]
    wq, wk, wv = jnp.split(w, 3, axis=1)
    q = (x @ wq).reshape(t, b, h, d)
    k = (x @ wk).reshape(t, b, h, d)
    v = (x @ wv).reshape(t, b, h, d)
    s = jnp.einsum("qbhd,kbhd->bhqk", q, k) * (d ** -0.5)
    if key_padding_mask is not None:
        s = jnp.where(
            key_padding_mask[:, None, None, :].astype(bool), -1e30, s)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
        s = jnp.where((col > row)[None, None], -1e30, s)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhqk,kbhd->qbhd", p, v).reshape(t, b, e)
    return ctx @ params["out_proj_weight"]


class TestSelfMultiheadAttn:
    def _mk(self, **kw):
        mod = SelfMultiheadAttn(embed_dim=E, num_heads=H, **kw)
        x = jnp.asarray(
            np.random.RandomState(0).randn(T, B, E), jnp.float32) * 0.3
        params = mod.init(jax.random.PRNGKey(0), x, is_training=False)
        return mod, params, x

    def test_matches_composed_reference(self):
        mod, params, x = self._mk()
        out, weights = mod.apply(params, x, is_training=False)
        assert weights is None
        expect = _composed_self_attn(params["params"], x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), atol=2e-5, rtol=2e-5)

    def test_time_mask(self):
        mod, params, x = self._mk()
        out, _ = mod.apply(params, x, attn_mask=True, is_training=False)
        expect = _composed_self_attn(params["params"], x, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), atol=2e-5, rtol=2e-5)

    def test_key_padding_mask(self):
        mod, params, x = self._mk()
        kpm = jnp.asarray(
            np.arange(T)[None, :] >= np.array([24, T])[:, None])
        out, _ = mod.apply(
            params, x, key_padding_mask=kpm, is_training=False)
        expect = _composed_self_attn(params["params"], x,
                                     key_padding_mask=kpm)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), atol=2e-5, rtol=2e-5)

    def test_mask_additive(self):
        mod, params, x = self._mk(mask_additive=True)
        add = np.zeros((B, T), np.float32)
        add[0, 24:] = -1e30
        out, _ = mod.apply(
            params, x, key_padding_mask=jnp.asarray(add),
            is_training=False)
        expect = _composed_self_attn(
            params["params"], x, key_padding_mask=jnp.asarray(add < 0))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), atol=2e-5, rtol=2e-5)

    def test_bias_and_separate_qkv(self):
        mod = SelfMultiheadAttn(
            embed_dim=E, num_heads=H, bias=True, separate_qkv_params=True)
        x = jnp.asarray(
            np.random.RandomState(1).randn(T, B, E), jnp.float32) * 0.3
        params = mod.init(jax.random.PRNGKey(1), x, is_training=False)
        p = params["params"]
        assert set(p) >= {"q_weight", "k_weight", "v_weight",
                          "q_bias", "k_bias", "v_bias",
                          "out_proj_weight", "out_proj_bias"}
        out, _ = mod.apply(params, x, is_training=False)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_norm_add_residual(self):
        mod, params, x = self._mk(include_norm_add=True)
        out, _ = mod.apply(params, x, is_training=False)
        # out = x + attn(LN(x)): subtracting the residual must give the
        # attention of the normalized input
        ln = (x - x.mean(-1, keepdims=True)) / jnp.sqrt(
            x.var(-1, keepdims=True) + 1e-5)
        expect = x + _composed_self_attn(params["params"], ln)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), atol=1e-4, rtol=1e-4)

    @pytest.mark.slow   # statistical; CI slow job
    def test_dropout_training_stochastic_and_unbiased(self):
        mod, params, x = self._mk(dropout=0.3)
        dense, _ = mod.apply(params, x, is_training=False)
        outs = []
        for i in range(32):
            out, _ = mod.apply(
                params, x, is_training=True,
                rngs={"dropout": jax.random.PRNGKey(i)})
            outs.append(np.asarray(out))
        assert not np.allclose(outs[0], outs[1])
        mean = np.stack(outs).mean(0)
        # E[dropout(P)] = P -> mean over seeds approaches the dense out
        err = np.abs(mean - np.asarray(dense)).mean()
        scale = np.abs(np.asarray(dense)).mean()
        assert err < 0.15 * scale, (err, scale)

    def test_dropout_grads_finite(self):
        mod, params, x = self._mk(dropout=0.2)

        def loss(p):
            out, _ = mod.apply(
                p, x, is_training=True,
                rngs={"dropout": jax.random.PRNGKey(0)})
            return jnp.sum(out ** 2)

        g = jax.grad(loss)(params)
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.all(np.isfinite(np.asarray(leaf)))


class TestEncdecMultiheadAttn:
    def _mk(self, **kw):
        mod = EncdecMultiheadAttn(embed_dim=E, num_heads=H, **kw)
        rs = np.random.RandomState(2)
        q = jnp.asarray(rs.randn(T, B, E), jnp.float32) * 0.3
        kv = jnp.asarray(rs.randn(T + 8, B, E), jnp.float32) * 0.3
        params = mod.init(jax.random.PRNGKey(2), q, kv, is_training=False)
        return mod, params, q, kv

    def test_matches_composed_reference(self):
        mod, params, q, kv = self._mk()
        out, _ = mod.apply(params, q, kv, is_training=False)
        p = params["params"]
        d = E // H
        tq, tk = q.shape[0], kv.shape[0]
        qq = (q @ p["in_proj_weight_q"]).reshape(tq, B, H, d)
        kvp = kv @ p["in_proj_weight_kv"]
        kk, vv = jnp.split(kvp, 2, axis=-1)
        kk = kk.reshape(tk, B, H, d)
        vv = vv.reshape(tk, B, H, d)
        s = jnp.einsum("qbhd,kbhd->bhqk", qq, kk) * (d ** -0.5)
        probs = jax.nn.softmax(s, -1)
        ctx = jnp.einsum("bhqk,kbhd->qbhd", probs, vv).reshape(tq, B, E)
        expect = ctx @ p["out_proj_weight"]
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), atol=2e-5, rtol=2e-5)

    def test_norm_add_and_dropout(self):
        mod, params, q, kv = self._mk(include_norm_add=True, dropout=0.2)
        out, _ = mod.apply(
            params, q, kv, is_training=True,
            rngs={"dropout": jax.random.PRNGKey(3)})
        assert out.shape == q.shape
        assert np.all(np.isfinite(np.asarray(out)))

    def test_key_padding(self):
        mod, params, q, kv = self._mk()
        kpm = jnp.asarray(
            np.arange(kv.shape[0])[None, :]
            >= np.array([kv.shape[0] - 8, kv.shape[0]])[:, None])
        out, _ = mod.apply(
            params, q, kv, key_padding_mask=kpm, is_training=False)
        assert np.all(np.isfinite(np.asarray(out)))


class TestMaskSoftmaxDropout:
    def test_matches_softmax(self):
        rs = np.random.RandomState(3)
        s = jnp.asarray(rs.randn(B * H, T, T), jnp.float32)
        out = fast_mask_softmax_dropout_func(False, H, s, None, False, 0.5)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(jax.nn.softmax(s, -1)),
            atol=1e-6, rtol=1e-5)

    def test_byte_and_additive_masks_agree(self):
        rs = np.random.RandomState(4)
        s = jnp.asarray(rs.randn(B * H, T, T), jnp.float32)
        byte = np.zeros((B, T), np.uint8)
        byte[0, 20:] = 1
        add = np.where(byte, -1e30, 0.0).astype(np.float32)
        a = fast_mask_softmax_dropout_func(
            False, H, s, jnp.asarray(byte), False, 0.0)
        b = fast_mask_softmax_dropout_func(
            False, H, s, jnp.asarray(add), True, 0.0)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-5)

    def test_dropout_statistics(self):
        rs = np.random.RandomState(5)
        s = jnp.asarray(rs.randn(B * H, T, T), jnp.float32)
        out = fast_mask_softmax_dropout_func(
            True, H, s, None, False, 0.4,
            dropout_rng=jax.random.PRNGKey(0))
        frac = (np.asarray(out) == 0).mean()
        assert abs(frac - 0.4) < 0.03
