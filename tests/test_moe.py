"""Switch MoE + expert parallelism tests (beyond-reference component;
the reference reserves --num-experts but ships no MoE runtime).

ISSUE 10 additions: the capacity-free ragged routing is parity-pinned
against the capacity path at generous capacity_factor (both see every
token), the explicit EP island (counted all_to_all dispatch, compressed
wire, ring overlap) against the unsharded ragged math, and the grouped
matmul kernel against its XLA segment-sum reference at adversarial
segment layouts.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.parallel.mesh import create_mesh
from apex_tpu.transformer.moe import init_moe_params, switch_moe_mlp

# the GSPMD ambient-mesh surface (abstract meshes + set_mesh) needs the
# jax>=0.9 toolchain; the explicit-mesh island below runs everywhere the
# conftest shard_map shim does
_HAS_GSPMD = (hasattr(jax.sharding, "get_abstract_mesh")
              and hasattr(jax, "set_mesh"))


def _data(b=2, s=16, h=32, seed=0):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.randn(b, s, h) * 0.5, jnp.float32)


class TestSwitchMoE:
    def test_single_expert_equals_dense_mlp(self):
        """E=1 routes every token to the one expert with gate=softmax=1,
        so the MoE equals the dense FFN exactly (capacity >= s)."""
        h, f = 32, 64
        params = init_moe_params(jax.random.PRNGKey(0), h, f, 1)
        x = _data(h=h)
        out = switch_moe_mlp(params, x, capacity_factor=1.0,
                             ep_axis=None)
        # capacity = s/1 * 1.0 = s -> nothing dropped
        assert float(out.dropped_fraction) == 0.0
        dense = jax.nn.gelu(
            (x @ params["fc1"][0] + params["fc1_bias"][0]).astype(
                jnp.float32), approximate=False).astype(jnp.float32)
        dense = dense @ params["fc2"][0] + params["fc2_bias"][0]
        np.testing.assert_allclose(
            np.asarray(out.out), np.asarray(dense), atol=1e-5, rtol=1e-5)
        assert float(out.aux_loss) == pytest.approx(1.0, rel=1e-5)

    def test_capacity_drops_reported(self):
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(1), h, f, E)
        # bias the router hard toward expert 0 so capacity overflows
        params["router"] = params["router"].at[:, 0].add(10.0)
        x = _data(h=h)
        out = switch_moe_mlp(params, x, capacity_factor=1.0)
        assert float(out.dropped_fraction) > 0.0
        # dropped tokens pass through with zero update
        assert np.isfinite(np.asarray(out.out)).all()

    def test_top2_routes_more_mass(self):
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(2), h, f, E)
        x = _data(h=h, seed=3)
        out1 = switch_moe_mlp(params, x, top_k=1, capacity_factor=4.0)
        out2 = switch_moe_mlp(params, x, top_k=2, capacity_factor=4.0)
        # top-2 output includes top-1's contribution plus the runner-up's
        n1 = float(jnp.sum(jnp.abs(out1.out)))
        n2 = float(jnp.sum(jnp.abs(out2.out)))
        assert n2 > n1

    def test_grads_flow_to_router_and_experts(self):
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(4), h, f, E)
        x = _data(h=h, seed=5)

        def loss(p):
            o = switch_moe_mlp(p, x, capacity_factor=2.0)
            return jnp.mean(o.out ** 2) + 0.01 * o.aux_loss

        g = jax.grad(loss)(params)
        for name in ("router", "fc1", "fc2"):
            assert float(jnp.sum(jnp.abs(g[name]))) > 0.0, name

    def test_expert_parallel_matches_single_device(self):
        """ep=4 GSPMD sharding must be numerically identical to the
        unsharded run (the all-to-alls are layout, not math)."""
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(6), h, f, E)
        x = _data(b=4, h=h, seed=7)
        ref = switch_moe_mlp(params, x, capacity_factor=2.0,
                             ep_axis=None)

        mesh = create_mesh(ep=4, tp=1, pp=1, sp=1)

        def put_experts(p):
            return jax.device_put(p, {
                "router": NamedSharding(mesh, P()),
                "fc1": NamedSharding(mesh, P("ep")),
                "fc1_bias": NamedSharding(mesh, P("ep")),
                "fc2": NamedSharding(mesh, P("ep")),
                "fc2_bias": NamedSharding(mesh, P("ep")),
            })

        sharded = put_experts(params)

        @jax.jit
        def run(p, xx):
            o = switch_moe_mlp(p, xx, capacity_factor=2.0)
            return o.out, o.aux_loss

        with jax.set_mesh(mesh):
            out, aux = run(sharded, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref.out), atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(
            float(aux), float(ref.aux_loss), rtol=1e-6)

    def test_aux_loss_prefers_balance(self):
        """Uniform routing gives aux = 1 (minimum); collapsed routing
        gives aux ~ E."""
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(8), h, f, E)
        x = _data(h=h, seed=9)
        collapsed = dict(params)
        collapsed["router"] = params["router"] * 0 + jnp.asarray(
            [10.0, 0, 0, 0])
        # positive activations so the (bias-free) router's expert-0
        # logit is large-positive for every token
        aux_c = float(switch_moe_mlp(
            collapsed, jnp.abs(x) + 0.1).aux_loss)
        balanced = dict(params)
        balanced["router"] = params["router"] * 0
        # perfectly uniform probs: aux == 1 regardless of argmax ties
        aux_b = float(switch_moe_mlp(balanced, x).aux_loss)
        assert aux_c > 2.0
        assert aux_b == pytest.approx(1.0, rel=1e-5)


def _offsets(counts):
    return jnp.asarray(np.concatenate([[0], np.cumsum(counts)]),
                       jnp.int32)


class TestGroupedMatmul:
    """Kernel-vs-reference parity for ops/grouped_matmul at the segment
    layouts that break naive implementations: empty segments, length-1
    segments, uneven splits, everything on one expert, and windows."""

    @pytest.mark.parametrize("counts", [
        [0, 37, 0],                 # all tokens on one expert
        [1, 0, 1, 35],              # empty + singleton segments
        [5, 0, 20, 1, 11],          # uneven
        [9, 9, 9, 10],              # near-even
    ])
    def test_kernel_matches_reference_fwd_bwd(self, counts):
        from apex_tpu.ops.grouped_matmul import (
            grouped_matmul, grouped_matmul_reference)

        rng = np.random.RandomState(0)
        n, k, p = sum(counts), 32, 48
        x = jnp.asarray(rng.randn(n, k), jnp.float32)
        w = jnp.asarray(rng.randn(len(counts), k, p) * 0.1, jnp.float32)
        off = _offsets(counts)
        ref = grouped_matmul_reference(x, w, off)
        # dense per-segment truth
        offn = np.asarray(off)
        for g in range(len(counts)):
            seg = np.asarray(x)[offn[g]:offn[g + 1]] @ np.asarray(w)[g]
            np.testing.assert_allclose(
                np.asarray(ref)[offn[g]:offn[g + 1]], seg,
                atol=1e-4, rtol=1e-4)
        ker = grouped_matmul(x, w, off, backend="kernel")
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)
        cot = jnp.asarray(rng.randn(n, p), jnp.float32)

        def loss(a, b, backend):
            return jnp.vdot(grouped_matmul(a, b, off, backend=backend),
                            cot)

        gk = jax.grad(functools.partial(loss, backend="kernel"),
                      argnums=(0, 1))(x, w)
        gr = jax.grad(functools.partial(loss, backend="reference"),
                      argnums=(0, 1))(x, w)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_window_offsets_zero_outside(self):
        """offsets[0] > 0 / offsets[-1] < N (the EP ring's local-expert
        window): rows outside come back exactly zero on both routes."""
        from apex_tpu.ops.grouped_matmul import (
            grouped_matmul, grouped_matmul_reference)

        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(40, 32), jnp.float32)
        w = jnp.asarray(rng.randn(3, 32, 16) * 0.1, jnp.float32)
        off = jnp.asarray([7, 12, 12, 30], jnp.int32)
        for backend in ("reference", "kernel"):
            out = np.asarray(grouped_matmul(x, w, off, backend=backend))
            assert (out[:7] == 0).all() and (out[30:] == 0).all(), backend
        np.testing.assert_allclose(
            np.asarray(grouped_matmul(x, w, off, backend="kernel")),
            np.asarray(grouped_matmul_reference(x, w, off)),
            atol=1e-4, rtol=1e-4)

    def test_traced_offsets_under_jit(self):
        from apex_tpu.ops.grouped_matmul import (
            grouped_matmul, grouped_matmul_reference)

        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(24, 16), jnp.float32)
        w = jnp.asarray(rng.randn(4, 16, 8), jnp.float32)
        off = _offsets([3, 0, 17, 4])
        out = jax.jit(functools.partial(
            grouped_matmul, backend="kernel"))(x, w, off)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(grouped_matmul_reference(x, w, off)),
            atol=1e-4, rtol=1e-4)

    def test_backend_validation(self, monkeypatch):
        from apex_tpu.ops.grouped_matmul import _route, grouped_matmul

        monkeypatch.setenv("APEX_TPU_GROUPED_MATMUL", "reference")
        assert _route(None) == "reference"
        monkeypatch.setenv("APEX_TPU_GROUPED_MATMUL", "bogus")
        with pytest.raises(ValueError, match="bogus"):
            _route(None)
        x = jnp.zeros((4, 8))
        w = jnp.zeros((2, 8, 8))
        with pytest.raises(ValueError, match="offsets length"):
            grouped_matmul(x, w, jnp.zeros((2,), jnp.int32))


class TestRaggedRouting:
    """Capacity-free routing vs the capacity path at generous
    capacity_factor — both see every token, so the math must agree."""

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_capacity_fp32_fwd_bwd(self, top_k):
        h, f, E = 32, 64, 8
        params = init_moe_params(jax.random.PRNGKey(0), h, f, E)
        x = _data(h=h, seed=11)

        def loss(p, routing):
            o = switch_moe_mlp(
                p, x, capacity_factor=float(E), top_k=top_k,
                ep_axis=None, routing=routing)
            return (jnp.mean(o.out.astype(jnp.float32) ** 2)
                    + 0.01 * o.aux_loss), o

        (lc, oc), gc = jax.value_and_grad(
            functools.partial(loss, routing="capacity"),
            has_aux=True)(params)
        (lr, orag), gr = jax.value_and_grad(
            functools.partial(loss, routing="ragged"),
            has_aux=True)(params)
        np.testing.assert_allclose(np.asarray(orag.out),
                                   np.asarray(oc.out),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(float(lr), float(lc), rtol=1e-6)
        np.testing.assert_allclose(float(orag.aux_loss),
                                   float(oc.aux_loss), rtol=1e-6)
        for name in gc:
            np.testing.assert_allclose(
                np.asarray(gr[name]), np.asarray(gc[name]),
                atol=2e-5, rtol=2e-3, err_msg=name)

    def test_matches_capacity_bf16_loose(self):
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(1), h, f, E)
        x = _data(h=h, seed=12).astype(jnp.bfloat16)
        cap = switch_moe_mlp(params, x, capacity_factor=float(E),
                             top_k=2, ep_axis=None)
        rag = switch_moe_mlp(params, x, top_k=2, ep_axis=None,
                             routing="ragged")
        np.testing.assert_allclose(
            np.asarray(rag.out, np.float32),
            np.asarray(cap.out, np.float32), atol=5e-2, rtol=5e-2)

    def test_swiglu_ragged_parity(self):
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(2), h, f, E,
                                 activation="swiglu")
        x = _data(h=h, seed=13)
        cap = switch_moe_mlp(params, x, capacity_factor=float(E),
                             top_k=2, ep_axis=None,
                             activation="swiglu")
        rag = switch_moe_mlp(params, x, top_k=2, ep_axis=None,
                             routing="ragged", activation="swiglu")
        np.testing.assert_allclose(np.asarray(rag.out),
                                   np.asarray(cap.out),
                                   atol=1e-5, rtol=1e-5)

    def test_dropped_fraction_exactly_zero_by_construction(self):
        """The capacity path drops under a hard-biased router; the
        ragged path must report EXACTLY 0.0 (not merely small) on the
        identical input — drop-freedom is structural, not statistical."""
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(3), h, f, E)
        params["router"] = params["router"].at[:, 0].add(10.0)
        x = _data(h=h)
        cap = switch_moe_mlp(params, x, capacity_factor=1.0,
                             ep_axis=None)
        assert float(cap.dropped_fraction) > 0.0
        rag = switch_moe_mlp(params, x, ep_axis=None, routing="ragged")
        assert float(rag.dropped_fraction) == 0.0
        assert np.isfinite(np.asarray(rag.out)).all()
        # every assignment lands on an expert: loads sum to b*s*top_k
        assert float(jnp.sum(rag.expert_load)) == x.shape[0] * x.shape[1]

    def test_top2_aux_counts_runner_up_traffic(self):
        """The balance term must see ALL k selections: with every
        token's top-1 spread but every top-2 on one expert, the
        argmax-only formula reports balance while the correct one
        reports the pileup (satellite fix)."""
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(4), h, f, E)
        x = _data(h=h, seed=14)
        out = switch_moe_mlp(params, x, capacity_factor=float(E),
                             top_k=2, ep_axis=None)
        # recompute both formulas from the router math
        logits = np.asarray(x, np.float64).reshape(-1, h) @ np.asarray(
            params["router"], np.float64)
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        top1 = probs.argmax(-1)
        masked = probs.copy()
        masked[np.arange(len(top1)), top1] = -1
        top2 = masked.argmax(-1)
        counts = (np.bincount(top1, minlength=E)
                  + np.bincount(top2, minlength=E))
        want = E * float(
            (counts / counts.sum() * probs.mean(0)).sum())
        argmax_only = E * float(
            (np.bincount(top1, minlength=E) / len(top1)
             * probs.mean(0)).sum())
        np.testing.assert_allclose(float(out.aux_loss), want, rtol=1e-4)
        assert abs(want - argmax_only) > 1e-6, (
            "fixture failed to separate the two formulas")
        np.testing.assert_allclose(np.asarray(out.expert_load), counts)

    def test_routing_validation(self):
        params = init_moe_params(jax.random.PRNGKey(0), 8, 16, 2)
        x = jnp.zeros((1, 4, 8))
        with pytest.raises(ValueError, match="routing"):
            switch_moe_mlp(params, x, routing="bogus")
        with pytest.raises(ValueError, match="moe_comm"):
            switch_moe_mlp(params, x, routing="ragged",
                           moe_comm="fp8")


class TestRaggedEPIsland:
    """The explicit expert-parallel island on the 8-virtual-device ep
    mesh: counted all_to_all dispatch with compressed wire, ring
    overlap, and the moe.* telemetry invariants."""

    E = 8

    def _setup(self, seed=0, dtype=jnp.float32):
        h, f = 32, 64
        params = init_moe_params(jax.random.PRNGKey(seed), h, f, self.E)
        x = _data(b=2, s=16, h=h, seed=seed).astype(dtype)
        mesh = create_mesh(ep=8)
        return params, x, mesh

    def _loss(self, params, x, **kw):
        o = switch_moe_mlp(params, x, top_k=2, routing="ragged", **kw)
        return (jnp.mean(o.out.astype(jnp.float32) ** 2)
                + 0.01 * o.aux_loss), o

    def test_island_matches_local_fp32_fwd_bwd(self):
        params, x, mesh = self._setup()
        (l_ref, o_ref), g_ref = jax.value_and_grad(
            functools.partial(self._loss, ep_axis=None),
            has_aux=True)(params, x)
        (l_is, o_is), g_is = jax.jit(jax.value_and_grad(
            functools.partial(self._loss, ep_mesh=mesh),
            has_aux=True))(params, x)
        np.testing.assert_allclose(np.asarray(o_is.out),
                                   np.asarray(o_ref.out),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(float(l_is), float(l_ref), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(o_is.expert_load),
                                   np.asarray(o_ref.expert_load))
        for name in g_ref:
            np.testing.assert_allclose(
                np.asarray(g_is[name]), np.asarray(g_ref[name]),
                atol=2e-5, rtol=2e-3, err_msg=name)

    def test_int8_dispatch_within_tolerance_and_wire_ratio(self):
        """int8 wire parity within the PR-2 error-feedback-style bound,
        and the trace-time telemetry must show wire < 0.3x raw."""
        from apex_tpu.observability import configure, shutdown
        from apex_tpu.observability import metrics as _telemetry

        params, x, mesh = self._setup(seed=5)
        _, o_ref = self._loss(params, x, ep_axis=None)
        reg = _telemetry.registry()
        owned = reg is None
        if owned:
            configure(stderr_summary=False)
            reg = _telemetry.registry()
        w0 = reg.counter("moe.dispatch_bytes").value
        r0 = reg.counter("moe.dispatch_raw_bytes").value
        try:
            (_, o), _ = jax.jit(jax.value_and_grad(
                functools.partial(self._loss, ep_mesh=mesh,
                                  moe_comm="int8"),
                has_aux=True))(params, x)
            wire = reg.counter("moe.dispatch_bytes").value - w0
            raw = reg.counter("moe.dispatch_raw_bytes").value - r0
        finally:
            if owned:
                shutdown()
        # int8 step bound on the FFN-output scale (coherent-sum form,
        # like the dryrun comm phase's reduce-scatter bound)
        scale = float(np.abs(np.asarray(o_ref.out)).max()) + 1e-6
        err = float(np.abs(np.asarray(o.out, np.float32)
                           - np.asarray(o_ref.out, np.float32)).max())
        assert err < 0.05 * scale, f"int8 err {err:.3e} vs {scale:.3e}"
        assert raw > 0 and wire < 0.3 * raw, (
            f"moe telemetry: wire {wire} not < 0.3x raw {raw}")

    def test_overlap_parity_and_ring_invariant(self):
        """Ring-overlapped dispatch/combine == the all_to_all island
        (fwd+bwd), and moe.ring_hops == (ep-1) x moe.ring_calls."""
        from apex_tpu.observability import configure, shutdown
        from apex_tpu.observability import metrics as _telemetry

        params, x, mesh = self._setup(seed=6)
        reg = _telemetry.registry()
        owned = reg is None
        if owned:
            configure(stderr_summary=False)
            reg = _telemetry.registry()
        c0 = reg.counter("moe.ring_calls").value
        h0 = reg.counter("moe.ring_hops").value
        try:
            (l_off, o_off), g_off = jax.jit(jax.value_and_grad(
                functools.partial(self._loss, ep_mesh=mesh,
                                  overlap_comm=False),
                has_aux=True))(params, x)
            assert reg.counter("moe.ring_calls").value == c0, (
                "overlap off must not ring")
            (l_on, o_on), g_on = jax.jit(jax.value_and_grad(
                functools.partial(self._loss, ep_mesh=mesh,
                                  overlap_comm=True),
                has_aux=True))(params, x)
            calls = reg.counter("moe.ring_calls").value - c0
            hops = reg.counter("moe.ring_hops").value - h0
        finally:
            if owned:
                shutdown()
        assert calls > 0 and hops == (8 - 1) * calls, (
            f"moe ring telemetry: hops {hops} != (ep-1) x calls "
            f"(7 x {calls})")
        np.testing.assert_allclose(np.asarray(o_on.out),
                                   np.asarray(o_off.out),
                                   atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(float(l_on), float(l_off), rtol=1e-5)
        for name in g_off:
            np.testing.assert_allclose(
                np.asarray(g_on[name]), np.asarray(g_off[name]),
                atol=2e-5, rtol=2e-3, err_msg=name)

    def test_bf16_wire_loose(self):
        params, x, mesh = self._setup(seed=7)
        _, o_ref = self._loss(params, x, ep_axis=None)
        for overlap in (False, True):
            _, o = jax.jit(functools.partial(
                self._loss, ep_mesh=mesh, moe_comm="bf16",
                overlap_comm=overlap))(params, x)
            np.testing.assert_allclose(
                np.asarray(o.out, np.float32),
                np.asarray(o_ref.out, np.float32),
                atol=2e-2, rtol=2e-2)

    def test_bf16_compute_backward_through_ring(self):
        """bf16 activations through the overlap island, fwd AND bwd —
        pins the straight-through VJP's primal/cotangent dtype contract
        (the exchange runs fp32 internally regardless of compute
        dtype)."""
        params, x, mesh = self._setup(seed=7, dtype=jnp.bfloat16)
        (loss, _), grads = jax.jit(jax.value_and_grad(
            functools.partial(self._loss, ep_mesh=mesh,
                              overlap_comm=True),
            has_aux=True))(params, x)
        assert np.isfinite(float(loss))
        for name, g in grads.items():
            a = np.asarray(g, np.float32)
            assert np.isfinite(a).all() and np.abs(a).sum() > 0, name

    @pytest.mark.skipif(not _HAS_GSPMD,
                        reason="needs the jax>=0.9 GSPMD surface")
    def test_ambient_mesh_activates_island(self):
        """Under jax.set_mesh the island self-activates from the
        abstract mesh — no explicit ep_mesh plumbing needed."""
        params, x, mesh = self._setup(seed=8)
        _, o_ref = self._loss(params, x, ep_axis=None)
        sharded = jax.device_put(params, {
            "router": NamedSharding(mesh, P()),
            "fc1": NamedSharding(mesh, P("ep")),
            "fc1_bias": NamedSharding(mesh, P("ep")),
            "fc2": NamedSharding(mesh, P("ep")),
            "fc2_bias": NamedSharding(mesh, P("ep")),
        })

        @jax.jit
        def run(p, xx):
            out, o = self._loss(p, xx)
            return out, o.out

        with jax.set_mesh(mesh):
            _, out = run(sharded, x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(o_ref.out),
                                   atol=1e-5, rtol=1e-5)

    def test_indivisible_tokens_fall_back_to_local(self):
        """b*s not divisible by ep: the island declines and the local
        ragged math runs (correctness over parallelism)."""
        h, f = 32, 64
        params = init_moe_params(jax.random.PRNGKey(9), h, f, self.E)
        x = _data(b=1, s=9, h=h, seed=9)   # 9 tokens, ep=8
        mesh = create_mesh(ep=8)
        ref = switch_moe_mlp(params, x, ep_axis=None, routing="ragged")
        got = switch_moe_mlp(params, x, routing="ragged", ep_mesh=mesh)
        np.testing.assert_allclose(np.asarray(got.out),
                                   np.asarray(ref.out),
                                   atol=1e-6, rtol=1e-6)
