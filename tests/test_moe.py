"""Switch MoE + expert parallelism tests (beyond-reference component;
the reference reserves --num-experts but ships no MoE runtime)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.parallel.mesh import create_mesh
from apex_tpu.transformer.moe import init_moe_params, switch_moe_mlp


def _data(b=2, s=16, h=32, seed=0):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.randn(b, s, h) * 0.5, jnp.float32)


class TestSwitchMoE:
    def test_single_expert_equals_dense_mlp(self):
        """E=1 routes every token to the one expert with gate=softmax=1,
        so the MoE equals the dense FFN exactly (capacity >= s)."""
        h, f = 32, 64
        params = init_moe_params(jax.random.PRNGKey(0), h, f, 1)
        x = _data(h=h)
        out = switch_moe_mlp(params, x, capacity_factor=1.0,
                             ep_axis=None)
        # capacity = s/1 * 1.0 = s -> nothing dropped
        assert float(out.dropped_fraction) == 0.0
        dense = jax.nn.gelu(
            (x @ params["fc1"][0] + params["fc1_bias"][0]).astype(
                jnp.float32), approximate=False).astype(jnp.float32)
        dense = dense @ params["fc2"][0] + params["fc2_bias"][0]
        np.testing.assert_allclose(
            np.asarray(out.out), np.asarray(dense), atol=1e-5, rtol=1e-5)
        assert float(out.aux_loss) == pytest.approx(1.0, rel=1e-5)

    def test_capacity_drops_reported(self):
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(1), h, f, E)
        # bias the router hard toward expert 0 so capacity overflows
        params["router"] = params["router"].at[:, 0].add(10.0)
        x = _data(h=h)
        out = switch_moe_mlp(params, x, capacity_factor=1.0)
        assert float(out.dropped_fraction) > 0.0
        # dropped tokens pass through with zero update
        assert np.isfinite(np.asarray(out.out)).all()

    def test_top2_routes_more_mass(self):
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(2), h, f, E)
        x = _data(h=h, seed=3)
        out1 = switch_moe_mlp(params, x, top_k=1, capacity_factor=4.0)
        out2 = switch_moe_mlp(params, x, top_k=2, capacity_factor=4.0)
        # top-2 output includes top-1's contribution plus the runner-up's
        n1 = float(jnp.sum(jnp.abs(out1.out)))
        n2 = float(jnp.sum(jnp.abs(out2.out)))
        assert n2 > n1

    def test_grads_flow_to_router_and_experts(self):
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(4), h, f, E)
        x = _data(h=h, seed=5)

        def loss(p):
            o = switch_moe_mlp(p, x, capacity_factor=2.0)
            return jnp.mean(o.out ** 2) + 0.01 * o.aux_loss

        g = jax.grad(loss)(params)
        for name in ("router", "fc1", "fc2"):
            assert float(jnp.sum(jnp.abs(g[name]))) > 0.0, name

    def test_expert_parallel_matches_single_device(self):
        """ep=4 GSPMD sharding must be numerically identical to the
        unsharded run (the all-to-alls are layout, not math)."""
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(6), h, f, E)
        x = _data(b=4, h=h, seed=7)
        ref = switch_moe_mlp(params, x, capacity_factor=2.0,
                             ep_axis=None)

        mesh = create_mesh(ep=4, tp=1, pp=1, sp=1)

        def put_experts(p):
            return jax.device_put(p, {
                "router": NamedSharding(mesh, P()),
                "fc1": NamedSharding(mesh, P("ep")),
                "fc1_bias": NamedSharding(mesh, P("ep")),
                "fc2": NamedSharding(mesh, P("ep")),
                "fc2_bias": NamedSharding(mesh, P("ep")),
            })

        sharded = put_experts(params)

        @jax.jit
        def run(p, xx):
            o = switch_moe_mlp(p, xx, capacity_factor=2.0)
            return o.out, o.aux_loss

        with jax.set_mesh(mesh):
            out, aux = run(sharded, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref.out), atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(
            float(aux), float(ref.aux_loss), rtol=1e-6)

    def test_aux_loss_prefers_balance(self):
        """Uniform routing gives aux = 1 (minimum); collapsed routing
        gives aux ~ E."""
        h, f, E = 32, 64, 4
        params = init_moe_params(jax.random.PRNGKey(8), h, f, E)
        x = _data(h=h, seed=9)
        collapsed = dict(params)
        collapsed["router"] = params["router"] * 0 + jnp.asarray(
            [10.0, 0, 0, 0])
        # positive activations so the (bias-free) router's expert-0
        # logit is large-positive for every token
        aux_c = float(switch_moe_mlp(
            collapsed, jnp.abs(x) + 0.1).aux_loss)
        balanced = dict(params)
        balanced["router"] = params["router"] * 0
        # perfectly uniform probs: aux == 1 regardless of argmax ties
        aux_b = float(switch_moe_mlp(balanced, x).aux_loss)
        assert aux_c > 2.0
        assert aux_b == pytest.approx(1.0, rel=1e-5)
