"""Weight-only int8 quantized matmuls (ISSUE 14): ops/dense.py's
block-scaled slab path and ops/grouped_matmul.py's expert-slab path —
kernel-vs-reference parity (fp32 tight / bf16 loose, interpret path on
the 8-virtual-device mesh), the high-precision custom VJP, the
``APEX_TPU_QUANT_MATMUL`` routing, quantize_params over the model
family, and the fake-quant oracle pin
(``generate(quantize_params(p)) == generate(dequantize_params(...))``
greedy token-for-token — the int8 path computes exactly what it
claims)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.dense import (
    dense_quantized, dequantize_weight, is_quantized, pick_quant_block,
    quantize_weight, quantized_matmul)
from apex_tpu.ops.grouped_matmul import (
    _dequantize_group, grouped_matmul, grouped_matmul_quantized,
    quantize_group_weights)


class TestQuantizeWeight:
    def test_round_trip_error_bounded(self):
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(96, 40) * 0.3, jnp.float32)
        qw = quantize_weight(w, block=32)
        assert qw["wire"].dtype == jnp.int8
        assert qw["scale"].shape == (3, 40)
        deq = dequantize_weight(qw["wire"], qw["scale"])
        # symmetric RTN: |w - deq| <= scale/2 per element
        bound = np.repeat(np.asarray(qw["scale"]), 32, axis=0) / 2
        assert (np.abs(np.asarray(deq - w)) <= bound + 1e-7).all()

    def test_zero_columns_exact(self):
        w = jnp.zeros((64, 8), jnp.float32)
        qw = quantize_weight(w)
        np.testing.assert_array_equal(
            np.asarray(dequantize_weight(qw["wire"], qw["scale"])), 0.0)
        # all-zero block -> scale 1 (the comm/quantize contract)
        np.testing.assert_array_equal(np.asarray(qw["scale"]), 1.0)

    def test_pick_block_divides(self):
        assert pick_quant_block(96, 128) == 96
        assert pick_quant_block(256, 128) == 128
        assert pick_quant_block(100, 128) == 100
        assert pick_quant_block(7, 128) == 7
        with pytest.raises(ValueError, match="positive"):
            pick_quant_block(64, 0)

    def test_is_quantized(self):
        w = jnp.ones((8, 4))
        assert not is_quantized(w)
        assert is_quantized(quantize_weight(w))


class TestDenseParity:
    @pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-5),
                                           (jnp.bfloat16, 2e-2)])
    def test_kernel_vs_reference(self, dtype, tol):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(37, 96), dtype)   # ragged row count
        w = jnp.asarray(rng.randn(96, 40) * 0.3, jnp.float32)
        qw = quantize_weight(w, block=32)
        ref = dense_quantized(x, qw["wire"], qw["scale"],
                              backend="reference")
        ker = dense_quantized(x, qw["wire"], qw["scale"],
                              backend="kernel")
        assert ref.dtype == dtype and ker.dtype == dtype
        np.testing.assert_allclose(
            np.asarray(ker, np.float32), np.asarray(ref, np.float32),
            atol=tol, rtol=tol)

    def test_matches_fake_quant_matmul(self):
        """The quantized path computes exactly x @ dequantize(w) —
        the claim the fake-quant generate pin scales up."""
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(5, 64), jnp.float32)
        w = jnp.asarray(rng.randn(64, 24) * 0.3, jnp.float32)
        qw = quantize_weight(w, block=16)
        deq = dequantize_weight(qw["wire"], qw["scale"])
        out = dense_quantized(x, qw["wire"], qw["scale"],
                              backend="reference")
        np.testing.assert_allclose(np.asarray(out), np.asarray(x @ deq),
                                   atol=1e-6, rtol=1e-6)

    def test_swiglu_paired_3d_kernel(self):
        """[h, 2, f] paired kernels flatten for the GEMM and restore
        on the output — the _mlp swiglu drop-in."""
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(4, 6, 64), jnp.float32)
        w = jnp.asarray(rng.randn(64, 2, 24) * 0.3, jnp.float32)
        qw = quantize_weight(w, block=32)
        out = dense_quantized(x, qw["wire"], qw["scale"],
                              backend="kernel")
        assert out.shape == (4, 6, 2, 24)
        want = jnp.einsum("bsh,hcf->bscf", x,
                          dequantize_weight(qw["wire"], qw["scale"]))
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_backward_high_precision(self):
        """dx flows against the fp32-dequantized weights (both
        routes); the frozen wire/scales take no gradient."""
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(6, 64), jnp.float32)
        w = jnp.asarray(rng.randn(64, 16) * 0.3, jnp.float32)
        qw = quantize_weight(w, block=16)
        deq = dequantize_weight(qw["wire"], qw["scale"])
        want = jax.grad(lambda x: jnp.sum((x @ deq) ** 2))(x)
        for backend in ("reference", "kernel"):
            got = jax.grad(lambda x: jnp.sum(dense_quantized(
                x, qw["wire"], qw["scale"], backend=backend) ** 2))(x)
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(want),
                                       atol=5e-5, rtol=5e-5)
        ds = jax.grad(lambda s: jnp.sum(dense_quantized(
            x, qw["wire"], s, backend="reference")))(qw["scale"])
        np.testing.assert_array_equal(np.asarray(ds), 0.0)

    def test_plain_leaf_passthrough_bitwise(self):
        """quantized_matmul over a float array is byte-identical to
        the historical `x @ w.astype(x.dtype)` site."""
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(3, 32), jnp.bfloat16)
        w = jnp.asarray(rng.randn(32, 8), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(quantized_matmul(x, w), np.float32),
            np.asarray(x @ w.astype(x.dtype), np.float32))

    def test_validation(self):
        x = jnp.zeros((4, 32))
        qw = quantize_weight(jnp.ones((16, 8)))
        with pytest.raises(ValueError, match="contraction mismatch"):
            dense_quantized(x, qw["wire"], qw["scale"])
        with pytest.raises(ValueError, match="do not tile"):
            dense_quantized(jnp.zeros((4, 16)), qw["wire"],
                            jnp.ones((3, 8)))
        with pytest.raises(ValueError, match="expects"):
            quantize_weight(jnp.ones((8,)))


class TestRouting:
    def test_env_routes_and_rejects(self, monkeypatch):
        rng = np.random.RandomState(6)
        x = jnp.asarray(rng.randn(4, 32), jnp.float32)
        qw = quantize_weight(jnp.asarray(rng.randn(32, 8), jnp.float32))
        # off-TPU auto == reference (bitwise)
        auto = dense_quantized(x, qw["wire"], qw["scale"])
        ref = dense_quantized(x, qw["wire"], qw["scale"],
                              backend="reference")
        np.testing.assert_array_equal(np.asarray(auto), np.asarray(ref))
        monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
        ker = dense_quantized(x, qw["wire"], qw["scale"])
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        monkeypatch.setenv("APEX_TPU_QUANT_MATMUL", "nonsense")
        with pytest.raises(ValueError, match="backend"):
            dense_quantized(x, qw["wire"], qw["scale"])


class TestGroupedParity:
    def _case(self, rng, G=3, k=64, p=48, N=40):
        x = jnp.asarray(rng.randn(N, k), jnp.float32)
        w = jnp.asarray(rng.randn(G, k, p) * 0.3, jnp.float32)
        return x, w, quantize_group_weights(w, block=16)

    @pytest.mark.parametrize("off", [
        [0, 12, 12, 40],          # one empty group
        [0, 40, 40, 40],          # everything on one expert
        [0, 1, 20, 40],           # singleton segment
    ])
    def test_kernel_vs_reference_segment_layouts(self, off):
        rng = np.random.RandomState(7)
        x, w, qw = self._case(rng)
        offs = jnp.asarray(off, jnp.int32)
        ref = grouped_matmul_quantized(x, qw["wire"], qw["scale"], offs,
                                       backend="reference")
        ker = grouped_matmul_quantized(x, qw["wire"], qw["scale"], offs,
                                       backend="kernel")
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        # and against the float primitive over the dequantized slab
        want = grouped_matmul(x, _dequantize_group(qw["wire"],
                                                   qw["scale"]),
                              offs, backend="reference")
        np.testing.assert_allclose(np.asarray(ref), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)

    def test_window_offsets_zero_outside(self):
        rng = np.random.RandomState(8)
        x, w, qw = self._case(rng)
        offs = jnp.asarray([8, 20, 20, 32], jnp.int32)
        for backend in ("reference", "kernel"):
            out = grouped_matmul_quantized(
                x, qw["wire"], qw["scale"], offs, backend=backend)
            np.testing.assert_array_equal(np.asarray(out[:8]), 0.0)
            np.testing.assert_array_equal(np.asarray(out[32:]), 0.0)

    def test_bf16_loose(self):
        rng = np.random.RandomState(9)
        x, w, qw = self._case(rng)
        xb = x.astype(jnp.bfloat16)
        offs = jnp.asarray([0, 16, 28, 40], jnp.int32)
        ref = grouped_matmul_quantized(xb, qw["wire"], qw["scale"],
                                       offs, backend="reference")
        ker = grouped_matmul_quantized(xb, qw["wire"], qw["scale"],
                                       offs, backend="kernel")
        assert ref.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(ker, np.float32), np.asarray(ref, np.float32),
            atol=2e-2, rtol=2e-2)

    def test_backward_high_precision(self):
        rng = np.random.RandomState(10)
        x, w, qw = self._case(rng)
        offs = jnp.asarray([0, 16, 28, 40], jnp.int32)
        deq = _dequantize_group(qw["wire"], qw["scale"])
        want = jax.grad(lambda x: jnp.sum(grouped_matmul(
            x, deq, offs, backend="reference") ** 2))(x)
        got = jax.grad(lambda x: jnp.sum(grouped_matmul_quantized(
            x, qw["wire"], qw["scale"], offs,
            backend="reference") ** 2))(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=5e-5, rtol=5e-5)

    def test_validation(self):
        x = jnp.zeros((8, 16))
        qw = quantize_group_weights(jnp.ones((2, 16, 4)))
        with pytest.raises(ValueError, match="offsets length"):
            grouped_matmul_quantized(x, qw["wire"], qw["scale"],
                                     jnp.zeros((4,), jnp.int32))
        with pytest.raises(ValueError, match="does not tile"):
            grouped_matmul_quantized(x, qw["wire"], jnp.ones((2, 3, 4)),
                                     jnp.zeros((3,), jnp.int32))
        with pytest.raises(ValueError, match="expects"):
            quantize_group_weights(jnp.ones((16, 4)))


class TestQuantizedParams:
    def _model(self, activation="gelu"):
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.transformer_lm import init_gpt_params

        cfg = TransformerConfig(
            num_layers=2, hidden_size=64, num_attention_heads=4,
            vocab_size=128, max_position_embeddings=64,
            compute_dtype=jnp.float32, remat=False,
            activation=activation)
        return cfg, init_gpt_params(jax.random.PRNGKey(0), cfg)

    @pytest.mark.parametrize("activation", ["gelu", "swiglu"])
    def test_fake_quant_oracle_greedy_identical(self, activation):
        """THE correctness pin: generation off the int8 slabs is
        token-identical to a float model holding the dequantized
        weights — int8 changed the bytes, not the math."""
        from apex_tpu.models.generate import generate
        from apex_tpu.models.quantized import (
            dequantize_params, quantize_params)

        cfg, params = self._model(activation)
        qp = quantize_params(params)
        fq = dequantize_params(qp)
        rng = np.random.RandomState(11)
        prompt = jnp.asarray(rng.randint(0, 128, (2, 9)), jnp.int32)
        out_q = np.asarray(generate(qp, prompt, cfg, max_new_tokens=8))
        out_fq = np.asarray(generate(fq, prompt, cfg, max_new_tokens=8))
        np.testing.assert_array_equal(out_q, out_fq)

    def test_bytes_shrink_and_structure(self):
        from apex_tpu.models.quantized import (
            is_quantized_tree, param_bytes, quantize_params)

        cfg, params = self._model()
        qp = quantize_params(params)
        assert is_quantized_tree(qp) and not is_quantized_tree(params)
        assert is_quantized(qp["layers"]["qkv_kernel"])
        assert qp["layers"]["qkv_kernel"]["wire"].dtype == jnp.int8
        # embedding/head stay float (gather + tied head, documented)
        assert not is_quantized(qp["embedding"]["word"])
        # layer kernels dominate this config, so the tree shrinks hard
        assert param_bytes(qp) < 0.5 * param_bytes(params)
        with pytest.raises(ValueError, match="already quantized"):
            quantize_params(qp)

    def test_prefill_logits_close(self):
        """Quantized-weight prefill tracks the float forward within
        the int8 weight budget (loose — the bound is a sanity rail,
        the exact pin is the fake-quant oracle)."""
        from apex_tpu.models.generate import prefill
        from apex_tpu.models.quantized import quantize_params

        cfg, params = self._model()
        rng = np.random.RandomState(12)
        prompt = jnp.asarray(rng.randint(0, 128, (2, 12)), jnp.int32)
        lg_f, _ = prefill(params, prompt, cfg)
        lg_q, _ = prefill(quantize_params(params), prompt, cfg)
        np.testing.assert_allclose(np.asarray(lg_q), np.asarray(lg_f),
                                   atol=0.5, rtol=0.5)

    def test_manual_tp_rejects_quantized(self):
        """The quantized tree is a serving artifact: the manual-TP
        forward refuses it loudly instead of sharding dict leaves."""
        from apex_tpu.models.quantized import quantize_params
        from apex_tpu.models.transformer_lm import _attention

        cfg, params = self._model()
        qp = quantize_params(params)

        class _FakeTP:
            tp = 2
            tp_axis = "tp"

            def copy_in(self, x):
                return x

        lp = jax.tree_util.tree_map(lambda x: x[0], qp["layers"])
        with pytest.raises(ValueError, match="single-device serving"):
            _attention(cfg, lp, jnp.zeros((1, 2, 64)), _FakeTP(),
                       None, None, None)


class TestQuantizedMoE:
    def test_ragged_quantized_slabs_match_fake_quant(self):
        from apex_tpu.transformer.moe import init_moe_params, \
            switch_moe_mlp

        params = init_moe_params(jax.random.PRNGKey(0), hidden_size=32,
                                 ffn_hidden_size=64, num_experts=4)
        x = jnp.asarray(np.random.RandomState(13).randn(2, 8, 32),
                        jnp.float32)
        qp = dict(params,
                  fc1=quantize_group_weights(params["fc1"], block=16),
                  fc2=quantize_group_weights(params["fc2"], block=16))
        fq = dict(params,
                  fc1=_dequantize_group(qp["fc1"]["wire"],
                                        qp["fc1"]["scale"]),
                  fc2=_dequantize_group(qp["fc2"]["wire"],
                                        qp["fc2"]["scale"]))
        out_q = switch_moe_mlp(qp, x, routing="ragged", ep_axis=None)
        out_fq = switch_moe_mlp(fq, x, routing="ragged", ep_axis=None)
        np.testing.assert_allclose(np.asarray(out_q.out),
                                   np.asarray(out_fq.out),
                                   atol=1e-5, rtol=1e-5)
        # zero drops still holds on the quantized path
        assert float(out_q.dropped_fraction) == 0.0

    def test_capacity_routing_rejected(self):
        from apex_tpu.transformer.moe import init_moe_params, \
            switch_moe_mlp

        params = init_moe_params(jax.random.PRNGKey(0), hidden_size=32,
                                 ffn_hidden_size=64, num_experts=4)
        qp = dict(params,
                  fc1=quantize_group_weights(params["fc1"]))
        x = jnp.zeros((2, 8, 32), jnp.float32)
        with pytest.raises(ValueError, match="routing='ragged'"):
            switch_moe_mlp(qp, x, routing="capacity", ep_axis=None)
