"""Fused LayerNorm/RMSNorm numerics.

Reference analog: tests/L0/run_fused_layer_norm/test_fused_layer_norm.py —
fused op vs torch composition, fwd + bwd, affine/plain, mixed dtype,
memory-efficient mode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.ops.layer_norm import (
    fused_layer_norm,
    fused_rms_norm,
    layer_norm_ref,
)


def _torch_ln(x, w, b, eps=1e-5):
    tx = torch.tensor(x, requires_grad=True)
    tw = torch.tensor(w, requires_grad=True) if w is not None else None
    tb = torch.tensor(b, requires_grad=True) if b is not None else None
    y = torch.nn.functional.layer_norm(
        tx, (x.shape[-1],), weight=tw, bias=tb, eps=eps
    )
    return tx, tw, tb, y


@pytest.mark.parametrize("affine", [True, False])
@pytest.mark.parametrize("shape", [(4, 8, 256), (3, 384)])
def test_layer_norm_matches_torch(affine, shape):
    rng = np.random.RandomState(0)
    x = rng.randn(*shape).astype(np.float32)
    w = rng.rand(shape[-1]).astype(np.float32) + 0.5 if affine else None
    b = rng.randn(shape[-1]).astype(np.float32) if affine else None

    y = fused_layer_norm(jnp.asarray(x), None if w is None else jnp.asarray(w),
                         None if b is None else jnp.asarray(b))
    tx, tw, tb, ty = _torch_ln(x, w, b)
    np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                               atol=1e-5, rtol=1e-5)

    # gradients
    dy = rng.randn(*shape).astype(np.float32)

    def f(x_, w_, b_):
        return jnp.sum(fused_layer_norm(x_, w_, b_) * jnp.asarray(dy))

    if affine:
        gx, gw, gb = jax.grad(f, argnums=(0, 1, 2))(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)
        )
    else:
        gx = jax.grad(f)(jnp.asarray(x), None, None)
    ty.backward(torch.tensor(dy))
    np.testing.assert_allclose(np.asarray(gx), tx.grad.numpy(),
                               atol=1e-4, rtol=1e-4)
    if affine:
        np.testing.assert_allclose(np.asarray(gw), tw.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(gb), tb.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)


def test_rms_norm_matches_reference_formula():
    rng = np.random.RandomState(1)
    x = rng.randn(6, 256).astype(np.float32)
    w = (rng.rand(256) + 0.5).astype(np.float32)
    y = fused_rms_norm(jnp.asarray(x), jnp.asarray(w))
    expect = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-5) * w
    np.testing.assert_allclose(np.asarray(y), expect, atol=1e-5, rtol=1e-5)

    # grad vs numerical finite differences on a reduced function
    def f(w_):
        return jnp.sum(jnp.square(fused_rms_norm(jnp.asarray(x), w_)))

    g = jax.grad(f)(jnp.asarray(w))
    eps = 1e-3
    for i in [0, 100, 255]:
        wp, wm = w.copy(), w.copy()
        wp[i] += eps
        wm[i] -= eps
        num = (float(f(jnp.asarray(wp))) - float(f(jnp.asarray(wm)))) / (2 * eps)
        np.testing.assert_allclose(float(g[i]), num, rtol=2e-2, atol=1e-2)


def test_memory_efficient_matches_standard():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(5, 128).astype(np.float32))
    w = jnp.asarray((rng.rand(128) + 0.5).astype(np.float32))
    b = jnp.asarray(rng.randn(128).astype(np.float32))
    dy = jnp.asarray(rng.randn(5, 128).astype(np.float32))

    def loss(mem_eff):
        def f(x_, w_, b_):
            return jnp.sum(
                fused_layer_norm(x_, w_, b_, memory_efficient=mem_eff) * dy
            )
        return jax.grad(f, argnums=(0, 1, 2))(x, w, b)

    g_std = loss(False)
    g_mem = loss(True)
    for a, c in zip(g_std, g_mem):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   atol=1e-4, rtol=1e-4)


def test_mixed_dtype_bf16_input_fp32_params():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(4, 256), jnp.bfloat16)
    w = jnp.ones((256,), jnp.float32)
    b = jnp.zeros((256,), jnp.float32)
    y = fused_layer_norm(x, w, b)
    assert y.dtype == jnp.bfloat16
    ref = layer_norm_ref(x.astype(jnp.float32), w, b)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(ref), atol=2e-2
    )


def test_pallas_interpret_matches_ref(monkeypatch):
    monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(9, 256).astype(np.float32))  # odd rows → pad
    w = jnp.asarray((rng.rand(256) + 0.5).astype(np.float32))
    b = jnp.asarray(rng.randn(256).astype(np.float32))
    dy = jnp.asarray(rng.randn(9, 256).astype(np.float32))

    def f(x_, w_, b_):
        return jnp.sum(fused_layer_norm(x_, w_, b_) * dy)

    gx, gw, gb = jax.grad(f, argnums=(0, 1, 2))(x, w, b)
    y = fused_layer_norm(x, w, b)

    monkeypatch.delenv("APEX_TPU_PALLAS_INTERPRET")
    y_ref = fused_layer_norm(x, w, b)
    gx_r, gw_r, gb_r = jax.grad(f, argnums=(0, 1, 2))(x, w, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_r), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb_r), atol=1e-4)


@pytest.mark.parametrize("mode", ["pallas"])
def test_pallas_bwd_kernel_opt_in(monkeypatch, mode):
    """The Pallas revisit backward became the default in round 5 (it wins
    the on-chip fwd+bwd chain, 0.725x the XLA mix — BASELINE.md kernel
    ledger); the round-4 pallas_split variant was deleted (Mosaic rejects
    its partials block spec).  Exercise the kernel against the XLA
    composition so it cannot rot."""
    monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("APEX_TPU_LN_BWD", mode)
    rng = np.random.RandomState(11)
    # >512 rows -> multiple grid blocks (_rows_block(256, 8) = 512): the
    # revisit accumulator must actually cross block boundaries, not
    # degenerate to the single-block case
    x = jnp.asarray(rng.randn(1040, 256).astype(np.float32))
    w = jnp.asarray((rng.rand(256) + 0.5).astype(np.float32))
    b = jnp.asarray(rng.randn(256).astype(np.float32))
    dy = jnp.asarray(rng.randn(1040, 256).astype(np.float32))

    def f(x_, w_, b_):
        return jnp.sum(fused_layer_norm(x_, w_, b_) * dy)

    gx, gw, gb = jax.grad(f, argnums=(0, 1, 2))(x, w, b)

    monkeypatch.delenv("APEX_TPU_PALLAS_INTERPRET")
    monkeypatch.setenv("APEX_TPU_LN_BWD", "xla")  # reference side: XLA composition
    gx_r, gw_r, gb_r = jax.grad(f, argnums=(0, 1, 2))(x, w, b)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_r), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb_r), atol=1e-4)

    # RMS variant through the same opt-in
    monkeypatch.setenv("APEX_TPU_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("APEX_TPU_LN_BWD", mode)

    def fr(x_, w_):
        return jnp.sum(fused_rms_norm(x_, w_) * dy)

    rx, rw = jax.grad(fr, argnums=(0, 1))(x, w)
    monkeypatch.delenv("APEX_TPU_PALLAS_INTERPRET")
    monkeypatch.setenv("APEX_TPU_LN_BWD", "xla")  # reference side: XLA composition
    rx_r, rw_r = jax.grad(fr, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(rx), np.asarray(rx_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(rw), np.asarray(rw_r), atol=1e-4)


def test_flax_modules():
    from apex_tpu.normalization import FusedLayerNorm, FusedRMSNorm

    x = jnp.ones((2, 64))
    ln = FusedLayerNorm(normalized_shape=64)
    params = ln.init(jax.random.PRNGKey(0), x)
    y = ln.apply(params, x)
    assert y.shape == x.shape
    np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-5)

    rms = FusedRMSNorm(normalized_shape=64)
    params = rms.init(jax.random.PRNGKey(0), x)
    y = rms.apply(params, x)
    np.testing.assert_allclose(np.asarray(y), 1.0, atol=1e-3)
