"""Grouped-query attention (cfg.num_query_groups) — beyond the
reference (whose Megatron-era model is MHA-only; GQA per
arXiv:2305.13245).  MHA keeps the legacy interleaved qkv layout
bit-identical (golden traces, HF import); these tests pin the GQA
group-major layout (per group [q x rep | k | v]), the group-width KV
cache, and the composition surfaces — including manual TP, which the
group-major layout makes legal whenever tp divides the group count."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.transformer_lm import (
    gpt_forward, gpt_loss, init_gpt_params, manual_ctx)


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 8)
    kw.setdefault("num_query_groups", 2)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 48)
    kw.setdefault("compute_dtype", jnp.float32)
    return TransformerConfig(**kw)


def _data(cfg, b=2, s=24, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)), jnp.int32),
            jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)), jnp.int32))


class TestGQAForward:
    def test_param_shapes_and_loss(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        p, kvp = cfg.projection_size, cfg.kv_projection_size
        assert kvp == 2 * cfg.kv_channels
        assert params["layers"]["qkv_kernel"].shape == (
            cfg.num_layers, cfg.hidden_size, p + 2 * kvp)
        tokens, labels = _data(cfg)
        loss = gpt_loss(params, tokens, labels, cfg)
        assert np.isfinite(float(loss))
        # random init ⇒ loss ≈ log(vocab)
        assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.0

    def test_causality(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(1), cfg)
        tokens, _ = _data(cfg, seed=2)
        logits = gpt_forward(params, tokens, cfg)
        tokens2 = tokens.at[:, -1].set(
            (tokens[:, -1] + 1) % cfg.vocab_size)
        logits2 = gpt_forward(params, tokens2, cfg)
        np.testing.assert_allclose(
            np.asarray(logits[:, :-1]), np.asarray(logits2[:, :-1]),
            atol=1e-5)
        assert float(jnp.max(jnp.abs(logits[:, -1] - logits2[:, -1]))) > 1e-4

    def test_mqa_extreme_and_grads(self):
        cfg = _cfg(num_query_groups=1)   # multi-query attention
        params = init_gpt_params(jax.random.PRNGKey(2), cfg)
        tokens, labels = _data(cfg, seed=3)
        loss, grads = jax.value_and_grad(gpt_loss)(
            params, tokens, labels, cfg)
        assert np.isfinite(float(loss))
        flat = jax.tree_util.tree_leaves(grads)
        assert all(bool(jnp.all(jnp.isfinite(g))) for g in flat)
        assert float(jnp.max(jnp.abs(
            grads["layers"]["qkv_kernel"]))) > 0

    @pytest.mark.parametrize("bad", [3, 0, -2])
    def test_invalid_groups_rejected(self, bad):
        # 3: not a divisor of 8; 0: would ZeroDivisionError unguarded;
        # -2: divides evenly but a negative width is nonsense
        with pytest.raises(ValueError, match="divisor"):
            _cfg(num_query_groups=bad)

    def test_manual_tp_loss_matches_single_device(self):
        """The group-major qkv layout makes a contiguous tp chunk hold
        whole [q x rep | k | v] groups, so the manual shard_map TP path
        (the pipeline's per-stage context) trains GQA when tp divides
        the group count."""
        import functools

        from jax.sharding import PartitionSpec as P

        from apex_tpu.models.transformer_lm import gpt_param_specs
        from apex_tpu.parallel.mesh import create_mesh

        cfg = _cfg()   # 8 heads, 2 groups; tp=2 → 1 group per rank
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        tokens, labels = _data(cfg)
        ref = float(gpt_loss(params, tokens, labels, cfg))
        mesh = create_mesh(tp=2)
        specs = gpt_param_specs(cfg)

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=P())
        def run(p, t, y):
            return gpt_loss(p, t, y, cfg, manual_ctx(2))

        got = float(run(params, tokens, labels))
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    @pytest.mark.slow   # loss variant keeps default-tier coverage
    def test_manual_tp_grads_match_single_device(self):
        import functools

        from jax.sharding import PartitionSpec as P

        from apex_tpu.models.transformer_lm import gpt_param_specs
        from apex_tpu.parallel.mesh import create_mesh

        cfg = _cfg(num_query_groups=4)   # 2 groups per rank
        params = init_gpt_params(jax.random.PRNGKey(3), cfg)
        tokens, labels = _data(cfg, seed=9)
        ref_grads = jax.grad(gpt_loss)(params, tokens, labels, cfg)
        mesh = create_mesh(tp=2)
        specs = gpt_param_specs(cfg)

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=specs)
        def run(p, t, y):
            return jax.grad(gpt_loss)(p, t, y, cfg, manual_ctx(2))

        grads = run(params, tokens, labels)
        for path in [("layers", "qkv_kernel"), ("layers", "proj_kernel"),
                     ("embedding", "word")]:
            g, r = grads, ref_grads
            for k in path:
                g, r = g[k], r[k]
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), atol=2e-4,
                err_msg=str(path))

    def test_manual_tp_rejected_when_tp_exceeds_groups(self):
        """MQA (1 group) cannot hand each of 2 tp ranks a whole group —
        that config still needs GSPMD (which replicates KV heads)."""
        import functools

        from jax.sharding import PartitionSpec as P

        from apex_tpu.models.transformer_lm import gpt_param_specs
        from apex_tpu.parallel.mesh import create_mesh

        cfg = _cfg(num_query_groups=1)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        tokens, labels = _data(cfg)
        mesh = create_mesh(tp=2)
        specs = gpt_param_specs(cfg)

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=P())
        def run(p, t, y):
            return gpt_loss(p, t, y, cfg, manual_ctx(2))

        with pytest.raises(ValueError, match="divide the group"):
            run(params, tokens, labels)


class TestGQADecode:
    def test_cached_decode_matches_full_forward(self):
        """The group-width KV cache must reproduce the full forward's
        logits token-for-token (the same oracle as the MHA decode
        tests)."""
        from apex_tpu.models.generate import decode_step, init_kv_cache

        cfg = _cfg(position_embedding_type="rope",
                   num_query_groups=4)
        params = init_gpt_params(jax.random.PRNGKey(4), cfg)
        rng = np.random.RandomState(5)
        b, s = 2, 12
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)),
                             jnp.int32)
        full = gpt_forward(params, tokens, cfg)

        cache = init_kv_cache(cfg, b, s)
        # GQA evidence: the cache holds group heads, not query heads
        assert cache["k"].shape[3] == 4 != cfg.num_attention_heads
        outs = []
        for t in range(s):
            logits, cache = decode_step(params, tokens[:, t], cache, cfg)
            outs.append(logits)
        got = jnp.stack(outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(full), atol=2e-4, rtol=2e-4)

    def test_generate_runs(self):
        from apex_tpu.models.generate import generate

        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(6), cfg)
        prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        out = generate(params, prompt, cfg, max_new_tokens=6)
        assert out.shape == (1, 10)
        assert bool(jnp.all((out >= 0) & (out < cfg.vocab_size)))


class TestGQAPipeline:
    """GQA through the 1F1B pipeline — the round-4 gap: the pipeline's
    per-stage manual context could not run GQA at all.  The group-major
    layout closes it for pp alone (single-device stages) and for pp x tp
    (tp dividing the group count)."""

    @pytest.mark.parametrize(
        "tp", [1, pytest.param(2, marks=pytest.mark.slow)])
    def test_pipeline_loss_matches_sequential(self, tp):
        import functools

        from jax.sharding import PartitionSpec as P

        from apex_tpu.models.gpt import (
            gpt_pipeline_loss_and_grads, make_gpt_pipeline_stage,
            pipeline_packet, stack_pipeline_params)
        from apex_tpu.models.transformer_lm import gpt_param_specs
        from apex_tpu.parallel.mesh import create_mesh

        pp, n_micro, mb = 2, 2, 2
        cfg = _cfg(num_layers=4)
        params = init_gpt_params(jax.random.PRNGKey(6), cfg)
        tokens, labels = _data(cfg, b=n_micro * mb, seed=11)
        ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(
            params, tokens, labels, cfg)

        stacked = stack_pipeline_params(params, cfg, pp)
        packets = pipeline_packet(
            tokens.reshape(n_micro, mb, -1), labels.reshape(n_micro, mb, -1),
            cfg)
        mesh = create_mesh(pp=pp, tp=tp)
        stage_fn = make_gpt_pipeline_stage(cfg, pp, tp)
        pspecs = gpt_param_specs(cfg, pp_axis="pp")
        if tp == 1:
            pspecs = jax.tree_util.tree_map(
                lambda s: P(*(a if a != "tp" else None for a in s)),
                pspecs, is_leaf=lambda x: isinstance(x, P))

        @jax.jit
        @functools.partial(
            jax.shard_map, mesh=mesh,
            in_specs=(pspecs, P()), out_specs=(P(), pspecs))
        def run(p, mbs):
            return gpt_pipeline_loss_and_grads(
                stage_fn, p, mbs, n_micro=n_micro)

        loss, grads = run(stacked, packets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        g = grads["layers"]["qkv_kernel"]
        r = stack_pipeline_params(ref_grads, cfg, pp)["layers"]["qkv_kernel"]
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=3e-4)


class TestGQATraining:
    def test_gspmd_train_step_learns(self):
        from apex_tpu.models.gpt import make_gpt_train_step
        from apex_tpu.optimizers import fused_adam
        from apex_tpu.parallel.mesh import create_mesh

        cfg = _cfg(compute_dtype=jnp.bfloat16)
        mesh = create_mesh(dp=4, tp=2)
        init, step = make_gpt_train_step(cfg, fused_adam(lr=2e-3), "O2",
                                         mesh)
        state = init(jax.random.PRNGKey(0))
        tokens, labels = _data(cfg, b=4, seed=7)
        losses = []
        for _ in range(4):
            state, m = step(state, tokens, labels)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses

    @pytest.mark.slow   # compile-heavy GQA x cp; CI slow job
    def test_context_parallel_composes(self):
        from apex_tpu.models.gpt import make_gpt_train_step
        from apex_tpu.optimizers import fused_adam
        from apex_tpu.parallel.mesh import create_mesh

        cfg = _cfg(max_position_embeddings=64)
        mesh = create_mesh(dp=2, sp=4)
        tokens, labels = _data(cfg, b=2, s=64, seed=8)
        for mode in ("ring", "ulysses"):
            init, step = make_gpt_train_step(
                cfg, fused_adam(lr=1e-3), "O2", mesh, seq_axis="sp",
                context_parallel=mode)
            state = init(jax.random.PRNGKey(0))
            state, m = step(state, tokens, labels)
            assert np.isfinite(float(m["loss"])), mode
