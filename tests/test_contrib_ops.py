"""Contrib op parity tests: focal loss, index_mul_2d, transducer.

Mirrors the reference's contrib test strategy (apex/contrib/test/*: each
fused op vs a framework-composed reference implementation).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.transducer import (
    joint_mask,
    transducer_joint,
    transducer_loss,
)


# --------------------------------------------------------------------------
# focal loss — oracle: torchvision.ops.sigmoid_focal_loss formula
# --------------------------------------------------------------------------


def sigmoid_focal_loss_ref(x, y, alpha, gamma):
    """Literal port of the torchvision formula (the reference's oracle)."""
    p = 1.0 / (1.0 + np.exp(-x))
    ce = np.maximum(x, 0) - x * y + np.log1p(np.exp(-np.abs(x)))
    p_t = p * y + (1 - p) * (1 - y)
    loss = ce * (1 - p_t) ** gamma
    alpha_t = alpha * y + (1 - alpha) * (1 - y)
    return np.sum(alpha_t * loss)


class TestFocalLoss:
    @pytest.mark.parametrize("gamma", [0.0, 1.0, 2.0])
    def test_matches_torchvision_formula(self, gamma):
        rng = np.random.RandomState(0)
        n, k = 12, 8
        x = rng.randn(n, k).astype(np.float32)
        classes = rng.randint(0, k, n)
        y = np.eye(k, dtype=np.float32)[classes]
        want = sigmoid_focal_loss_ref(x, y, alpha=0.24, gamma=gamma)
        got = focal_loss(jnp.asarray(x), jnp.asarray(classes),
                         jnp.float32(1.0), k, 0.24, gamma)
        np.testing.assert_allclose(float(got), want, rtol=1e-5)

    def test_negative_class_is_all_background(self):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 8).astype(np.float32)
        y = np.zeros((4, 8), np.float32)
        want = sigmoid_focal_loss_ref(x, y, 0.25, 2.0)
        got = focal_loss(jnp.asarray(x), jnp.full((4,), -1), 1.0, 8,
                         0.25, 2.0)
        np.testing.assert_allclose(float(got), want, rtol=1e-5)

    def test_padded_classes_excluded(self):
        rng = np.random.RandomState(2)
        x = rng.randn(6, 16).astype(np.float32)
        classes = rng.randint(0, 10, 6)
        got_padded = focal_loss(jnp.asarray(x), jnp.asarray(classes),
                                2.0, 10, 0.25, 2.0)
        y = np.eye(16, dtype=np.float32)[classes]
        want = sigmoid_focal_loss_ref(x[:, :10], y[:, :10], 0.25, 2.0) / 2.0
        np.testing.assert_allclose(float(got_padded), want, rtol=1e-5)

    def test_grad_finite(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(5, 8), jnp.float32)
        g = jax.grad(lambda x: focal_loss(
            x, jnp.asarray(rng.randint(0, 8, 5)), 1.0, 8, 0.25, 2.0,
            label_smoothing=0.1))(x)
        assert np.all(np.isfinite(np.asarray(g)))

    def test_label_smoothing_parity_k2(self):
        """Smoothing uses the kernel's constant K=2 (kernel:35-45): the bce
        term's effective targets are 1-s+s/2 (pos) / s/2 (neg), while the
        modulating/alpha factors keep the hard targets."""
        rng = np.random.RandomState(4)
        n, k, s, alpha, gamma = 9, 16, 0.1, 0.3, 2.0
        x = rng.randn(n, k).astype(np.float32)
        classes = rng.randint(0, k, n)
        y = np.eye(k, dtype=np.float32)[classes]
        y_eff = y * (1.0 - s) + s / 2.0
        p = 1.0 / (1.0 + np.exp(-x))
        bce = np.maximum(x, 0) - x * y_eff + np.log1p(np.exp(-np.abs(x)))
        p_t = p * y + (1 - p) * (1 - y)
        alpha_t = alpha * y + (1 - alpha) * (1 - y)
        want = (alpha_t * (1 - p_t) ** gamma * bce).sum()
        got = focal_loss(jnp.asarray(x), jnp.asarray(classes), 1.0, k,
                         alpha, gamma, label_smoothing=s)
        np.testing.assert_allclose(float(got), want, rtol=1e-5)

    def test_ignored_matches_skipped(self):
        """Rows with target -2 contribute zero loss and zero grad
        (kernel:60-67), unlike -1 which is an all-background row."""
        rng = np.random.RandomState(5)
        x = rng.randn(6, 8).astype(np.float32)
        classes = np.array([3, -2, 1, -2, -1, 0])
        keep = classes != -2

        def f(x, cls):
            return focal_loss(x, jnp.asarray(cls), 1.0, 8, 0.25, 2.0)

        got = float(f(jnp.asarray(x), classes))
        want = float(f(jnp.asarray(x[keep]), classes[keep]))
        np.testing.assert_allclose(got, want, rtol=1e-6)
        g = np.asarray(jax.grad(f)(jnp.asarray(x), classes))
        assert np.all(g[~keep] == 0.0)
        assert np.any(g[keep] != 0.0)


class TestIndexMul2d:
    def test_forward_and_grads(self):
        rng = np.random.RandomState(0)
        m, n, d = 10, 16, 8
        in1 = jnp.asarray(rng.randn(m, d), jnp.float32)
        in2 = jnp.asarray(rng.randn(n, d), jnp.float32)
        idx = jnp.asarray(rng.randint(0, m, n))
        out = index_mul_2d(in1, in2, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(in1)[np.asarray(idx)]
            * np.asarray(in2), rtol=1e-6)

        def f(a, b):
            return jnp.sum(index_mul_2d(a, b, idx) ** 2)

        g1, g2 = jax.grad(f, argnums=(0, 1))(in1, in2)
        # oracle: plain jnp composition
        g1r, g2r = jax.grad(
            lambda a, b: jnp.sum((a[idx] * b) ** 2), argnums=(0, 1))(
                in1, in2)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g1r),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g2), np.asarray(g2r),
                                   rtol=1e-5)


# --------------------------------------------------------------------------
# transducer — oracle: brute-force DP in numpy
# --------------------------------------------------------------------------


def rnnt_loss_ref(lsm, label, t_len, u_len, blank):
    """O(T·U) sequential alpha recurrence (Graves 2012 eq. 16-18)."""
    B, T, U, K = lsm.shape
    out = np.zeros(B)
    for b in range(B):
        Tb, Ub = t_len[b], u_len[b]
        alpha = np.full((Tb, Ub + 1), -np.inf)
        alpha[0, 0] = 0.0
        for t in range(Tb):
            for u in range(Ub + 1):
                terms = []
                if t > 0:
                    terms.append(alpha[t - 1, u] + lsm[b, t - 1, u, blank])
                if u > 0:
                    terms.append(alpha[t, u - 1]
                                 + lsm[b, t, u - 1, label[b, u - 1]])
                if terms:
                    alpha[t, u] = np.logaddexp.reduce(terms)
        out[b] = -(alpha[Tb - 1, Ub] + lsm[b, Tb - 1, Ub, blank])
    return out


class TestTransducer:
    def test_joint_shapes_and_mask(self):
        rng = np.random.RandomState(0)
        f = jnp.asarray(rng.randn(2, 5, 8), jnp.float32)
        g = jnp.asarray(rng.randn(2, 4, 8), jnp.float32)
        f_len = jnp.asarray([5, 3])
        g_len = jnp.asarray([3, 2])
        h = transducer_joint(f, g, f_len, g_len)
        assert h.shape == (2, 5, 4, 8)
        np.testing.assert_allclose(
            np.asarray(h[0, 1, 2]),
            np.asarray(f[0, 1] + g[0, 2]), rtol=1e-6)
        # masked region zeroed: batch 1 has f_len=3 → t=3,4 invalid
        assert float(jnp.max(jnp.abs(h[1, 3:]))) == 0.0
        assert float(jnp.max(jnp.abs(h[1, :, 3:]))) == 0.0

    def test_joint_relu(self):
        f = jnp.asarray([[[-1.0, 2.0]]])
        g = jnp.asarray([[[0.5, -3.0]]])
        h = transducer_joint(f, g, jnp.asarray([1]), jnp.asarray([0]),
                             relu=True)
        np.testing.assert_allclose(np.asarray(h[0, 0, 0]), [0.0, 0.0],
                                   atol=1e-6)

    @pytest.mark.parametrize("seed", [0, 1])
    def test_loss_matches_bruteforce(self, seed):
        rng = np.random.RandomState(seed)
        B, T, U, K = 3, 6, 5, 7
        x = rng.randn(B, T, U, K).astype(np.float32)
        label = rng.randint(1, K, (B, U - 1))
        t_len = np.array([6, 4, 5])
        u_len = np.array([4, 2, 3])     # label lengths (u_len <= U-1)
        lsm = np.asarray(jax.nn.log_softmax(jnp.asarray(x), axis=-1))
        want = rnnt_loss_ref(lsm, label, t_len, u_len, blank=0)
        got = transducer_loss(jnp.asarray(x), jnp.asarray(label),
                              jnp.asarray(t_len), jnp.asarray(u_len))
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4)

    def test_loss_grad_finite_and_nonzero(self):
        rng = np.random.RandomState(2)
        B, T, U, K = 2, 5, 4, 6
        x = jnp.asarray(rng.randn(B, T, U, K), jnp.float32)
        label = jnp.asarray(rng.randint(1, K, (B, U - 1)))
        t_len = jnp.asarray([5, 4])
        u_len = jnp.asarray([3, 2])
        g = jax.grad(lambda x: jnp.sum(transducer_loss(
            x, label, t_len, u_len)))(x)
        arr = np.asarray(g)
        assert np.all(np.isfinite(arr))
        assert np.max(np.abs(arr)) > 0


class TestPackedTransducer:
    """Round-4: the reference's pack_output/packed_input modes under the
    static-capacity contract (max_tokens, like the MoE capacity factor)."""

    def _data(self, B=3, T=6, U=4, H=5, seed=0):
        rng = np.random.RandomState(seed)
        f = jnp.asarray(rng.randn(B, T, H), jnp.float32)
        g = jnp.asarray(rng.randn(B, U, H), jnp.float32)
        f_len = jnp.asarray([6, 4, 5], jnp.int32)
        g_len = jnp.asarray([3, 2, 1], jnp.int32)   # u <= g_len valid
        return f, g, f_len, g_len

    def test_pack_unpack_roundtrip(self):
        from apex_tpu.contrib.transducer import (
            joint_mask, pack_joint_output, transducer_joint, unpack_joint)

        f, g, f_len, g_len = self._data()
        B, T, U = f.shape[0], f.shape[1], g.shape[1]
        h = transducer_joint(f, g, f_len, g_len)
        cap = B * T * U
        packed, offsets, n_valid = pack_joint_output(
            h, f_len, g_len, cap)
        expect_valid = int(np.sum(
            np.asarray(f_len) * (np.asarray(g_len) + 1)))
        assert int(n_valid) == expect_valid
        assert np.asarray(offsets).tolist() == [
            0, 24, 24 + 12, 24 + 12 + 10]
        # rows past n_valid are zero
        assert not np.any(np.asarray(packed)[expect_valid:])
        dense = unpack_joint(packed, offsets, f_len, g_len, T, U)
        mask = np.asarray(joint_mask(f_len, g_len, T, U))
        np.testing.assert_allclose(
            np.asarray(dense)[mask], np.asarray(h)[mask], rtol=1e-6)
        assert not np.any(np.asarray(dense)[~mask])

    def test_packed_loss_matches_dense(self):
        from apex_tpu.contrib.transducer import (
            TransducerJoint, TransducerLoss, transducer_loss)

        rng = np.random.RandomState(1)
        B, T, U, H, K = 2, 5, 4, 8, 6
        f = jnp.asarray(rng.randn(B, T, H), jnp.float32)
        g = jnp.asarray(rng.randn(B, U, H), jnp.float32)
        w = jnp.asarray(rng.randn(H, K) * 0.3, jnp.float32)
        f_len = jnp.asarray([5, 4], jnp.int32)
        y_len = jnp.asarray([3, 2], jnp.int32)
        label = jnp.asarray(rng.randint(1, K, (B, U - 1)), jnp.int32)

        joint = TransducerJoint(pack_output=True, relu=True,
                                max_tokens=B * T * U)
        packed_h, offsets, _ = joint(f, g, f_len, y_len)
        packed_logits = packed_h @ w
        loss_p = TransducerLoss(packed_input=True)(
            packed_logits, label, f_len, y_len, offsets=offsets,
            max_f_len=T, max_g_len=U)

        from apex_tpu.contrib.transducer import transducer_joint
        dense_logits = transducer_joint(
            f, g, f_len, y_len, relu=True) @ w
        loss_d = transducer_loss(dense_logits, label, f_len, y_len)
        np.testing.assert_allclose(
            np.asarray(loss_p), np.asarray(loss_d), rtol=1e-5)

    def test_capacity_drop_is_not_silent_corruption(self):
        from apex_tpu.contrib.transducer import pack_joint_output

        f, g, f_len, g_len = self._data()
        from apex_tpu.contrib.transducer import transducer_joint
        h = transducer_joint(f, g, f_len, g_len)
        packed, offsets, n_valid = pack_joint_output(h, f_len, g_len, 10)
        # n_valid reports the TRUE count so the caller can detect drops
        assert int(n_valid) == 46 and packed.shape[0] == 10

    def test_pack_requires_capacity(self):
        from apex_tpu.contrib.transducer import TransducerJoint

        with pytest.raises(ValueError, match="max_tokens"):
            TransducerJoint(pack_output=True)

    def test_grads_flow_through_packed_path(self):
        from apex_tpu.contrib.transducer import (
            TransducerJoint, TransducerLoss)

        rng = np.random.RandomState(2)
        B, T, U, H, K = 2, 4, 3, 6, 5
        f = jnp.asarray(rng.randn(B, T, H), jnp.float32)
        g = jnp.asarray(rng.randn(B, U, H), jnp.float32)
        w = jnp.asarray(rng.randn(H, K) * 0.3, jnp.float32)
        f_len = jnp.asarray([4, 3], jnp.int32)
        y_len = jnp.asarray([2, 2], jnp.int32)
        label = jnp.asarray(rng.randint(1, K, (B, U - 1)), jnp.int32)

        def loss_fn(w):
            packed_h, offsets, _ = TransducerJoint(
                pack_output=True, max_tokens=B * T * U)(f, g, f_len, y_len)
            lp = TransducerLoss(packed_input=True)(
                packed_h @ w, label, f_len, y_len, offsets=offsets,
                max_f_len=T, max_g_len=U)
            return jnp.mean(lp)

        gw = jax.grad(loss_fn)(w)
        assert np.all(np.isfinite(np.asarray(gw)))
        assert float(jnp.max(jnp.abs(gw))) > 0
