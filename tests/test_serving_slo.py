"""ISSUE 7 acceptance: serving SLO accounting, two-way derivation
agreement, scrape/JSONL round-trip, and exact fleet merge.

The load-bearing soak test derives per-request TTFT/TPOT **two ways**
— the engine's own lifecycle arithmetic (Response fields feeding the
per-class sketches) vs. an independent reconstruction from the
``serving.request.{begin,first_token,end}`` events in the JSONL
stream — and requires them to agree within timer resolution.  The
``/metrics`` scrape taken during the soak must parse as valid
OpenMetrics and, after the drain, answer the same p50/p95 the JSONL
sketch records do.  Splitting the soak across two engines/streams and
merging with ``tools/aggregate_telemetry.py`` must reproduce the
union-stream sketch quantiles exactly.

Plus: slo.py unit coverage (target resolution, the judge), the
SLO-violation detector's window/hysteresis, goodput counter
consistency, and the preemption-overhead path on the paged layout.
"""

import importlib.util
import json
import math
import os
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.observability as obs
from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.observability import openmetrics
from apex_tpu.observability.sketches import LogBucketSketch
from apex_tpu.serving import (
    DEFAULT_SLO_TARGETS, SLOTarget, ServingEngine, resolve_slo_targets)
from apex_tpu.serving.slo import judge

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# two-way agreement bound: both derivations stamp adjacent lines of
# the same host code path (perf_counter for the engine, the record
# stream's time.time() for the reconstruction), so the gap is
# scheduling noise between those lines, not measurement semantics
AGREE_S = 0.1


@pytest.fixture(autouse=True)
def _clean_telemetry():
    yield
    obs.shutdown()


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _submit_mix(engine, rng, n=8, max_new=6):
    """n requests across two classes; returns {rid: slo_class}."""
    classes = {}
    for i in range(n):
        cls = "interactive" if i % 2 else "standard"
        rid = engine.submit(rng.randint(0, 100, (4 + i % 5,)),
                            max_new_tokens=max_new, slo_class=cls)
        classes[rid] = cls
    return classes


def _events(path, name):
    out = {}
    for line in open(path):
        rec = json.loads(line)
        if rec.get("type") == "event" and rec.get("name") == name:
            out[rec["data"]["id"]] = rec
    return out


# ---------------------------------------------------------------------------
# slo.py units
# ---------------------------------------------------------------------------


class TestSLOTargets:
    def test_defaults_and_overlay(self):
        t = resolve_slo_targets({"interactive": (100.0, 10.0),
                                 "custom": {"ttft_ms": 5.0}})
        assert t["interactive"] == SLOTarget(100.0, 10.0)
        assert t["custom"] == SLOTarget(ttft_ms=5.0)
        assert t["standard"] == DEFAULT_SLO_TARGETS["standard"]
        assert t["batch"].ttft_ms is None          # deadline-free
        assert t["default"].ttft_ms is None

    def test_invalid_targets_raise(self):
        with pytest.raises(ValueError, match="positive"):
            SLOTarget(ttft_ms=-1.0)
        with pytest.raises(ValueError, match="unknown keys"):
            resolve_slo_targets({"x": {"ttft": 5.0}})
        with pytest.raises(ValueError, match="expected"):
            resolve_slo_targets({"x": (1, 2, 3)})

    def test_judge(self):
        t = SLOTarget(ttft_ms=100.0, tpot_ms=10.0)
        assert judge(t, 99.0, 9.0)
        assert not judge(t, 101.0, 9.0)            # TTFT miss
        assert not judge(t, 99.0, 11.0)            # TPOT miss
        assert judge(t, 99.0, None)                # 1-token: no TPOT
        assert judge(SLOTarget(), 1e9, 1e9)        # no deadlines
        assert judge(None, 1e9, 1e9)               # unknown class

    def test_slo_detector_window_and_hysteresis(self):
        from apex_tpu.observability.detectors import SLOViolationDetector

        det = SLOViolationDetector(window=8, rate_threshold=0.5,
                                   min_points=4)
        # below min_points: never fires
        assert det.feed("a", False) is None
        assert det.feed("a", False) is None
        assert det.feed("a", False) is None
        a = det.feed("a", False)                   # 4/4 missed
        assert a is not None and a.kind == "slo_violation"
        assert a.detail["slo_class"] == "a"
        # latched: continued misses do not re-fire
        assert det.feed("a", False) is None
        # recovery below threshold/2 re-arms, then a new storm fires
        # exactly once more (latched again for its duration)
        for _ in range(8):
            det.feed("a", True)
        fired = [det.feed("a", False) for _ in range(8)]
        assert sum(a is not None for a in fired) == 1
        # classes are independent
        assert det.feed("b", True) is None


# ---------------------------------------------------------------------------
# the soak: two-way derivation + scrape round-trip + exact fleet merge
# ---------------------------------------------------------------------------


class TestSLOSoak:
    def test_soak_two_way_agreement_and_roundtrip(self, model, tmp_path):
        cfg, params = model
        jsonl = tmp_path / "soak.jsonl"
        reg = obs.configure(jsonl_path=str(jsonl), export_port=0)
        url = reg.exporter.url
        engine = ServingEngine(params, cfg, max_slots=3, max_len=32)
        rng = np.random.RandomState(0)
        classes = _submit_mix(engine, rng, n=10, max_new=5)
        responses, mid_parsed = [], None
        while not engine.idle:
            responses.extend(engine.step())
            if mid_parsed is None and responses:
                # mid-soak scrape: requests still in flight — must
                # already parse as valid OpenMetrics
                text = urllib.request.urlopen(
                    url + "/metrics", timeout=5).read().decode()
                mid_parsed = openmetrics.parse(text)
        assert mid_parsed is not None and mid_parsed["eof"]
        assert len(responses) == 10

        # -- derivation 1: the engine's own accounting ------------------
        by_rid = {r.request_id: r for r in responses}
        for rid, r in by_rid.items():
            assert r.slo_class == classes[rid]
            assert 0.0 <= r.queue_wait_ms <= r.ttft_ms
            assert r.ttft_ms <= r.e2e_ms
            assert r.tokens.size == 5 and r.tpot_ms > 0.0

        # -- derivation 2: reconstruction from the event stream ---------
        reg.flush()
        begins = _events(jsonl, "serving.request.begin")
        firsts = _events(jsonl, "serving.request.first_token")
        ends = _events(jsonl, "serving.request.end")
        assert set(begins) == set(firsts) == set(ends) == set(by_rid)
        for rid, r in by_rid.items():
            assert begins[rid]["data"]["slo_class"] == classes[rid]
            ttft_rec = firsts[rid]["t"] - begins[rid]["t"]
            tpot_rec = ((ends[rid]["t"] - firsts[rid]["t"])
                        / (r.tokens.size - 1))
            assert abs(ttft_rec - r.ttft_ms / 1e3) < AGREE_S, (
                f"rid {rid}: TTFT sketch-path {r.ttft_ms / 1e3:.4f}s vs "
                f"trace-event reconstruction {ttft_rec:.4f}s")
            assert abs(tpot_rec - r.tpot_ms / 1e3) < AGREE_S
            # the end event carries the engine numbers too
            assert ends[rid]["data"]["ttft_ms"] == pytest.approx(
                r.ttft_ms, abs=1e-3)

        # -- scrape vs JSONL sketch record round-trip -------------------
        text = urllib.request.urlopen(
            url + "/metrics", timeout=5).read().decode()
        parsed = openmetrics.parse(text)
        sketch_recs = {}
        for line in open(jsonl):
            rec = json.loads(line)
            if rec.get("type") == "sketch":
                key = (rec["name"], rec.get("tags", {}).get("slo_class"))
                sketch_recs[key] = rec["value"]     # last flush wins
        for cls in ("interactive", "standard"):
            for series in ("serving.ttft_ms", "serving.tpot_ms",
                           "serving.e2e_ms"):
                sk = LogBucketSketch.from_dict(sketch_recs[(series, cls)])
                fam = openmetrics.sanitize_name(series)
                buckets = openmetrics.bucket_series(
                    parsed, fam, {"slo_class": cls})
                assert buckets[-1][1] == sk.count
                for q in (0.50, 0.95):
                    assert openmetrics.histogram_quantile(buckets, q) \
                        == sk.quantile(q), (series, cls, q)

        # -- goodput counters == per-response verdicts ------------------
        for cls in ("interactive", "standard"):
            rs = [r for r in responses if r.slo_class == cls]
            met = openmetrics.sample_value(
                parsed, "serving_goodput_met_total",
                {"slo_class": cls}) or 0
            missed = openmetrics.sample_value(
                parsed, "serving_goodput_missed_total",
                {"slo_class": cls}) or 0
            assert met == sum(1 for r in rs if r.slo_met)
            assert missed == sum(1 for r in rs if not r.slo_met)
            assert met + missed == len(rs)

    def test_half_stream_merge_reproduces_full_quantiles(
            self, model, tmp_path):
        """The fleet-merge acceptance: run the same request set through
        one engine per 'host' (half each, own JSONL stream) and through
        one engine observing everything; aggregate_telemetry over the
        two half streams must reproduce the full stream's sketch
        quantiles EXACTLY — merge is count addition on shared
        boundaries, so the only way this fails is a real bug."""
        cfg, params = model
        agg_tool = _load_tool("aggregate_telemetry")
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, 100, (3 + i % 6,)) for i in range(8)]

        def _run_stream(path, prompts):
            obs.configure(jsonl_path=str(path))
            engine = ServingEngine(params, cfg, max_slots=2, max_len=32)
            for prompt in prompts:
                engine.submit(prompt, max_new_tokens=4,
                              slo_class="interactive")
            while not engine.idle:
                engine.step()
            obs.shutdown()   # final flush writes the sketch records

        _run_stream(tmp_path / "a.jsonl", prompts[:4])
        _run_stream(tmp_path / "b.jsonl", prompts[4:])
        merged = agg_tool.aggregate(agg_tool.load_records(
            [str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")]))
        key = "serving.ttft_ms{slo_class=interactive}"
        assert merged["sketches"][key]["count"] == 8
        # the union sketch, built directly from both streams' states
        states = []
        for path in (tmp_path / "a.jsonl", tmp_path / "b.jsonl"):
            for line in open(path):
                rec = json.loads(line)
                if (rec.get("type") == "sketch"
                        and rec["name"] == "serving.ttft_ms"):
                    states.append(rec["value"])
        assert len(states) == 2
        union = LogBucketSketch.merged(
            [LogBucketSketch.from_dict(s) for s in states])
        for q, field in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            assert merged["sketches"][key][field] == union.quantile(q)
        # and goodput totals add across the streams
        g = merged["goodput"]["interactive"]
        assert g["met"] + g["missed"] == 8

    def test_preemption_overhead_accounting_paged(self, model):
        """Paged layout under a starved pool: preempted requests carry
        preemptions > 0 and a positive preempt_overhead_ms, the
        overhead sketch only sees preempted requests, and TTFT ordering
        (queue_wait <= ttft <= e2e) survives the preempt/resume
        cycle."""
        cfg, params = model
        reg = obs.configure()
        engine = ServingEngine(params, cfg, max_slots=3, max_len=32,
                               cache_layout="paged", block_size=4,
                               num_blocks=14, reserve_blocks=1)
        rng = np.random.RandomState(2)
        for _ in range(3):
            engine.submit(rng.randint(0, 100, (6,)), max_new_tokens=12)
        responses = []
        while not engine.idle:
            responses.extend(engine.step())
        assert len(responses) == 3
        preempted = [r for r in responses if r.preemptions]
        assert engine.stats()["preemptions"] > 0 and preempted
        for r in responses:
            assert r.queue_wait_ms <= r.ttft_ms <= r.e2e_ms + 1e-6
            if r.preemptions:
                assert r.preempt_overhead_ms > 0.0
                assert r.preempt_overhead_ms <= r.e2e_ms
            else:
                assert r.preempt_overhead_ms == 0.0
        sk = reg.sketch("serving.preempt_overhead_ms",
                        {"slo_class": "default"})
        assert sk.summary()["count"] == len(preempted)

    def test_serve_dash_snapshot_from_live_exporter(self, model):
        """tools/serve_dash.py renders one frame from a live exporter
        and its snapshot carries the SLO table the operator watches."""
        import io

        cfg, params = model
        reg = obs.configure(export_port=0)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               slo_targets={"interactive": (1e6, 1e6)})
        rng = np.random.RandomState(3)
        for i in range(4):
            engine.submit(rng.randint(0, 100, (4,)), max_new_tokens=4,
                          slo_class="interactive")
        while not engine.idle:
            engine.step()
        dash = _load_tool("serve_dash")
        om = dash.load_openmetrics_module()
        out = io.StringIO()
        snap = dash.one_frame(om, reg.exporter.url, out=out)
        row = snap["classes"]["interactive"]
        assert row["requests"] == 4
        assert row["goodput"] == 1.0               # 1e6 ms deadlines
        assert row["ttft_p50"] > 0 and row["tpot_p95"] > 0
        text = out.getvalue()
        assert "interactive" in text and "goodput" in text


# ---------------------------------------------------------------------------
# multi-token emission (ISSUE 8): TPOT by tokens, decode_steps coherence
# ---------------------------------------------------------------------------


class TestMultiTokenEmission:
    def test_tpot_divides_by_tokens_not_polls(self):
        """A 3-tokens-per-poll stream must report ~1/3 the per-poll
        interval: 3 polls 100ms apart delivering 3 tokens each (plus
        the first token at t=0) = 10 tokens over 300ms -> 33.3ms TPOT,
        NOT the 100ms a polls-based divisor would claim."""
        from apex_tpu.serving.slo import tpot_ms

        assert tpot_ms(10.0, 10.3, 10) == pytest.approx(1e3 * 0.3 / 9)
        # non-spec degenerate case (one token per poll): equals the
        # per-poll interval, i.e. the historical semantics
        assert tpot_ms(10.0, 10.3, 4) == pytest.approx(100.0)
        # a one-token response has no interval, hence no TPOT verdict
        assert tpot_ms(10.0, 10.3, 1) is None
        assert tpot_ms(10.0, 10.3, 0) is None

    def test_decode_steps_vs_tokens_coherent_with_spec(self, model):
        """With spec on, Response.decode_steps counts POLLS: strictly
        fewer than tokens when drafts are accepted, and never fewer
        than tokens/(k+1) — the coherence envelope.  Spec-off keeps the
        historical identity decode_steps == tokens - 1 - preemptions,
        and the per-request TPOT is consistent with e2e timing."""
        cfg, params = model
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, 100, (4 + i,)) for i in range(3)]
        off = ServingEngine(params, cfg, max_slots=2, max_len=48)
        off_resps = off.run([dict(prompt=p, max_new_tokens=12)
                             for p in prompts])
        for r in off_resps:
            assert r.decode_steps == r.tokens.size - 1 - r.preemptions
        eng = ServingEngine(params, cfg, max_slots=2, max_len=48,
                            spec="ngram")
        k = eng.stats()["spec_k"]
        resps = eng.run([dict(prompt=p, max_new_tokens=12)
                         for p in prompts])
        for r, ro in zip(resps, off_resps):
            np.testing.assert_array_equal(r.tokens, ro.tokens)
            emitted = r.tokens.size - 1 - r.preemptions
            assert 1 <= r.decode_steps <= emitted
            assert emitted <= r.decode_steps * (k + 1)
            if r.tokens.size > 1:
                assert r.tpot_ms > 0.0
        # the greedy self-repetition of a tiny model accepts drafts, so
        # at least one request must realize the multi-token win
        assert any(r.decode_steps < r.tokens.size - 1 for r in resps)

    def test_serve_dash_shows_accept_rate_with_spec_counters(
            self, model):
        """ISSUE 8 satellite: the dashboard surfaces the spec accept
        rate when the generate.spec.* counters are present — and hides
        the row when they are not."""
        import io

        cfg, params = model
        reg = obs.configure(export_port=0)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=48,
                               spec="ngram")
        rng = np.random.RandomState(7)
        for i in range(3):
            engine.submit(rng.randint(0, 100, (5,)), max_new_tokens=8)
        while not engine.idle:
            engine.step()
        dash = _load_tool("serve_dash")
        om = dash.load_openmetrics_module()
        out = io.StringIO()
        snap = dash.one_frame(om, reg.exporter.url, out=out)
        assert snap["spec_accept_rate"] is not None
        assert 0.0 <= snap["spec_accept_rate"] <= 1.0
        assert snap["spec_verify_calls"] >= 1
        assert "spec accept-rate" in out.getvalue()
        # counters must reconcile with the registry's own view
        draft = reg.counter("generate.spec.draft_tokens").value
        acc = reg.counter("generate.spec.accepted_tokens").value
        assert snap["spec_accept_rate"] == pytest.approx(acc / draft)
        obs.shutdown()
        # spec-off engine: no counters, no row
        reg = obs.configure(export_port=0)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=48)
        engine.submit(rng.randint(0, 100, (5,)), max_new_tokens=4)
        while not engine.idle:
            engine.step()
        out = io.StringIO()
        snap = dash.one_frame(om, reg.exporter.url, out=out)
        assert snap["spec_accept_rate"] is None
        assert "spec accept-rate" not in out.getvalue()
