"""Flagship GPT model tests.

Reference analogs: tests/L0/run_transformer/run_gpt_minimal_test.py and
test_pipeline_parallel_fwd_bwd.py — loss/grad parity of the parallel model
against a sequential single-device run of the same params.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import (
    TransformerConfig,
    gpt_pipeline_loss_and_grads,
    gpt_forward,
    gpt_loss,
    gpt_param_specs,
    gspmd_ctx,
    init_gpt_params,
    make_gpt_pipeline_stage,
    make_gpt_train_step,
    manual_ctx,
    pipeline_packet,
    stack_pipeline_params,
)
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel.mesh import create_mesh
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving,
)

shard_map = jax.shard_map


def tiny_cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 32)
    kw.setdefault("compute_dtype", jnp.float32)   # exact parity checks
    return TransformerConfig(**kw)


def data(cfg, b=4, s=16, seed=0):
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
    return tokens, labels


class TestSingleDevice:
    def test_forward_shapes_and_loss(self):
        cfg = tiny_cfg()
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        tokens, labels = data(cfg)
        logits = gpt_forward(params, tokens, cfg)
        assert logits.shape == (4, 16, cfg.vocab_size)
        loss = gpt_loss(params, tokens, labels, cfg)
        assert jnp.isfinite(loss)
        # random init ⇒ loss ≈ log(vocab)
        assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.0

    # rope/swiglu/rms have default-tier kernel coverage; their combo
    # rides the slow tier. untied embeddings have no other coverage
    # anywhere, so that variant stays default.
    @pytest.mark.parametrize("variant", [
        pytest.param("rope_swiglu_rms", marks=pytest.mark.slow),
        "untied"])
    def test_variants(self, variant):
        if variant == "rope_swiglu_rms":
            cfg = tiny_cfg(position_embedding_type="rope",
                           activation="swiglu", normalization="rmsnorm")
        else:
            cfg = tiny_cfg(untie_embeddings_and_output_weights=True)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        tokens, labels = data(cfg)
        loss, grads = jax.value_and_grad(gpt_loss)(
            params, tokens, labels, cfg)
        assert jnp.isfinite(loss)
        flat = jax.tree_util.tree_leaves(grads)
        assert all(bool(jnp.all(jnp.isfinite(g))) for g in flat)
        # every param gets gradient signal somewhere
        assert any(float(jnp.max(jnp.abs(g))) > 0 for g in flat)

    def test_scan_matches_unrolled(self):
        cfg_s = tiny_cfg(scan_layers=True)
        cfg_u = tiny_cfg(scan_layers=False)
        params = init_gpt_params(jax.random.PRNGKey(1), cfg_s)
        tokens, labels = data(cfg_s)
        l1 = gpt_loss(params, tokens, labels, cfg_s)
        l2 = gpt_loss(params, tokens, labels, cfg_u)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)

    def test_padding_mask_isolates_positions(self):
        # bert_large-style bidirectional model: a fully-masked-out key
        # position must not affect other positions' logits
        cfg = tiny_cfg(attn_mask_type="padding")
        params = init_gpt_params(jax.random.PRNGKey(7), cfg)
        tokens, labels = data(cfg)
        b, s = tokens.shape
        mask = jnp.zeros((b, 1, s, s), bool).at[:, :, :, -1].set(True)
        logits = gpt_forward(params, tokens, cfg, attention_mask=mask)
        tokens2 = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab_size)
        logits2 = gpt_forward(params, tokens2, cfg, attention_mask=mask)
        np.testing.assert_allclose(
            np.asarray(logits[:, :-1]), np.asarray(logits2[:, :-1]),
            atol=1e-5)
        # and the masked loss path runs through gpt_loss too
        loss = gpt_loss(params, tokens, labels, cfg, attention_mask=mask)
        assert jnp.isfinite(loss)

    def test_causal_combines_with_user_mask(self):
        # causal LM + explicit padding mask: both must apply
        cfg = tiny_cfg()   # attn_mask_type='causal'
        params = init_gpt_params(jax.random.PRNGKey(8), cfg)
        tokens, _ = data(cfg)
        b, s = tokens.shape
        pad = jnp.zeros((b, 1, s, s), bool).at[:, :, :, s // 2].set(True)
        logits = gpt_forward(params, tokens, cfg, attention_mask=pad)
        # perturbing the masked-out key position changes nothing downstream
        tokens2 = tokens.at[:, s // 2].set(
            (tokens[:, s // 2] + 1) % cfg.vocab_size)
        logits2 = gpt_forward(params, tokens2, cfg, attention_mask=pad)
        np.testing.assert_allclose(
            np.asarray(logits[:, s // 2 + 1:]),
            np.asarray(logits2[:, s // 2 + 1:]), atol=1e-5)
        # and causality still holds with the mask present
        tokens3 = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab_size)
        logits3 = gpt_forward(params, tokens3, cfg, attention_mask=pad)
        np.testing.assert_allclose(
            np.asarray(logits[:, :-1]), np.asarray(logits3[:, :-1]),
            atol=1e-5)

    def test_causality(self):
        cfg = tiny_cfg()
        params = init_gpt_params(jax.random.PRNGKey(2), cfg)
        tokens, _ = data(cfg)
        logits = gpt_forward(params, tokens, cfg)
        # perturb the last token: logits at earlier positions unchanged
        tokens2 = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab_size)
        logits2 = gpt_forward(params, tokens2, cfg)
        np.testing.assert_allclose(
            np.asarray(logits[:, :-1]), np.asarray(logits2[:, :-1]),
            atol=1e-5)
        assert float(jnp.max(jnp.abs(logits[:, -1] - logits2[:, -1]))) > 1e-4


class TestManualTP:
    # one loss param stays default: both exercise identical manual-TP
    # machinery, and swiglu is the superset (extra gated projection);
    # the gelu variant rides the slow tier with the grads test
    @pytest.mark.parametrize("activation", [
        pytest.param("gelu", marks=pytest.mark.slow), "swiglu"])
    def test_tp_loss_matches_single_device(self, activation):
        tp = 2
        cfg = tiny_cfg(activation=activation)
        params = init_gpt_params(jax.random.PRNGKey(3), cfg)
        tokens, labels = data(cfg)
        ref = float(gpt_loss(params, tokens, labels, cfg))

        mesh = create_mesh(tp=tp)
        specs = gpt_param_specs(cfg)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(specs, P(), P()), out_specs=P())
        def run(p, t, y):
            ctx = manual_ctx(tp)
            return gpt_loss(p, t, y, cfg, ctx)

        got = float(run(params, tokens, labels))
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    @pytest.mark.slow   # manual-TP loss variants keep the default-tier TP coverage
    def test_tp_grads_match_single_device(self):
        tp = 2
        cfg = tiny_cfg()
        params = init_gpt_params(jax.random.PRNGKey(4), cfg)
        tokens, labels = data(cfg)
        ref_grads = jax.grad(gpt_loss)(params, tokens, labels, cfg)

        mesh = create_mesh(tp=tp)
        specs = gpt_param_specs(cfg)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=specs)
        def run(p, t, y):
            ctx = manual_ctx(tp)
            return jax.grad(gpt_loss)(p, t, y, cfg, ctx)

        grads = run(params, tokens, labels)
        for path in [("embedding", "word"), ("layers", "qkv_kernel"),
                     ("layers", "fc2_kernel"), ("final_ln", "scale")]:
            g, r = grads, ref_grads
            for k in path:
                g, r = g[k], r[k]
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), atol=2e-4,
                err_msg=str(path))


class TestGSPMD:
    @pytest.mark.slow   # dryrun gspmd phase covers AMP mesh step + parity
    def test_train_step_runs_and_learns(self):
        cfg = tiny_cfg(compute_dtype=jnp.bfloat16)
        mesh = create_mesh(tp=2, dp=4)
        init, step = make_gpt_train_step(
            cfg, fused_adam(lr=1e-3), "O2", mesh)
        state = init(jax.random.PRNGKey(0))
        tokens, labels = data(cfg, b=8)
        losses = []
        for _ in range(5):
            state, metrics = step(state, tokens, labels)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]
        assert int(state.step) == 5

    def test_gspmd_loss_matches_single_device(self):
        cfg = tiny_cfg()
        mesh = create_mesh(tp=2, dp=2, pp=2)
        params = init_gpt_params(jax.random.PRNGKey(5), cfg)
        tokens, labels = data(cfg)
        ref = float(gpt_loss(params, tokens, labels, cfg))
        with jax.set_mesh(mesh):
            got = float(
                jax.jit(gpt_loss, static_argnums=(3, 4))(
                    params, tokens, labels, cfg, gspmd_ctx()))
        np.testing.assert_allclose(got, ref, rtol=1e-5)


class TestPipeline:
    # both params ride the slow tier (CI every push): these are
    # single-shot loss/grad parity assertions, exactly what the dryrun
    # pipeline phase re-asserts on every driver run; the schedule logic
    # keeps default-tier coverage via test_pipeline.py's toy stages
    @pytest.mark.parametrize(
        "tp", [pytest.param(1, marks=pytest.mark.slow),
               pytest.param(2, marks=pytest.mark.slow)])
    def test_pipeline_loss_and_grads_match_sequential(self, tp):
        pp, n_micro, mb = 2, 4, 2
        cfg = tiny_cfg(num_layers=4, remat=False)
        params = init_gpt_params(jax.random.PRNGKey(6), cfg)
        tokens, labels = data(cfg, b=n_micro * mb)

        ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(
            params, tokens, labels, cfg)

        stacked = stack_pipeline_params(params, cfg, pp)
        tokens_mb = tokens.reshape(n_micro, mb, -1)
        labels_mb = labels.reshape(n_micro, mb, -1)
        packets = pipeline_packet(tokens_mb, labels_mb, cfg)

        mesh = create_mesh(pp=pp, tp=tp)
        stage_fn = make_gpt_pipeline_stage(cfg, pp, tp)
        pspecs = gpt_param_specs(cfg, pp_axis="pp")
        if tp == 1:
            pspecs = jax.tree_util.tree_map(
                lambda s: P(*(a if a != "tp" else None for a in s)),
                pspecs, is_leaf=lambda x: isinstance(x, P))

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(pspecs, P()), out_specs=(P(), pspecs))
        def run(p, mbs):
            return gpt_pipeline_loss_and_grads(
                stage_fn, p, mbs, n_micro=n_micro)

        loss, grads = run(stacked, packets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)

        ref_stacked = stack_pipeline_params(ref_grads, cfg, pp)
        for path in [("embedding", "word"), ("layers", "qkv_kernel"),
                     ("layers", "fc1_kernel"), ("final_ln", "scale")]:
            g, r = grads, ref_stacked
            for k in path:
                g, r = g[k], r[k]
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), atol=3e-4, err_msg=str(path))


class TestPipelineMasksAndDropout:
    """VERDICT r1 item 7: padding masks + dropout through the pipeline
    packet (BERT-style models under PP)."""

    @pytest.mark.slow   # dryrun pipeline feature phase runs the same mask packet
    def test_padding_mask_matches_sequential(self):
        pp, n_micro, mb = 2, 2, 2
        cfg = tiny_cfg(num_layers=4, remat=False,
                       attn_mask_type="padding")
        params = init_gpt_params(jax.random.PRNGKey(7), cfg)
        tokens, labels = data(cfg, b=n_micro * mb)
        s = tokens.shape[-1]
        # mask out a tail of keys per sequence
        lens = np.array([10, 16, 12, 16])
        kpm = jnp.asarray(np.arange(s)[None, :] >= lens[:, None])

        ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(
            params, tokens, labels, cfg, attention_mask=kpm)

        stacked = stack_pipeline_params(params, cfg, pp)
        packets = pipeline_packet(
            tokens.reshape(n_micro, mb, -1),
            labels.reshape(n_micro, mb, -1), cfg,
            attention_mask_mb=kpm.reshape(n_micro, mb, -1))

        mesh = create_mesh(pp=pp, tp=1)
        stage_fn = make_gpt_pipeline_stage(cfg, pp, 1)
        pspecs = gpt_param_specs(cfg, pp_axis="pp")
        pspecs = jax.tree_util.tree_map(
            lambda sp: P(*(a if a != "tp" else None for a in sp)),
            pspecs, is_leaf=lambda x: isinstance(x, P))

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(pspecs, P()), out_specs=(P(), pspecs))
        def run(p, mbs):
            return gpt_pipeline_loss_and_grads(
                stage_fn, p, mbs, n_micro=n_micro)

        loss, grads = run(stacked, packets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        ref_stacked = stack_pipeline_params(ref_grads, cfg, pp)
        for path in [("embedding", "word"), ("layers", "qkv_kernel")]:
            g, r = grads, ref_stacked
            for k in path:
                g, r = g[k], r[k]
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), atol=3e-4,
                err_msg=str(path))

    @pytest.mark.slow   # dryrun pipeline feature phase covers masks+dropout
    def test_dropout_runs_and_is_seed_deterministic(self):
        pp, n_micro, mb = 2, 2, 2
        cfg = tiny_cfg(num_layers=4, remat=False,
                       hidden_dropout=0.1, attention_dropout=0.1)
        params = init_gpt_params(jax.random.PRNGKey(8), cfg)
        tokens, labels = data(cfg, b=n_micro * mb)
        stacked = stack_pipeline_params(params, cfg, pp)
        seeds = jnp.arange(n_micro, dtype=jnp.int32) + 7
        packets = pipeline_packet(
            tokens.reshape(n_micro, mb, -1),
            labels.reshape(n_micro, mb, -1), cfg, dropout_seeds=seeds)

        mesh = create_mesh(pp=pp, tp=1)
        stage_fn = make_gpt_pipeline_stage(cfg, pp, 1)
        pspecs = gpt_param_specs(cfg, pp_axis="pp")
        pspecs = jax.tree_util.tree_map(
            lambda sp: P(*(a if a != "tp" else None for a in sp)),
            pspecs, is_leaf=lambda x: isinstance(x, P))

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(pspecs, P()), out_specs=(P(), pspecs))
        def run(p, mbs):
            return gpt_pipeline_loss_and_grads(
                stage_fn, p, mbs, n_micro=n_micro)

        loss1, grads1 = run(stacked, packets)
        loss2, _ = run(stacked, packets)
        # same seeds -> identical stochastic loss; grads finite
        np.testing.assert_allclose(float(loss1), float(loss2))
        # different seeds -> different dropout mask
        packets2 = pipeline_packet(
            tokens.reshape(n_micro, mb, -1),
            labels.reshape(n_micro, mb, -1), cfg,
            dropout_seeds=seeds + 100)
        loss3, _ = run(stacked, packets2)
        assert float(loss3) != float(loss1)
        for leaf in jax.tree_util.tree_leaves(grads1):
            assert np.all(np.isfinite(np.asarray(leaf)))


class TestVirtualPipeline:
    """Interleaved (vpp) schedule driving the real GPT model — chunk
    identity from the chunk_id leaf, embed/head on their owning chunks
    only (reference fwd_bwd_pipelining_with_interleaving.py:26 +
    build_model virtual chunks)."""

    @pytest.mark.slow   # dryrun vpp phase asserts the same parity
    def test_vpp_loss_and_grads_match_sequential(self):
        from apex_tpu.models.gpt import (
            gpt_vpp_loss_and_grads,
            make_gpt_vpp_stage,
            stack_pipeline_params_vpp,
        )

        pp, vpp, n_micro, mb = 2, 2, 4, 2
        cfg = tiny_cfg(num_layers=8, remat=False)
        params = init_gpt_params(jax.random.PRNGKey(9), cfg)
        tokens, labels = data(cfg, b=n_micro * mb)

        ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(
            params, tokens, labels, cfg)

        stacked = stack_pipeline_params_vpp(params, cfg, pp, vpp)
        packets = pipeline_packet(
            tokens.reshape(n_micro, mb, -1),
            labels.reshape(n_micro, mb, -1), cfg)

        mesh = create_mesh(pp=pp, tp=1)
        stage_fn = make_gpt_vpp_stage(cfg, pp, vpp)
        base = gpt_param_specs(cfg, pp_axis="pp")
        base = jax.tree_util.tree_map(
            lambda sp: P(*(a if a != "tp" else None for a in sp)),
            base, is_leaf=lambda x: isinstance(x, P))
        # in: every non-layer leaf vpp-broadcast (leading None); layers
        # [vpp, pp, per, ...] shard dim 1; chunk_id [vpp, pp]
        pspecs_in = jax.tree_util.tree_map(
            lambda sp: P(None, *sp), base,
            is_leaf=lambda x: isinstance(x, P))
        pspecs_in["layers"] = jax.tree_util.tree_map(
            lambda sp: P(None, *sp), base["layers"],
            is_leaf=lambda x: isinstance(x, P))
        pspecs_in["chunk_id"] = P(None, "pp")
        # out: layer grads stacked, replicated grads plain (vpp-summed)
        pspecs_out = dict(base)
        pspecs_out["layers"] = pspecs_in["layers"]

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(pspecs_in, P()), out_specs=(P(), pspecs_out))
        def run(p, mbs):
            return gpt_vpp_loss_and_grads(
                stage_fn, p, mbs, n_micro=n_micro, vpp=vpp)

        loss, grads = run(stacked, packets)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)

        ref_layers = stack_pipeline_params_vpp(
            ref_grads, cfg, pp, vpp)["layers"]
        for path, ref_tree in [
            (("embedding", "word"), ref_grads),
            (("final_ln", "scale"), ref_grads),
            (("layers", "qkv_kernel"), {"layers": ref_layers}),
            (("layers", "fc2_kernel"), {"layers": ref_layers}),
        ]:
            g, r = grads, ref_tree
            for k in path:
                g, r = g[k], r[k]
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), atol=3e-4,
                err_msg=str(path))


class TestGPTMoE:
    """GPT-MoE model family (cfg.num_experts) — Switch FFN in the
    backbone with the load-balance aux loss in the training objective."""

    def test_forward_and_loss_finite(self):
        cfg = tiny_cfg(num_experts=4, remat=False)
        params = init_gpt_params(jax.random.PRNGKey(10), cfg)
        assert "router_kernel" in params["layers"]
        assert "fc1_kernel" not in params["layers"]
        tokens, labels = data(cfg)
        loss = gpt_loss(params, tokens, labels, cfg)
        assert np.isfinite(float(loss))

    def test_aux_loss_included(self):
        cfg0 = tiny_cfg(num_experts=4, remat=False, moe_aux_loss_coeff=0.0)
        cfg1 = tiny_cfg(num_experts=4, remat=False, moe_aux_loss_coeff=1.0)
        params = init_gpt_params(jax.random.PRNGKey(11), cfg0)
        tokens, labels = data(cfg0)
        l0 = float(gpt_loss(params, tokens, labels, cfg0))
        l1 = float(gpt_loss(params, tokens, labels, cfg1))
        assert l1 > l0  # the balance term is positive (>= 1 per layer)

    @pytest.mark.slow   # gspmd_expert_parallel/forward_and_loss keep MoE coverage
    def test_train_step_learns_and_routes(self):
        from apex_tpu.optimizers import fused_adam

        cfg = tiny_cfg(num_experts=4, remat=False)
        init, step = make_gpt_train_step(cfg, fused_adam(lr=1e-3), "O0")
        state = init(jax.random.PRNGKey(12))
        tokens, labels = data(cfg)
        router0 = np.asarray(
            state.master_params["layers"]["router_kernel"]).copy()
        state, m0 = step(state, tokens, labels)
        for _ in range(10):
            state, m = step(state, tokens, labels)
        assert float(m["loss"]) < float(m0["loss"])
        # router actually moved (gradients flow through the gates)
        router1 = np.asarray(state.master_params["layers"]["router_kernel"])
        assert np.abs(router1 - router0).sum() > 0

    @pytest.mark.slow   # dryrun moe phase covers expert-parallel parity
    def test_gspmd_expert_parallel_step(self):
        from apex_tpu.optimizers import fused_adam

        cfg = tiny_cfg(num_experts=4, remat=False)
        mesh = create_mesh(dp=2, ep=4, tp=1, pp=1)
        init, step = make_gpt_train_step(
            cfg, fused_adam(lr=1e-3), "O2", mesh)
        state = init(jax.random.PRNGKey(13))
        tokens, labels = data(cfg, b=4)
        state, m = step(state, tokens, labels)
        assert np.isfinite(float(m["loss"]))


class TestGPTMoESwiglu:
    """Round-3: the MoE + SwiGLU combination (gate lifted)."""

    @pytest.mark.slow   # MoE+SwiGLU combo; components covered separately
    def test_forward_and_train(self):
        from apex_tpu.optimizers import fused_adam

        cfg = tiny_cfg(num_experts=4, activation="swiglu", remat=False)
        params = init_gpt_params(jax.random.PRNGKey(20), cfg)
        f = cfg.ffn_hidden_size
        assert params["layers"]["moe_fc1"].shape[-1] == 2 * f
        tokens, labels = data(cfg)
        loss = gpt_loss(params, tokens, labels, cfg)
        assert np.isfinite(float(loss))

        init, step = make_gpt_train_step(cfg, fused_adam(lr=1e-3), "O0")
        state = init(jax.random.PRNGKey(21))
        state, m0 = step(state, tokens, labels)
        for _ in range(8):
            state, m = step(state, tokens, labels)
        assert float(m["loss"]) < float(m0["loss"])


class TestGPTMoEPipeline:
    """Round-3: MoE composes with the shard_map pipeline — experts run
    locally per stage, the aux loss rides the packet to the last stage."""

    def _run_pipeline(self, cfg, params, tokens, labels, pp, n_micro, mb,
                      vpp=None):
        from apex_tpu.models.gpt import stack_pipeline_params_vpp

        stacked = (stack_pipeline_params_vpp(params, cfg, pp, vpp)
                   if vpp else stack_pipeline_params(params, cfg, pp))
        tokens_mb = tokens.reshape(n_micro, mb, -1)
        labels_mb = labels.reshape(n_micro, mb, -1)
        packets = pipeline_packet(tokens_mb, labels_mb, cfg)
        mesh = create_mesh(pp=pp, tp=1)
        # pp_axis set -> gpt_param_specs already drops 'ep' (local experts)
        pspecs = gpt_param_specs(cfg, pp_axis="pp")
        pspecs = jax.tree_util.tree_map(
            lambda s: P(*(a if a != "tp" else None for a in s)),
            pspecs, is_leaf=lambda x: isinstance(x, P))
        if vpp:
            from apex_tpu.models.gpt import (
                gpt_vpp_loss_and_grads, make_gpt_vpp_stage)

            vspecs = jax.tree_util.tree_map(
                lambda s: P(None, *s), pspecs,
                is_leaf=lambda x: isinstance(x, P))
            grad_specs = dict(pspecs)
            grad_specs["layers"] = vspecs["layers"]
            in_v = dict(vspecs)
            in_v["chunk_id"] = P(None, "pp")
            stage_fn = make_gpt_vpp_stage(cfg, pp, vpp)

            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(in_v, P()), out_specs=(P(), grad_specs))
            def run(p, mbs):
                return gpt_vpp_loss_and_grads(
                    stage_fn, p, mbs, n_micro=n_micro, vpp=vpp)
        else:
            stage_fn = make_gpt_pipeline_stage(cfg, pp, 1)

            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(pspecs, P()), out_specs=(P(), pspecs))
            def run(p, mbs):
                return gpt_pipeline_loss_and_grads(
                    stage_fn, p, mbs, n_micro=n_micro)

        return run(stacked, packets)

    @pytest.mark.slow   # dryrun pipeline phase asserts MoE x PP parity
    def test_moe_pipeline_matches_sequential(self):
        pp, n_micro, mb = 2, 2, 2
        cfg = tiny_cfg(num_experts=4, num_layers=4, remat=False)
        params = init_gpt_params(jax.random.PRNGKey(30), cfg)
        tokens, labels = data(cfg, b=n_micro * mb)

        def ref_loss(p):
            per = [gpt_loss(p, tokens.reshape(n_micro, mb, -1)[i],
                            labels.reshape(n_micro, mb, -1)[i], cfg)
                   for i in range(n_micro)]
            return jnp.mean(jnp.stack(per))

        ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
        loss, grads = self._run_pipeline(
            cfg, params, tokens, labels, pp, n_micro, mb)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        # expert + router grads agree with the sequential model
        ref_stacked = stack_pipeline_params(ref_g, cfg, pp)
        for key in ("router_kernel", "moe_fc1", "moe_fc2"):
            np.testing.assert_allclose(
                np.asarray(grads["layers"][key]),
                np.asarray(ref_stacked["layers"][key]),
                atol=3e-4, err_msg=key)

    @pytest.mark.slow
    def test_moe_vpp_matches_sequential(self):
        pp, vpp, n_micro, mb = 2, 2, 4, 2
        cfg = tiny_cfg(num_experts=4, num_layers=4, remat=False)
        params = init_gpt_params(jax.random.PRNGKey(31), cfg)
        tokens, labels = data(cfg, b=n_micro * mb)

        def ref_loss(p):
            per = [gpt_loss(p, tokens.reshape(n_micro, mb, -1)[i],
                            labels.reshape(n_micro, mb, -1)[i], cfg)
                   for i in range(n_micro)]
            return jnp.mean(jnp.stack(per))

        ref_l, _ = jax.value_and_grad(ref_loss)(params)
        loss, _ = self._run_pipeline(
            cfg, params, tokens, labels, pp, n_micro, mb, vpp=vpp)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)


class TestResidualPostLayernorm:
    """apply_residual_connection_post_layernorm (reference
    standalone_transformer_lm.py:620,707,738): residual taken from the
    LN output instead of the block input."""

    def test_flag_changes_output_and_matches_manual(self):
        import dataclasses

        from apex_tpu.models.transformer_lm import (
            apply_norm, gpt_forward, single_device_ctx, _attention, _mlp)

        cfg = tiny_cfg(num_layers=1, remat=False, scan_layers=False,
                       compute_dtype=jnp.float32)
        cfg_post = dataclasses.replace(
            cfg, apply_residual_connection_post_layernorm=True)
        params = init_gpt_params(jax.random.PRNGKey(40), cfg)
        tokens, _ = data(cfg)

        pre = gpt_forward(params, tokens, cfg)
        post = gpt_forward(params, tokens, cfg_post)
        assert not np.allclose(np.asarray(pre), np.asarray(post))

        # manual single-layer recomputation of the post-LN-residual rule
        ctx = single_device_ctx()
        from apex_tpu.models.transformer_lm import embed_tokens

        lp = jax.tree_util.tree_map(lambda v: v[0], params["layers"])
        x = embed_tokens(params["embedding"], tokens, cfg_post, ctx)
        h = apply_norm(cfg_post, x, lp["ln1_scale"], lp["ln1_bias"])
        x = h + _attention(cfg_post, lp, h, ctx, None, None, None)
        h = apply_norm(cfg_post, x, lp["ln2_scale"], lp["ln2_bias"])
        x = h + _mlp(cfg_post, lp, h, ctx)
        x = apply_norm(cfg_post, x, params["final_ln"]["scale"],
                       params["final_ln"]["bias"])
        from apex_tpu.models.transformer_lm import lm_head_logits

        want = lm_head_logits(params, x, cfg_post)
        np.testing.assert_allclose(np.asarray(post), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


class TestDropPath:
    """drop_path stochastic depth (reference DropPath,
    standalone_transformer_lm.py:712-728)."""

    def test_whole_branch_dropped_per_sample(self):
        from apex_tpu.models.transformer_lm import _drop_path

        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(16, 8, 4), jnp.float32)
        out = np.asarray(_drop_path(x, 0.5, jax.random.PRNGKey(0)))
        kept = dropped = 0
        for i in range(16):
            if np.all(out[i] == 0.0):
                dropped += 1
            else:
                # kept samples carry the WHOLE branch, scaled 1/(1-p)
                np.testing.assert_allclose(
                    out[i], np.asarray(x)[i] / 0.5, rtol=1e-6)
                kept += 1
        assert kept > 0 and dropped > 0, (kept, dropped)

        # and it actually perturbs a model forward
        import dataclasses

        cfg = tiny_cfg(num_layers=1, remat=False, scan_layers=False,
                       compute_dtype=jnp.float32)
        cfg_dp = dataclasses.replace(cfg, drop_path_rate=0.99)
        params = init_gpt_params(jax.random.PRNGKey(41), cfg)
        tokens, _ = data(cfg, b=8)
        from apex_tpu.models.transformer_lm import gpt_forward

        got = gpt_forward(params, tokens, cfg_dp,
                          dropout_rng=jax.random.PRNGKey(0))
        base = gpt_forward(params, tokens, cfg,
                           dropout_rng=jax.random.PRNGKey(0))
        assert not np.allclose(np.asarray(got), np.asarray(base))
        assert np.isfinite(np.asarray(got)).all()

    def test_eval_mode_unaffected(self):
        import dataclasses

        cfg = tiny_cfg(num_layers=2, remat=False,
                       compute_dtype=jnp.float32)
        cfg_dp = dataclasses.replace(cfg, drop_path_rate=0.5)
        params = init_gpt_params(jax.random.PRNGKey(42), cfg)
        tokens, _ = data(cfg)
        from apex_tpu.models.transformer_lm import gpt_forward

        # no rng -> deterministic eval path, identical to rate 0
        a = gpt_forward(params, tokens, cfg)
        b = gpt_forward(params, tokens, cfg_dp)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_expected_value_preserved(self):
        import dataclasses

        cfg = tiny_cfg(num_layers=1, remat=False, scan_layers=False,
                       compute_dtype=jnp.float32, hidden_size=32,
                       num_attention_heads=2)
        cfg_dp = dataclasses.replace(cfg, drop_path_rate=0.3)
        params = init_gpt_params(jax.random.PRNGKey(43), cfg)
        tokens, _ = data(cfg, b=4)
        from apex_tpu.models.transformer_lm import gpt_forward

        base = np.asarray(gpt_forward(params, tokens, cfg))
        outs = []
        fwd = jax.jit(lambda r: gpt_forward(params, tokens, cfg_dp,
                                            dropout_rng=r))
        for i in range(300):
            outs.append(np.asarray(fwd(jax.random.PRNGKey(i))))
        mean = np.mean(outs, axis=0)
        # E[drop_path(x)] == x: the scaled-branch mean approaches the
        # deterministic forward (loose tolerance; 300 samples)
        err = np.abs(mean - base).mean() / (np.abs(base).mean() + 1e-6)
        assert err < 0.15, err
