"""Speculative decoding (ISSUE 8): verify-forward parity, n-gram
drafting, rejection-sampling correctness, and the acceptance pins —
spec-on greedy token-identical to spec-off greedy on BOTH cache
layouts (generate and the serving engine, preempt→resume included)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import (
    decode_step, decode_verify, generate, init_kv_cache, prefill)
from apex_tpu.models.speculative import (
    SpecConfig, _accept, ngram_draft, resolve_spec, spec_generate)
from apex_tpu.models.transformer_lm import init_gpt_params


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 96)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


class TestDecodeVerify:
    """Feeding the gold sequence through decode_verify must reproduce
    decode_step run m times — the strongest pin of the multi-token
    cache math (write positions, per-query causal masks, rope
    offsets)."""

    @pytest.mark.parametrize("variant", [
        {},
        {"position_embedding_type": "rope", "num_query_groups": 2},
    ])
    @pytest.mark.parametrize("layout,bs", [("contiguous", 16),
                                           ("paged", 4)])
    def test_verify_matches_stepwise_decode(self, variant, layout, bs):
        cfg = _cfg(**variant)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        b, s = 2, 10
        toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)),
                           jnp.int32)
        cache = init_kv_cache(cfg, b, s, cache_layout=layout,
                              block_size=bs)
        want = []
        for i in range(s):
            lg, cache = decode_step(params, toks[:, i], cache, cfg)
            want.append(np.asarray(lg))
        want = np.stack(want, 1)
        vcache = init_kv_cache(cfg, b, s, cache_layout=layout,
                               block_size=bs)
        got, vcache = decode_verify(params, toks, vcache, cfg)
        np.testing.assert_allclose(np.asarray(got), want, atol=2e-4,
                                   rtol=2e-4, err_msg=f"{variant}")
        np.testing.assert_array_equal(np.asarray(vcache["pos"]),
                                      np.full((b,), s))
        # the written caches must agree too (verify's K/V land where
        # the stepwise decode would have put them)
        np.testing.assert_allclose(np.asarray(vcache["k"]),
                                   np.asarray(cache["k"]), atol=2e-4,
                                   rtol=2e-4)

    def test_verify_after_prefill_at_ragged_offsets(self):
        """Verify appended mid-sequence (after a ragged prefill) sees
        per-sequence offsets — the spec-round geometry."""
        cfg = _cfg(position_embedding_type="rope")
        params = init_gpt_params(jax.random.PRNGKey(1), cfg)
        rng = np.random.RandomState(1)
        b, s, m = 2, 8, 3
        toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s + m)),
                           jnp.int32)
        lens = jnp.asarray([4, 8], jnp.int32)
        cache = init_kv_cache(cfg, b, s + m)
        _, cache = prefill(params, toks[:, :s], cfg, prompt_lens=lens,
                           cache=cache)
        # continue each row from its own length with the gold tokens
        nxt = jnp.stack([toks[i, lens[i]: lens[i] + m]
                         for i in range(b)])
        got, _ = decode_verify(params, nxt, cache, cfg)
        for i in range(b):
            scache = init_kv_cache(cfg, 1, s + m)
            _, scache = prefill(params, toks[i: i + 1, : lens[i]], cfg,
                                cache=scache)
            for j in range(m):
                lg, scache = decode_step(
                    params, nxt[i: i + 1, j], scache, cfg)
                np.testing.assert_allclose(
                    np.asarray(got)[i, j], np.asarray(lg)[0],
                    atol=2e-4, rtol=2e-4, err_msg=f"row {i} pos {j}")


class TestNgramDraft:
    def test_suffix_match_proposes_continuation(self):
        # history: 5 6 7 9 5 6 7 | suffix (5 6 7) matched at j=2 ->
        # draft the tokens that followed: 9, then 5, 6 (most recent
        # occurrence of the trigram ends at index 2)
        toks = jnp.asarray([[5, 6, 7, 9, 5, 6, 7, 0, 0]], jnp.int32)
        lens = jnp.asarray([7], jnp.int32)
        d = np.asarray(ngram_draft(toks, lens, k=3, max_ngram=3))
        np.testing.assert_array_equal(d, [[9, 5, 6]])

    def test_most_recent_match_wins(self):
        # bigram (1 2) occurs twice; the later occurrence (followed by
        # 8) must win over the earlier one (followed by 7)
        toks = jnp.asarray([[1, 2, 7, 1, 2, 8, 3, 1, 2]], jnp.int32)
        lens = jnp.asarray([9], jnp.int32)
        d = np.asarray(ngram_draft(toks, lens, k=1, max_ngram=2))
        np.testing.assert_array_equal(d, [[8]])

    def test_longer_ngram_preferred(self):
        # unigram 2 matches in several places, but the full bigram
        # (9 2) pins the 4 continuation; a unigram-only drafter could
        # pick the 5 after the other 2
        toks = jnp.asarray([[2, 5, 9, 2, 4, 6, 9, 2]], jnp.int32)
        lens = jnp.asarray([8], jnp.int32)
        d = np.asarray(ngram_draft(toks, lens, k=1, max_ngram=2))
        np.testing.assert_array_equal(d, [[4]])

    def test_no_match_repeats_last_token(self):
        toks = jnp.asarray([[1, 2, 3, 4, 5, 0]], jnp.int32)
        lens = jnp.asarray([5], jnp.int32)
        d = np.asarray(ngram_draft(toks, lens, k=3, max_ngram=3))
        np.testing.assert_array_equal(d, [[5, 5, 5]])

    def test_respects_per_row_lens(self):
        # row garbage past lens must not produce matches
        toks = jnp.asarray([[7, 8, 7, 99, 99, 99],
                            [3, 3, 3, 3, 3, 3]], jnp.int32)
        lens = jnp.asarray([3, 6], jnp.int32)
        d = np.asarray(ngram_draft(toks, lens, k=2, max_ngram=2))
        np.testing.assert_array_equal(d[0], [8, 7])   # 7 matched at 0
        np.testing.assert_array_equal(d[1], [3, 3])


class TestRejectionSampling:
    def test_greedy_onehot_accepts_iff_argmax(self):
        v = 8
        logits = jnp.asarray(np.random.RandomState(0).randn(3, 3, v),
                             jnp.float32)
        tgt = np.asarray(logits).argmax(-1)          # [3, 3]
        probs = jax.nn.one_hot(jnp.argmax(logits, -1), v,
                               dtype=jnp.float32)
        # draft row 0: both match; row 1: first mismatches; row 2:
        # first matches, second mismatches
        draft = jnp.asarray([
            [tgt[0, 0], tgt[0, 1]],
            [(tgt[1, 0] + 1) % v, tgt[1, 1]],
            [tgt[2, 0], (tgt[2, 1] + 1) % v],
        ], jnp.int32)
        n_acc, y = _accept(draft, probs, None, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(n_acc), [2, 0, 1])
        # the correction/bonus token is the target argmax at the
        # first-divergence position
        np.testing.assert_array_equal(
            np.asarray(y), [tgt[0, 2], tgt[1, 0], tgt[2, 1]])

    def test_point_mass_marginal_is_target_distribution(self):
        """The speculative-sampling identity for a point-mass drafter:
        accept d with prob p(d), else resample from p with d removed —
        the emitted marginal must equal p exactly.  N independent rows
        in ONE _accept call (per-row uniforms), χ² against p."""
        rng = np.random.RandomState(1)
        v, n = 6, 8192
        p_row = jax.nn.softmax(jnp.asarray(rng.randn(v), jnp.float32))
        p = np.asarray(p_row)
        draft_tok = int(np.argmax(p))                # draft the mode
        probs = jnp.tile(p_row[None, None], (n, 2, 1))
        draft = jnp.full((n, 1), draft_tok, jnp.int32)
        n_acc, y = _accept(draft, probs, None, jax.random.PRNGKey(2))
        emitted = np.where(np.asarray(n_acc) >= 1, draft_tok,
                           np.asarray(y))
        counts = np.bincount(emitted, minlength=v)
        chi2 = (((counts - n * p) ** 2) / (n * p)).sum()
        assert chi2 < 20.52, chi2     # chi2(5).ppf(0.999)

    def test_draft_model_hook_ratio_accept(self):
        """q_probs given: accept iff u < p(d)/q(d) — a draft whose q
        UNDERSTATES p must always be accepted (ratio > 1)."""
        v = 4
        p = jnp.asarray([[0.7, 0.1, 0.1, 0.1]], jnp.float32)
        probs = jnp.tile(p[:, None], (1, 2, 1))
        q = jnp.asarray([[[0.25, 0.25, 0.25, 0.25]]], jnp.float32)
        draft = jnp.asarray([[0]], jnp.int32)        # p=0.7 > q=0.25
        for seed in range(10):
            n_acc, _ = _accept(draft, probs, q, jax.random.PRNGKey(seed))
            assert int(n_acc[0]) == 1, seed


class TestSpecGenerateParity:
    """The acceptance pin: spec-on greedy output token-identical to
    spec-off greedy, both cache layouts, ragged + EOS included."""

    @pytest.mark.parametrize("layout,bs", [("contiguous", 16),
                                           ("paged", 4)])
    def test_greedy_token_identical(self, layout, bs):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(1), cfg)
        rng = np.random.RandomState(1)
        prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 6)),
                             jnp.int32)
        base = np.asarray(generate(params, prompt, cfg,
                                   max_new_tokens=20,
                                   cache_layout=layout, block_size=bs))
        out, stats = spec_generate(params, prompt, cfg,
                                   spec=SpecConfig(k=4),
                                   max_new_tokens=20,
                                   cache_layout=layout, block_size=bs)
        np.testing.assert_array_equal(base, np.asarray(out))
        assert stats["verify_calls"] >= 1
        assert 0 <= stats["accepted_tokens"] <= stats["draft_tokens"]
        # the generate(spec=...) wrapper takes the same path
        wrapped = generate(params, prompt, cfg, max_new_tokens=20,
                           cache_layout=layout, block_size=bs,
                           spec=SpecConfig(k=4))
        np.testing.assert_array_equal(base, np.asarray(wrapped))

    def test_greedy_identical_with_eos_and_ragged(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(2), cfg)
        rng = np.random.RandomState(2)
        prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 8)),
                             jnp.int32)
        lens = jnp.asarray([3, 8], jnp.int32)
        base = np.asarray(generate(params, prompt, cfg,
                                   max_new_tokens=14,
                                   prompt_lens=lens))
        out, _ = spec_generate(params, prompt, cfg, spec="ngram",
                               max_new_tokens=14, prompt_lens=lens)
        np.testing.assert_array_equal(base, np.asarray(out))
        eos = int(base[0, 6])    # a mid-generation token of row 0
        base_e = np.asarray(generate(params, prompt, cfg,
                                     max_new_tokens=14,
                                     prompt_lens=lens,
                                     eos_token_id=eos))
        out_e, _ = spec_generate(params, prompt, cfg, spec="ngram",
                                 max_new_tokens=14, prompt_lens=lens,
                                 eos_token_id=eos)
        np.testing.assert_array_equal(base_e, np.asarray(out_e))

    def test_high_accept_on_self_repetition(self):
        """Greedy decoding of a tiny model self-repeats; the n-gram
        drafter must catch the loop — the amortization the whole
        feature exists for (and the bench high-accept sweep's
        mechanism)."""
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(3), cfg)
        rng = np.random.RandomState(3)
        prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 6)),
                             jnp.int32)
        _, stats = spec_generate(params, prompt, cfg,
                                 spec=SpecConfig(k=4),
                                 max_new_tokens=32)
        accept = stats["accepted_tokens"] / max(stats["draft_tokens"], 1)
        assert accept > 0.5, stats
        # far fewer verify passes than tokens
        assert stats["verify_calls"] < 32

    def test_stochastic_seeded_and_supported(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(4), cfg)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        a, _ = spec_generate(params, prompt, cfg, spec="ngram",
                             max_new_tokens=10, temperature=1.0,
                             top_k=5, rng=jax.random.PRNGKey(7))
        b, _ = spec_generate(params, prompt, cfg, spec="ngram",
                             max_new_tokens=10, temperature=1.0,
                             top_k=5, rng=jax.random.PRNGKey(7))
        c, _ = spec_generate(params, prompt, cfg, spec="ngram",
                             max_new_tokens=10, temperature=1.0,
                             top_k=5, rng=jax.random.PRNGKey(8))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))
        assert np.asarray(a).max() < cfg.vocab_size

    def test_spec_counters_reach_telemetry(self):
        from apex_tpu.observability import metrics as telemetry

        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(5), cfg)
        prompt = jnp.asarray([[4, 5, 6]], jnp.int32)
        reg = telemetry.configure()
        try:
            generate(params, prompt, cfg, max_new_tokens=12,
                     spec="ngram")
            draft = reg.counter("generate.spec.draft_tokens").value
            acc = reg.counter("generate.spec.accepted_tokens").value
            verify = reg.counter("generate.spec.verify_calls").value
            assert draft > 0 and verify > 0
            assert 0 <= acc <= draft
        finally:
            telemetry.shutdown()

    def test_config_validation(self):
        with pytest.raises(ValueError, match="spec"):
            resolve_spec("warp")
        with pytest.raises(ValueError, match="k="):
            SpecConfig(k=0)
        with pytest.raises(ValueError, match="ngram"):
            SpecConfig(max_ngram=0)
        assert resolve_spec(None) is None
        assert resolve_spec("off") is None
        assert resolve_spec("ngram").k == 8

    def test_draft_model_hook_greedy_identity(self):
        """A (bad) draft model must still be CORRECT: rejection
        sampling fixes up every wrong draft, so greedy output stays
        token-identical — drafting quality is a speed knob only."""
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(6), cfg)
        prompt = jnp.asarray([[9, 8, 7]], jnp.int32)

        def bad_draft(tokens, lens, k):
            # always propose token 1 with a uniform q
            b = tokens.shape[0]
            q = jnp.full((b, k, cfg.vocab_size),
                         1.0 / cfg.vocab_size, jnp.float32)
            return jnp.ones((b, k), jnp.int32), q

        base = np.asarray(generate(params, prompt, cfg,
                                   max_new_tokens=12))
        out, stats = spec_generate(
            params, prompt, cfg, spec=SpecConfig(k=3,
                                                 draft_fn=bad_draft),
            max_new_tokens=12)
        np.testing.assert_array_equal(base, np.asarray(out))


class TestServingEngineSpec:
    def _model(self):
        cfg = _cfg(max_position_embeddings=128)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        return cfg, params

    @pytest.mark.parametrize("layout,bs", [("contiguous", 16),
                                           ("paged", 8)])
    def test_engine_greedy_identical(self, layout, bs):
        from apex_tpu.serving import ServingEngine

        cfg, params = self._model()
        rng = np.random.RandomState(0)
        reqs = [dict(prompt=rng.randint(0, cfg.vocab_size, (n,)),
                     max_new_tokens=16) for n in (5, 9, 3)]
        base = ServingEngine(params, cfg, max_slots=2, max_len=64,
                             cache_layout=layout, block_size=bs
                             ).run([dict(r) for r in reqs])
        spec = ServingEngine(params, cfg, max_slots=2, max_len=64,
                             cache_layout=layout, block_size=bs,
                             spec="ngram").run([dict(r) for r in reqs])
        for b, s in zip(base, spec):
            np.testing.assert_array_equal(b.tokens, s.tokens)
            # multi-token emission: polls < tokens for at least the
            # self-repeating rows, never more than tokens
            assert s.decode_steps <= b.decode_steps

    def test_engine_spec_preempt_resume_identical(self):
        """Spec + paged preemption compose: a starved pool that forces
        preempt→resume must still produce token-identical greedy
        output vs an unstarved spec engine."""
        from apex_tpu.serving import ServingEngine

        cfg, params = self._model()
        rng = np.random.RandomState(3)
        reqs = [dict(prompt=rng.randint(0, cfg.vocab_size, (12,)),
                     max_new_tokens=24) for _ in range(3)]
        big = ServingEngine(params, cfg, max_slots=3, max_len=64,
                            cache_layout="paged", block_size=4,
                            spec="ngram").run([dict(r) for r in reqs])
        small = ServingEngine(params, cfg, max_slots=3, max_len=64,
                              cache_layout="paged", block_size=4,
                              num_blocks=24, spec="ngram")
        out = small.run([dict(r) for r in reqs])
        assert small.stats()["preemptions"] >= 1    # starvation forced
        for b, s in zip(big, out):
            np.testing.assert_array_equal(b.tokens, s.tokens)
        # polls survive the preemption accounting (coherence envelope)
        k = small.stats()["spec_k"]
        for r in out:
            emitted = r.tokens.size - 1 - r.preemptions
            assert 1 <= r.decode_steps <= max(emitted, 1)
            assert emitted <= r.decode_steps * (k + 1)
