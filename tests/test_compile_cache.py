"""serving/compile_cache.py: persistent AOT compile cache (ISSUE 17).

The cold-start acceptance pins: an executable saved by one process
must load in a FRESH process and produce bitwise-identical logits; a
changed :func:`code_version` digest must invalidate (miss, never a
wrong hit); a torn cache entry or manifest must degrade to a miss,
never a crash; and :func:`warmup_ladder` must prime every executable
the engine needs so a second engine over the same directory serves
with zero compile misses."""

import hashlib
import json
import os
import pickle
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.serving import ServingEngine
from apex_tpu.serving import compile_cache as cc_mod
from apex_tpu.serving.compile_cache import (
    CompileCache, code_version, warmup_ladder)


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@jax.jit
def _double(x):
    return x * 2.0


class TestCompileCacheUnit:
    def test_round_trip_same_dir_is_hit(self, tmp_path):
        x = jnp.arange(8, dtype=jnp.float32)
        a = CompileCache(str(tmp_path))
        fn = a.load_or_compile("double", _double, (x,))
        assert a.misses == 1 and a.hits == 0
        np.testing.assert_array_equal(np.asarray(fn(x)),
                                      np.asarray(x) * 2)
        # a fresh instance over the same dir (= a fresh process's view)
        b = CompileCache(str(tmp_path))
        fn2 = b.load_or_compile("double", _double, (x,))
        assert b.hits == 1 and b.misses == 0
        np.testing.assert_array_equal(np.asarray(fn2(x)),
                                      np.asarray(x) * 2)
        assert b.stats()["entries"] == 1

    def test_memo_short_circuits_counters(self, tmp_path):
        x = jnp.ones((4,), jnp.float32)
        cc = CompileCache(str(tmp_path))
        cc.load_or_compile("double", _double, (x,))
        cc.load_or_compile("double", _double, (x,))
        # second call served from the per-process memo: no new counts
        assert (cc.hits, cc.misses) == (0, 1)

    def test_sds_and_concrete_share_a_key(self, tmp_path):
        x = jnp.ones((4,), jnp.float32)
        sds = jax.ShapeDtypeStruct(x.shape, x.dtype)
        cc = CompileCache(str(tmp_path))
        assert (cc.key_for("double", (sds,))
                == cc.key_for("double", (x,)))

    def test_key_covers_avals_and_parts(self, tmp_path):
        cc = CompileCache(str(tmp_path))
        a = jnp.ones((4,), jnp.float32)
        b = jnp.ones((8,), jnp.float32)
        c = jnp.ones((4,), jnp.bfloat16)
        k = cc.key_for("f", (a,))
        assert cc.key_for("f", (b,)) != k
        assert cc.key_for("f", (c,)) != k
        assert cc.key_for("g", (a,)) != k
        assert cc.key_for("f", (a,), key_parts={"bucket": 8}) != k

    def test_stale_code_version_invalidates(self, tmp_path,
                                            monkeypatch):
        x = jnp.ones((4,), jnp.float32)
        a = CompileCache(str(tmp_path))
        a.load_or_compile("double", _double, (x,))
        assert a.misses == 1
        # the package "changed": same dir, new digest -> a different
        # key, so the old entry is orphaned, never wrongly hit
        monkeypatch.setattr(cc_mod, "code_version", lambda: "stale!")
        b = CompileCache(str(tmp_path))
        fn = b.load_or_compile("double", _double, (x,))
        assert b.misses == 1 and b.hits == 0
        np.testing.assert_array_equal(np.asarray(fn(x)),
                                      np.asarray(x) * 2)

    def test_torn_entry_is_miss_not_crash(self, tmp_path):
        x = jnp.ones((4,), jnp.float32)
        a = CompileCache(str(tmp_path))
        key = a.key_for("double", (x,))
        a.load_or_compile("double", _double, (x,))
        path = os.path.join(str(tmp_path), key + ".xc")
        with open(path, "wb") as f:
            f.write(b"\x00torn bytes, not a pickle")
        b = CompileCache(str(tmp_path))
        fn = b.load_or_compile("double", _double, (x,))
        assert b.misses == 1 and b.hits == 0
        np.testing.assert_array_equal(np.asarray(fn(x)),
                                      np.asarray(x) * 2)
        # the recompile overwrote the torn entry: next reader hits
        c = CompileCache(str(tmp_path))
        c.load_or_compile("double", _double, (x,))
        assert c.hits == 1

    def test_unpicklable_but_valid_pickle_is_miss(self, tmp_path):
        """A well-formed pickle of the WRONG shape (version skew)
        must also degrade to a miss."""
        x = jnp.ones((4,), jnp.float32)
        a = CompileCache(str(tmp_path))
        key = a.key_for("double", (x,))
        with open(os.path.join(str(tmp_path), key + ".xc"), "wb") as f:
            pickle.dump({"not": "an executable"}, f)
        fn = a.load_or_compile("double", _double, (x,))
        assert a.misses == 1
        np.testing.assert_array_equal(np.asarray(fn(x)),
                                      np.asarray(x) * 2)

    def test_torn_manifest_degrades_to_empty(self, tmp_path):
        with open(os.path.join(str(tmp_path), "manifest.json"),
                  "w") as f:
            f.write("{torn json")
        cc = CompileCache(str(tmp_path))
        assert cc.stats()["entries"] == 0
        x = jnp.ones((4,), jnp.float32)
        cc.load_or_compile("double", _double, (x,))
        # the save re-indexes: the manifest heals
        with open(os.path.join(str(tmp_path), "manifest.json")) as f:
            m = json.load(f)
        assert len(m) == 1

    def test_not_aot_able_returns_none(self, tmp_path):
        cc = CompileCache(str(tmp_path))
        assert cc.load_or_compile("plain", lambda x: x,
                                  (jnp.ones(2),)) is None

    def test_code_version_is_stable_in_process(self):
        assert code_version() == code_version()
        assert len(code_version()) == 16


def _mk_engine(model, d, **kw):
    cfg, params = model
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("cache_layout", "paged")
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 24)
    return ServingEngine(params, cfg,
                         compile_cache_dir=(None if d is None
                                            else str(d)), **kw)


def _reqs(cfg, n=2):
    rng = np.random.RandomState(3)
    return [dict(prompt=rng.randint(0, cfg.vocab_size,
                                    (5 + i,)).astype(np.int32),
                 max_new_tokens=6) for i in range(n)]


class TestEngineRoundTrip:
    def test_cached_engine_tokens_identical_and_second_run_hits(
            self, model, tmp_path):
        cfg, params = model
        want = [r.tokens for r in _mk_engine(model, None).run(
            _reqs(cfg))]
        cold = _mk_engine(model, tmp_path)
        got = [r.tokens for r in cold.run(_reqs(cfg))]
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)
        st = cold.stats()["compile_cache"]
        assert st["misses"] > 0
        # fresh engine over the primed dir: loads, no compiles
        warm = _mk_engine(model, tmp_path)
        got2 = [r.tokens for r in warm.run(_reqs(cfg))]
        for g, w in zip(got2, want):
            np.testing.assert_array_equal(g, w)
        st2 = warm.stats()["compile_cache"]
        assert st2["hits"] > 0 and st2["misses"] == 0

    def test_no_cache_dir_stats_none(self, model):
        assert _mk_engine(model, None).stats()["compile_cache"] is None

    def test_warmup_ladder_primes_everything(self, model, tmp_path):
        cfg, _ = model
        eng = _mk_engine(model, tmp_path, chunk_tokens=8)
        out = warmup_ladder(eng)
        assert out["skipped"] == [], out["skipped"]
        # prefill+insert per bucket, decode, sample, chunk
        assert out["entries"] == 2 * len(eng.buckets) + 3
        assert out["misses"] == out["entries"] and out["hits"] == 0
        assert out["ms"] > 0
        # a fresh engine warms from disk alone...
        warm = _mk_engine(model, tmp_path, chunk_tokens=8)
        out2 = warmup_ladder(warm)
        assert out2["hits"] == out["entries"]
        assert out2["misses"] == 0 and out2["skipped"] == []
        # ...and then serves with ZERO further cache misses
        got = [r.tokens for r in warm.run(_reqs(cfg))]
        assert warm.stats()["compile_cache"]["misses"] == 0
        want = [r.tokens for r in _mk_engine(
            model, None, chunk_tokens=8).run(_reqs(cfg))]
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    def test_warmup_without_cache_is_a_noop(self, model):
        out = warmup_ladder(_mk_engine(model, None))
        assert out["entries"] == 0
        assert out["skipped"] == [("*", "no compile_cache_dir")]


_FRESH = r"""
import hashlib, json, sys
import jax
if not hasattr(jax, "typeof"):
    jax.typeof = lambda x: jax.core.get_aval(x)
import jax.numpy as jnp
import numpy as np
from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import prefill
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.serving.compile_cache import CompileCache

cfg = TransformerConfig(num_layers=1, hidden_size=32,
                        num_attention_heads=2, vocab_size=64,
                        max_position_embeddings=16,
                        compute_dtype=jnp.float32, remat=False)
params = init_gpt_params(jax.random.PRNGKey(0), cfg)
prompt = jnp.asarray([[1, 2, 3, 4, 0, 0, 0, 0]], jnp.int32)
lens = jnp.asarray([4], jnp.int32)
cc = CompileCache(sys.argv[1])
fn = cc.load_or_compile(
    "prefill", prefill, (params, prompt, cfg),
    dict(prompt_lens=lens, max_len=8, cache_dtype=None),
    key_parts={"bucket": 8})
logits, _cache = fn(params, prompt, prompt_lens=lens)
print(json.dumps({
    "digest": hashlib.sha256(
        np.asarray(logits, np.float32).tobytes()).hexdigest(),
    "hits": cc.hits, "misses": cc.misses}))
"""


class TestFreshProcess:
    def test_fresh_process_load_bitwise_logits(self, tmp_path):
        """THE round-trip pin: process A compiles and saves, process B
        (no shared jit caches, no shared memo) loads the serialized
        executable and its logits are byte-for-byte identical."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        runs = []
        for _ in range(2):
            out = subprocess.run(
                [sys.executable, "-c", _FRESH, str(tmp_path)],
                capture_output=True, text=True, timeout=300, env=env,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))
            assert out.returncode == 0, out.stderr[-2000:]
            runs.append(json.loads(out.stdout.strip().splitlines()[-1]))
        cold, warm = runs
        assert cold["misses"] == 1 and cold["hits"] == 0
        assert warm["hits"] == 1 and warm["misses"] == 0
        assert warm["digest"] == cold["digest"]
