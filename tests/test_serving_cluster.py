"""Cluster serving tier: router, workers, requeue, degradation
(ISSUE 9).

In-process integration over REAL sockets (each WorkerServer runs its
select loop in a thread; the router talks to it exactly as it would
across hosts), so the wire protocol, dispatch policy, and failure
paths are the ones production would run — minus process isolation,
which ``bench.py --serve-trace`` and the slow two-process test cover.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import observability as obs
from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.observability.detectors import PoolStallDetector
from apex_tpu.serving import ServingEngine
from apex_tpu.serving.cluster import Router, RouterBusy, WorkerServer


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _start(server):
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t


def _pools(params, cfg, n_decode=1, **decode_kw):
    """One prefill worker + n decode workers, each serving in a
    thread; returns (servers, threads)."""
    decode_kw.setdefault("max_len", 32)
    decode_kw.setdefault("cache_layout", "paged")
    decode_kw.setdefault("block_size", 4)
    decode_kw.setdefault("max_slots", 2)
    servers = [WorkerServer("prefill", params, cfg, max_len=32)]
    servers += [WorkerServer("decode", params, cfg, **decode_kw)
                for _ in range(n_decode)]
    threads = [_start(s) for s in servers]
    return servers, threads


# ---------------------------------------------------------------------------
# worker RPC surface (no sockets: handle() directly)
# ---------------------------------------------------------------------------


class TestWorkerRPC:
    def test_hello_stats_and_bad_ops(self, model):
        cfg, params = model
        w = WorkerServer("prefill", params, cfg, max_len=32)
        try:
            reply, _ = w.handle({"op": "hello"}, [])
            assert reply["ok"] and reply["role"] == "prefill"
            reply, _ = w.handle({"op": "stats"}, [])
            assert reply["stats"]["scratch_layout"] == "paged"
            reply, _ = w.handle({"op": "poll"}, [])
            assert not reply["ok"]               # poll needs an engine
            reply, _ = w.handle({"op": "nope"}, [])
            assert not reply["ok"] and "unknown op" in reply["error"]
            reply, _ = w.handle({"op": "prefill", "prompt": []}, [])
            assert not reply["ok"]
        finally:
            w.close()

    def test_prefill_decode_rpc_pair(self, model):
        """The RPC pair end to end without a router: prefill returns a
        KV handoff the decode worker accepts and serves."""
        cfg, params = model
        pf = WorkerServer("prefill", params, cfg, max_len=32)
        dc = WorkerServer("decode", params, cfg, max_len=32,
                          max_slots=1)
        try:
            prompt = list(range(1, 8))
            reply, blobs = pf.handle(
                {"op": "prefill", "prompt": prompt,
                 "temperature": 0.0}, [])
            assert reply["ok"] and reply["n"] == 7
            assert reply["handoff_bytes"] == sum(len(b) for b in blobs)
            ack, _ = dc.handle(
                {"op": "decode", "rid": 42, "prompt": prompt,
                 "first_token": reply["first_token"],
                 "kv": reply["kv"], "max_new_tokens": 4}, blobs)
            assert ack["ok"] and ack["accepted"]
            for _ in range(30):
                if dc.engine.idle:
                    break
                dc._pump()
            poll, _ = dc.handle({"op": "poll"}, [])
            (resp,) = poll["responses"]
            assert resp["rid"] == 42
            assert len(resp["tokens"]) == 4
            assert poll["stats"]["queued"] == 0
        finally:
            pf.close()
            dc.close()


# ---------------------------------------------------------------------------
# routing policy units
# ---------------------------------------------------------------------------


def _bare_router(**kw):
    """A Router with no sockets — just the policy state, for admission
    and priority units."""
    from collections import deque  # noqa: F401

    r = object.__new__(Router)
    r._prefill, r._decode = [], []
    r._slo_targets = __import__(
        "apex_tpu.serving.slo", fromlist=["resolve_slo_targets"]
    ).resolve_slo_targets(None)
    r._caps = kw.get("queue_caps", {})
    r._priority = kw.get("class_priority",
                         ("interactive", "standard", "default",
                          "batch"))
    r.wire_dtype = "raw"
    r._max_worker_queue = 4
    r._queues = {}
    r._next_rid = 0
    r._pf_rr = 0
    r._last_decode_pick = None
    r._requeued_total = 0
    r._completed_total = 0
    return r


class TestRoutingPolicy:
    def test_class_priority_order(self):
        r = _bare_router()
        for cls in ("batch", "bulk-custom", "standard", "interactive"):
            r.submit([1, 2], slo_class=cls)
        order = []
        while True:
            cls = r._next_class()
            if cls is None:
                break
            order.append(cls)
            r._queues[cls].popleft()
        # interactive first, explicit batch last, unknown classes just
        # above batch
        assert order == ["interactive", "standard", "bulk-custom",
                         "batch"]

    def test_queue_caps_shed_load(self):
        r = _bare_router(queue_caps={"batch": 2})
        r.submit([1], slo_class="batch")
        r.submit([1], slo_class="batch")
        with pytest.raises(RouterBusy, match="cap"):
            r.submit([1], slo_class="batch")
        r.submit([1], slo_class="interactive")   # other classes unhurt

    def test_pool_stall_detector_latch(self):
        det = PoolStallDetector(threshold=3)
        assert det.feed("decode", False) is None
        assert det.feed("decode", False) is None
        a = det.feed("decode", False)
        assert a is not None and a.kind == "pool_stall"
        assert det.stalled("decode")
        # latched: more failures do not re-fire
        assert det.feed("decode", False) is None
        # recovery needs threshold consecutive successes
        det.feed("decode", True)
        det.feed("decode", True)
        assert det.stalled("decode")
        det.feed("decode", True)
        assert not det.stalled("decode")
        # pools are independent
        assert det.feed("prefill", False) is None

    def test_autoscale_hints_from_fleet_summary(self):
        r = _bare_router()

        class _W:
            alive = True
            draining = False
            addr = "w0"
            stats = {"free_block_headroom": 5, "max_slots": 4,
                     "active": 1}
            in_flight = {}

        r._decode = [_W()]
        r._prefill = [_W()]
        sig = r.autoscale_signal()
        assert sig["decode"]["hint"] == 0
        # a windowed fleet summary showing interactive TTFT p95 over
        # its 500ms deadline asks for prefill scale-up; TPOT over
        # deadline asks for decode scale-up
        fleet = {"sketches": {
            "serving.ttft_ms{slo_class=interactive}": {"p95": 800.0},
            "serving.tpot_ms{slo_class=interactive}": {"p95": 90.0},
        }}
        sig = r.autoscale_signal(fleet)
        assert sig["prefill"]["hint"] == 1
        assert sig["decode"]["hint"] == 1
        assert set(sig["slo_violations"]) == {"interactive:ttft",
                                              "interactive:tpot"}


# ---------------------------------------------------------------------------
# integration over real sockets
# ---------------------------------------------------------------------------


class TestClusterIntegration:
    def test_token_identity_and_telemetry(self, model):
        """Routed greedy outputs == single-engine outputs, and the
        cluster telemetry counters carry the routing evidence."""
        cfg, params = model
        reg = obs.configure()
        rng = np.random.RandomState(1)
        prompts = [rng.randint(0, 128, (3 + 2 * i,)) for i in range(5)]
        classes = ["interactive", "standard", "batch", "default",
                   "interactive"]

        single = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               cache_layout="paged", block_size=4)
        for p, c in zip(prompts, classes):
            single.submit(p, max_new_tokens=4, slo_class=c)
        ref = {}
        while not single.idle:
            for r in single.step():
                ref[tuple(r.prompt.tolist())] = r.tokens.tolist()

        servers, _ = _pools(params, cfg)
        router = Router([servers[0].addr], [servers[1].addr])
        try:
            for p, c in zip(prompts, classes):
                router.submit(p, max_new_tokens=4, slo_class=c)
            out = router.run(max_wall_s=120)
            assert len(out) == 5
            for r in out:
                assert r.tokens.tolist() == ref[tuple(
                    r.prompt.tolist())]
                assert r.handoff_bytes > 0
                assert r.pool == servers[1].addr
                assert 0 <= r.queue_wait_ms <= r.ttft_ms <= r.e2e_ms
            counters = [r for r in reg.snapshot()
                        if r["kind"] == "counter"]
            route_total = sum(r["value"] for r in counters
                              if r["name"] == "cluster.route")
            assert route_total == 5
            handoff = sum(r["value"] for r in counters
                          if r["name"] == "cluster.handoff_bytes")
            assert handoff == sum(r.handoff_bytes for r in out)
        finally:
            router.close(shutdown_workers=True)
            obs.shutdown()

    def test_killed_decode_worker_requeues_not_loses(self, model):
        """THE SOAK PIN: kill one of two decode workers mid-flight —
        every request still completes (on the survivor), requeues are
        counted, outputs stay greedy-correct."""
        cfg, params = model
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, 128, (4 + i,)) for i in range(6)]

        single = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               cache_layout="paged", block_size=4)
        for p in prompts:
            single.submit(p, max_new_tokens=6)
        ref = {}
        while not single.idle:
            for r in single.step():
                ref[tuple(r.prompt.tolist())] = r.tokens.tolist()

        servers, _ = _pools(params, cfg, n_decode=2, max_slots=1)
        victim = servers[2]
        router = Router([servers[0].addr],
                        [servers[1].addr, servers[2].addr],
                        max_worker_queue=2)
        try:
            for p in prompts:
                router.submit(p, max_new_tokens=6)
            out = []
            # step until the victim worker owns in-flight work, then
            # kill it the hard way (loop stops, sockets close)
            deadline = time.time() + 60
            while time.time() < deadline:
                out.extend(router.step())
                victim_w = next(w for w in router._decode
                                if w.addr == victim.addr)
                if victim_w.in_flight:
                    break
            assert victim_w.in_flight, "victim never got work"
            victim.stop()
            time.sleep(0.1)
            out.extend(router.run(max_wall_s=120))
            got = {tuple(r.prompt.tolist()): r.tokens.tolist()
                   for r in out}
            assert got == ref                  # nothing lost, all exact
            assert router.stats()["requeued"] >= 1
            assert any(r.requeues > 0 for r in out)
            assert all(r.pool == servers[1].addr
                       for r in out if r.requeues)
        finally:
            router.close(shutdown_workers=True)

    def test_pool_stall_latches_healthz(self, model):
        """All decode workers dead + queued work = a pool stall: the
        detector latches and the router process's /healthz answers
        503 — the degradation signal a balancer acts on."""
        import json
        import urllib.error
        import urllib.request

        cfg, params = model
        reg = obs.configure(export_port=0)
        servers, _ = _pools(params, cfg)
        router = Router([servers[0].addr], [servers[1].addr])
        try:
            url = reg.exporter.url
            assert json.loads(urllib.request.urlopen(
                url + "/healthz", timeout=5).read())["status"] == "ok"
            servers[1].stop()
            time.sleep(0.1)
            router.submit([1, 2, 3], max_new_tokens=2)
            for _ in range(5):
                router.step()
            assert reg.detectors.pool.stalled("decode")
            kinds = {a.kind for a in reg.detectors.anomalies}
            assert "pool_stall" in kinds
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url + "/healthz", timeout=5)
            assert ei.value.code == 503
            doc = json.loads(ei.value.read().decode())
            assert "pool_stall" in doc["kinds"]
            # the request is requeued, not lost
            assert router.stats()["queued"] == 1
        finally:
            router.close(shutdown_workers=True)
            servers[0].stop()
            obs.shutdown()

    def test_scrape_stats_covers_prefill_pool(self, model):
        cfg, params = model
        servers, _ = _pools(params, cfg)
        router = Router([servers[0].addr], [servers[1].addr])
        try:
            router.scrape_stats()
            st = router.stats()
            assert st["pools"]["decode"][0]["stats"]["max_slots"] == 2
            assert router._prefill[0].stats["prefill_calls"] == 0
        finally:
            router.close(shutdown_workers=True)


class TestServeDashMultiPool:
    def test_warming_pool_renders_instead_of_crashing(self, model):
        """tools/serve_dash.py multi-pool mode: one live exporter +
        one refused port render one healthy block and one 'warming
        up / unreachable' block — the loop never dies on a pool that
        is still starting."""
        import importlib.util
        import io
        import os
        import socket as socket_mod

        cfg, params = model
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "serve_dash", os.path.join(repo, "tools", "serve_dash.py"))
        dash = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(dash)
        om = dash.load_openmetrics_module()

        reg = obs.configure(export_port=0)
        try:
            engine = ServingEngine(params, cfg, max_slots=1,
                                   max_len=32)
            engine.submit([1, 2, 3], max_new_tokens=2)
            while not engine.idle:
                engine.step()
            # a port nothing listens on = a pool mid-startup
            probe = socket_mod.socket()
            probe.bind(("127.0.0.1", 0))
            dead_port = probe.getsockname()[1]
            probe.close()

            out = io.StringIO()
            live = dash.pool_frame(om, reg.exporter.url, "pool 0",
                                   out=out)
            dead = dash.pool_frame(
                om, f"http://127.0.0.1:{dead_port}", "pool 1", out=out)
            text = out.getvalue()
            assert live is not None and dead is None
            assert "pool 0" in text and "pool 1" in text
            assert "warming up / unreachable" in text
            # and the CLI multi-URL form takes the same path
            rc = dash.main(["--once", reg.exporter.url,
                            f"127.0.0.1:{dead_port}"])
            assert rc == 0
        finally:
            obs.shutdown()


@pytest.mark.slow
class TestTwoProcess:
    def test_two_process_token_identity(self, model):
        """The full two-OS-process demo (also exercised by bench.py
        --serve-trace): spawned workers, router here, greedy outputs
        pinned against the single engine."""
        from apex_tpu.serving.cluster.worker import spawn_worker

        cfg, params = model
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 128, (5 + i,)) for i in range(4)]
        single = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               cache_layout="paged", block_size=4)
        for p in prompts:
            single.submit(p, max_new_tokens=5)
        ref = {}
        while not single.idle:
            for r in single.step():
                ref[tuple(r.prompt.tolist())] = r.tokens.tolist()

        flags = ["--hidden", "64", "--heads", "4", "--vocab", "128",
                 "--max-pos", "64", "--max-len", "32"]
        procs = []
        try:
            pf_proc, pf_addr, _ = spawn_worker("prefill",
                                               extra_args=flags)
            procs.append(pf_proc)
            dc_proc, dc_addr, _ = spawn_worker(
                "decode", extra_args=flags + [
                    "--max-slots", "2", "--cache-layout", "paged",
                    "--block-size", "4"])
            procs.append(dc_proc)
            router = Router([pf_addr], [dc_addr])
            for p in prompts:
                router.submit(p, max_new_tokens=5)
            out = router.run(max_wall_s=240)
            assert {tuple(r.prompt.tolist()): r.tokens.tolist()
                    for r in out} == ref
            router.close(shutdown_workers=True)
        finally:
            from apex_tpu.serving.cluster.worker import shutdown_worker

            reaped = []
            for proc in procs:
                try:
                    shutdown_worker(proc)
                    reaped.append(proc)
                except Exception:
                    proc.kill()
            # the APX504 contract end to end: no drain thread survives
            # its child (EOF + join in shutdown_worker).  Only checked
            # where shutdown_worker actually completed — the bare-kill
            # fallback path never joined, and asserting there would
            # mask the real teardown failure.
            for proc in reaped:
                drain = getattr(proc, "drain_thread", None)
                assert drain is None or not drain.is_alive()
