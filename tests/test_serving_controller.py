"""Elastic pool controller (ISSUE 15): hysteresis policy units, the
autoscale_signal edge cases the controller now exercises, lossless
drain migration, and the controller loop over real in-process
workers."""

import io
import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import observability as obs
from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.serving import ServingEngine
from apex_tpu.serving.cluster import (
    PoolController, Router, WorkerServer)


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _start(server):
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# stub-router policy units (no sockets, no jax state)
# ---------------------------------------------------------------------------


class _StubWorker:
    def __init__(self, addr, pool):
        self.addr = addr
        self.pool = pool
        self.alive = True
        self.draining = False
        self.in_flight = {}
        self.stats = {"max_slots": 4, "active": 0,
                      "headroom_tokens": 64, "block_size": 8}


class _StubRouter:
    """The surface PoolController touches, with a scripted signal."""

    def __init__(self, hints):
        self.hints = list(hints)          # per-tick decode hints
        self._prefill = [_StubWorker("p0", "prefill")]
        self._decode = [_StubWorker("d0", "decode")]
        self.spawned = 0
        self.drained = []

    def _pool_list(self, pool):
        return self._prefill if pool == "prefill" else self._decode

    def scrape_stats(self):
        pass

    def autoscale_signal(self, fleet_summary=None):
        hint = self.hints.pop(0) if self.hints else 0
        return {"decode": {"hint": hint, "workers": len(self._decode)},
                "prefill": {"hint": 0,
                            "workers": len(self._prefill)}}

    def add_worker(self, addr, pool):
        self._pool_list(pool).append(_StubWorker(addr, pool))

    def remove_worker(self, addr):
        for pool in (self._prefill, self._decode):
            for w in list(pool):
                if w.addr == addr:
                    pool.remove(w)

    def drain_worker(self, addr):
        self.drained.append(addr)
        for w in self._decode:
            if w.addr == addr:
                w.draining = True
        return {"migrated": 1, "requeued": 0, "completed": 0}


def _stub_ctrl(hints, **kw):
    router = _StubRouter(hints)
    kw.setdefault("min_decode", 1)
    kw.setdefault("max_decode", 3)
    kw.setdefault("scale_up_after", 2)
    kw.setdefault("scale_down_after", 2)
    kw.setdefault("cooldown_ticks", 1)
    kw.setdefault("tick_interval_s", 0.0)

    def spawn(pool):
        router.spawned += 1
        return object(), f"new{router.spawned}"

    ctrl = PoolController(router, spawn=spawn, **kw)
    return router, ctrl


class TestHysteresis:
    def test_flapping_signal_never_acts(self):
        """THE no-oscillation pin: a noisy window flipping
        +1/0/+1/0/-1/0... moves nothing — every flap back to 0 resets
        both streaks."""
        router, ctrl = _stub_ctrl([1, 0, 1, 0, -1, 0, 1, 0, -1, 0])
        for _ in range(10):
            ctrl.tick()
        assert ctrl.stats()["actions_taken"] == 0
        assert router.spawned == 0 and router.drained == []

    def test_sustained_up_spawns_once_then_cooldown(self):
        router, ctrl = _stub_ctrl([1, 1, 1, 1, 1, 1],
                                  cooldown_ticks=3)
        acts = [ctrl.tick()["actions"] for _ in range(4)]
        # tick 1: streak 1 -> nothing; tick 2: spawn; ticks 3-4 are
        # inside the cooldown even though the hint stays +1
        assert [len(a) for a in acts] == [0, 1, 0, 0]
        assert router.spawned == 1
        assert ctrl.stats()["last_action"]["action"] == "spawn"

    def test_sustained_down_drains_and_reaps(self):
        router, ctrl = _stub_ctrl([0, 0, -1, -1])
        router.add_worker("d1", "decode")       # room to shrink
        for _ in range(4):
            ctrl.tick()
        assert router.drained == ["d0"] or router.drained == ["d1"]
        assert ctrl.stats()["drained_requests"] == 1
        assert ctrl.stats()["pool_size"]["decode"] == 1

    def test_bounds_respected(self):
        # at max: a sustained up-signal takes no action
        router, ctrl = _stub_ctrl([1] * 6, max_decode=1)
        for _ in range(6):
            ctrl.tick()
        assert router.spawned == 0
        # at min: a sustained down-signal takes no action
        router, ctrl = _stub_ctrl([-1] * 6)
        for _ in range(6):
            ctrl.tick()
        assert router.drained == []

    def test_chip_seconds_accrue(self):
        router, ctrl = _stub_ctrl([0] * 3)
        ctrl.tick()
        time.sleep(0.05)
        ctrl.tick()
        assert ctrl.stats()["chip_seconds"] > 0

    def test_bad_knobs_raise(self):
        with pytest.raises(ValueError, match="min pool"):
            PoolController(_StubRouter([]), spawn=lambda p: None,
                           min_decode=0)
        with pytest.raises(ValueError, match="below min"):
            PoolController(_StubRouter([]), spawn=lambda p: None,
                           min_decode=2, max_decode=1)

    def test_transient_spawn_failure_recorded_not_raised(self):
        """A spawn that times out (worker never READY) must not unwind
        the serving loop the controller rides on: the tick records a
        spawn_failed action, cooldown applies, and the next sustained
        streak retries."""
        router = _StubRouter([1] * 6)
        calls = []

        def spawn(pool):
            calls.append(pool)
            raise RuntimeError("worker failed to become ready")

        ctrl = PoolController(router, spawn=spawn, min_decode=1,
                              max_decode=3, scale_up_after=2,
                              cooldown_ticks=2, tick_interval_s=0.0)
        for _ in range(6):
            ctrl.tick()                  # must not raise
        st = ctrl.stats()
        fails = [a for a in st["actions"]
                 if a["action"] == "spawn_failed"]
        assert fails and "ready" in fails[0]["error"]
        assert len(calls) == 2           # retried after the cooldown

    def test_spawn_without_flags_or_hook_fails_loudly(self):
        router = _StubRouter([1, 1, 1])
        ctrl = PoolController(router, min_decode=1, max_decode=2,
                              scale_up_after=2, cooldown_ticks=0,
                              tick_interval_s=0.0)
        ctrl.tick()
        with pytest.raises(ValueError, match="worker_flags"):
            ctrl.tick()


# ---------------------------------------------------------------------------
# autoscale_signal edge cases the controller exercises
# ---------------------------------------------------------------------------


def _bare_router(**kw):
    from apex_tpu.serving.slo import resolve_slo_targets

    r = object.__new__(Router)
    r._prefill, r._decode = [], []
    r._slo_targets = resolve_slo_targets(None)
    r._caps = kw.get("queue_caps", {})
    r._priority = ("interactive", "standard", "default", "batch")
    r.wire_dtype = "raw"
    r._max_worker_queue = 4
    r._queues = {}
    r._next_rid = 0
    r._pf_rr = 0
    r._last_decode_pick = None
    r._requeued_total = 0
    r._completed_total = 0
    r._drain_completed = []
    return r


_SIG_N = [0]


class _SigWorker:
    def __init__(self, headroom=64, active=1, draining=False):
        _SIG_N[0] += 1
        self.addr = f"sig{_SIG_N[0]}"
        self.alive = True
        self.draining = draining
        self.in_flight = {}
        self.stats = {"headroom_tokens": headroom, "max_slots": 4,
                      "active": active, "block_size": 8}


class TestAutoscaleEdges:
    def test_empty_fleet_summary(self):
        """{} and None both degrade to live signals only."""
        r = _bare_router()
        r._decode = [_SigWorker()]
        r._prefill = [_SigWorker()]
        for fleet in (None, {}, {"sketches": {}}):
            sig = r.autoscale_signal(fleet)
            assert sig["decode"]["hint"] == 0
            assert "slo_violations" not in sig

    def test_single_class_traffic(self):
        """One class queued deep enough trips the backpressure grow
        signal; the per-class queue shape doesn't matter."""
        r = _bare_router()
        r._decode = [_SigWorker()]
        r._prefill = [_SigWorker()]
        for _ in range(5):
            r.submit([1, 2], slo_class="standard")
        sig = r.autoscale_signal()
        assert sig["decode"]["hint"] == 1
        assert sig["decode"]["router_queue"] == 5

    def test_all_pools_draining_reads_as_grow(self):
        """Every decode worker draining = an empty pool about to
        happen: hint must be +1 (and never -1 'idle headroom')."""
        r = _bare_router()
        r._decode = [_SigWorker(draining=True),
                     _SigWorker(draining=True)]
        r._prefill = [_SigWorker()]
        sig = r.autoscale_signal()
        assert sig["decode"]["hint"] == 1
        assert sig["decode"]["workers"] == 0
        assert sig["decode"]["draining"] == 2

    def test_headroom_counted_in_tokens(self):
        """An int8-style worker advertising more headroom_tokens keeps
        the fused signal from reading exhausted; a worker without the
        key falls back to blocks x block_size."""
        r = _bare_router()
        old = _SigWorker()
        del old.stats["headroom_tokens"]
        old.stats["free_block_headroom"] = 4      # 4 * 8 = 32 tokens
        r._decode = [old, _SigWorker(headroom=120)]
        r._prefill = [_SigWorker()]
        sig = r.autoscale_signal()
        assert sig["decode"]["headroom_tokens"] == 152

    def test_draining_worker_excluded_from_shrink_candidates(self):
        """A draining worker's idle occupancy must not count toward
        the shrink signal (it is already leaving)."""
        r = _bare_router()
        r._decode = [_SigWorker(active=2),
                     _SigWorker(active=0, draining=True)]
        r._prefill = [_SigWorker()]
        sig = r.autoscale_signal()
        # mean occupancy over NON-draining workers only: 2/4 = 0.5
        assert sig["decode"]["mean_occupancy"] == 0.5
        assert sig["decode"]["hint"] == 0


# ---------------------------------------------------------------------------
# drain migration over real sockets (the lossless scale-down pin)
# ---------------------------------------------------------------------------


def _pools(params, cfg, n_decode=2, **decode_kw):
    decode_kw.setdefault("max_len", 32)
    decode_kw.setdefault("cache_layout", "paged")
    decode_kw.setdefault("block_size", 4)
    decode_kw.setdefault("max_slots", 2)
    servers = [WorkerServer("prefill", params, cfg, max_len=32)]
    servers += [WorkerServer("decode", params, cfg, **decode_kw)
                for _ in range(n_decode)]
    for s in servers:
        _start(s)
    return servers


def _wait_until(pred, timeout=30.0, interval=0.002):
    """Deadline-poll a predicate instead of sleeping a fixed amount —
    the deflake contract for every timing-sensitive wait below."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _refusing(addr):
    """True once a worker's listener actually refuses connections —
    ``stop()`` only flags the serve loop, which closes its sockets up
    to one select-timeout later."""
    import socket

    host, port = addr.rsplit(":", 1)
    try:
        socket.create_connection((host, int(port)), timeout=0.2).close()
    except OSError:
        return True
    return False


class _MidflightGate:
    """Deterministic mid-flight pin for the drain tests.

    The old fixed-sleep/stat-scrape waits gambled that the drain RPC
    would land while the victim still held live lanes — on a fast
    machine one ``router.step()`` dispatches everything AND the victim
    finishes its whole 40-token decode inside that same call, so the
    window the sleeps bet on is already gone (``migrated == 0``, the
    historical flake).  The gate closes the race instead of re-tuning
    it: once an engine's step has ADMITTED work, later steps hold
    (return no completions, touch no state) until :meth:`release`, so
    the lanes provably stay mid-flight until the drain lands.
    """

    def __init__(self, *engines):
        self._open = threading.Event()
        self._orig = []
        for e in engines:
            orig = e.step

            def gated(e=e, orig=orig):
                if not self._open.is_set() and e._pool.n_active:
                    time.sleep(0.002)      # no hot-spin in the pump
                    return []
                return orig()

            self._orig.append((e, orig))
            e.step = gated

    def release(self):
        self._open.set()

    def restore(self):
        self._open.set()
        for e, orig in self._orig:
            e.step = orig


class TestDrainMigration:
    def test_mid_flight_drain_token_identical(self, model):
        """THE ACCEPTANCE PIN: drain a decode worker while it holds
        in-flight requests — every request completes on the survivor
        with tokens IDENTICAL to a never-drained single engine (raw
        wire), zero lost, migrations counted."""
        cfg, params = model
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, 128, (4 + i,)) for i in range(6)]
        # 40-token decodes keep lanes busy long enough for the drain
        # to land mid-flight (the point of the test)
        single = ServingEngine(params, cfg, max_slots=2, max_len=64,
                               cache_layout="paged", block_size=4)
        for p in prompts:
            single.submit(p, max_new_tokens=40)
        ref = {}
        while not single.idle:
            for r in single.step():
                ref[tuple(r.prompt.tolist())] = r.tokens.tolist()

        servers = _pools(params, cfg, max_len=64)
        victim = servers[1]
        gate = _MidflightGate(victim.engine)
        router = Router([servers[0].addr],
                        [servers[1].addr, servers[2].addr],
                        max_worker_queue=3)
        try:
            for p in prompts:
                router.submit(p, max_new_tokens=40)
            out = []
            victim_w = next(w for w in router._decode
                            if w.addr == victim.addr)
            assert _wait_until(
                lambda: (out.extend(router.step()),
                         victim_w.in_flight)[1],
                timeout=60, interval=0), "victim never got work"
            # wait until the victim's ENGINE holds a live lane — the
            # gate keeps it mid-flight from then on, so the drain
            # cannot race the request's completion
            assert _wait_until(
                lambda: victim.engine._pool.n_active >= 1, timeout=60)
            # fresh stats: _migrate picks the survivor by its LAST
            # snapshot, and the dispatch burst above left a stale
            # backlog estimate that would veto every candidate
            router.scrape_stats()
            drained = router.drain_worker(victim.addr)
            assert drained["migrated"] >= 1
            out.extend(router.take_drain_completions())
            router.remove_worker(victim.addr)
            gate.restore()
            out.extend(router.run(max_wall_s=120))
            got = {tuple(r.prompt.tolist()): r.tokens.tolist()
                   for r in out}
            assert got == ref              # zero lost, all exact
            assert any(r.migrations > 0 for r in out)
            assert all(r.pool == servers[2].addr for r in out
                       if r.migrations)
        finally:
            gate.restore()
            router.close(shutdown_workers=True)
            for s in servers:
                s.stop()

    def test_drain_requeues_engine_queued_requests(self, model):
        """Requests still QUEUED inside the drained worker's engine
        (admission-blocked) requeue at the router for a fresh dispatch
        — nothing migrates for them, nothing is lost."""
        cfg, params = model
        rng = np.random.RandomState(12)
        # 1-slot victim: dispatch two -> one live + one engine-queued
        servers = _pools(params, cfg, n_decode=2, max_slots=1,
                         max_len=64)
        victim = servers[1]
        gate = _MidflightGate(victim.engine)
        router = Router([servers[0].addr],
                        [servers[1].addr, servers[2].addr],
                        max_worker_queue=3)
        prompts = [rng.randint(0, 128, (5 + i,)) for i in range(4)]
        single = ServingEngine(params, cfg, max_slots=1, max_len=64,
                               cache_layout="paged", block_size=4)
        for p in prompts:
            single.submit(p, max_new_tokens=40)
        ref = {}
        while not single.idle:
            for r in single.step():
                ref[tuple(r.prompt.tolist())] = r.tokens.tolist()
        try:
            for p in prompts:
                router.submit(p, max_new_tokens=40)
            out = []
            victim_w = next(w for w in router._decode
                            if w.addr == victim.addr)
            assert _wait_until(
                lambda: (out.extend(router.step()),
                         len(victim_w.in_flight) >= 2)[1],
                timeout=60, interval=0), "victim never got 2 requests"
            assert _wait_until(
                lambda: victim.engine._pool.n_active >= 1, timeout=60)
            drained = router.drain_worker(victim.addr)
            out.extend(router.take_drain_completions())
            assert drained["requeued"] >= 1 or drained["migrated"] >= 1
            router.remove_worker(victim.addr)
            gate.restore()
            out.extend(router.run(max_wall_s=120))
            got = {tuple(r.prompt.tolist()): r.tokens.tolist()
                   for r in out}
            assert got == ref
        finally:
            gate.restore()
            router.close(shutdown_workers=True)
            for s in servers:
                s.stop()

    def test_double_migration_keeps_all_tokens(self, model):
        """A request drained TWICE (A→B, then B→C) must stitch all
        three legs — prior_tokens extends across migrations, never
        overwrites (the truncation regression)."""
        cfg, params = model
        rng = np.random.RandomState(17)
        prompt = rng.randint(0, 128, (6,))
        single = ServingEngine(params, cfg, max_slots=2, max_len=64,
                               cache_layout="paged", block_size=4)
        single.submit(prompt, max_new_tokens=50)
        ref = None
        while not single.idle:
            for r in single.step():
                ref = r.tokens.tolist()

        servers = _pools(params, cfg, n_decode=3, max_len=64)
        # every decode engine gated: the request must survive two
        # successive mid-flight drains, so each holder in turn has to
        # be pinned live until its drain lands
        gate = _MidflightGate(*(s.engine for s in servers[1:]))
        router = Router([servers[0].addr],
                        [s.addr for s in servers[1:]],
                        max_worker_queue=3)
        try:
            router.submit(prompt, max_new_tokens=50)
            out = []

            def holder():
                return next((w for w in router._decode
                             if w.in_flight), None)

            engines = {s.addr: s.engine for s in servers[1:]}
            for _ in range(2):               # two successive drains
                assert _wait_until(
                    lambda: (out.extend(router.step()),
                             holder() is not None)[1],
                    timeout=60, interval=0), "request never landed"
                w = holder()
                assert _wait_until(
                    lambda: engines[w.addr]._pool.n_active >= 1,
                    timeout=60)
                drained = router.drain_worker(w.addr)
                out.extend(router.take_drain_completions())
                assert drained["migrated"] == 1
                router.remove_worker(w.addr)
            gate.release()
            out.extend(router.run(max_wall_s=120))
            (resp,) = out
            assert resp.migrations == 2
            assert resp.tokens.tolist() == ref
        finally:
            gate.restore()
            router.close(shutdown_workers=True)
            for s in servers:
                s.stop()

    def test_drain_dead_worker_requeues_everything(self, model):
        """A worker that dies before/at the drain RPC degrades to the
        death path: everything requeues, nothing migrates, nothing is
        lost."""
        cfg, params = model
        rng = np.random.RandomState(13)
        servers = _pools(params, cfg)
        victim = servers[1]
        router = Router([servers[0].addr],
                        [servers[1].addr, servers[2].addr],
                        max_worker_queue=3)
        prompts = [rng.randint(0, 128, (4 + i,)) for i in range(4)]
        try:
            for p in prompts:
                router.submit(p, max_new_tokens=6)
            out = []
            deadline = time.time() + 60
            while time.time() < deadline:
                out.extend(router.step())
                victim_w = next(w for w in router._decode
                                if w.addr == victim.addr)
                if victim_w.in_flight:
                    break
            victim.stop()
            # poll-with-deadline, not a fixed sleep: stop() only flags
            # the serve loop — wait for the sockets to actually close
            # so the drain RPC deterministically hits the death path
            assert _wait_until(lambda: _refusing(victim.addr))
            drained = router.drain_worker(victim.addr)
            assert drained["migrated"] == 0
            assert drained["requeued"] >= 1
            router.remove_worker(victim.addr)
            out.extend(router.run(max_wall_s=120))
            assert len(out) == len(prompts)
        finally:
            router.close(shutdown_workers=True)
            for s in servers:
                s.stop()

    def test_externally_draining_worker_refusal_requeues(self, model):
        """A worker drain-flagged OUTSIDE this router (another router,
        an operator) refuses decode dispatch with 'draining'; the
        router must adopt the flag and requeue — never count the
        request failed."""
        cfg, params = model
        servers = _pools(params, cfg, n_decode=2)
        servers[1]._draining = True          # router does not know
        router = Router([servers[0].addr],
                        [servers[1].addr, servers[2].addr])
        try:
            rng = np.random.RandomState(21)
            for i in range(3):
                router.submit(rng.randint(0, 128, (4 + i,)),
                              max_new_tokens=4)
            out = router.run(max_wall_s=60)
            assert len(out) == 3
            assert all(r.pool == servers[2].addr for r in out)
            flagged = next(w for w in router._decode
                           if w.addr == servers[1].addr)
            assert flagged.draining          # flag adopted
        finally:
            router.close(shutdown_workers=True)
            for s in servers:
                s.stop()

    def test_add_worker_role_mismatch_refused(self, model):
        cfg, params = model
        servers = _pools(params, cfg, n_decode=1)
        router = Router([servers[0].addr], [servers[1].addr])
        try:
            with pytest.raises(ValueError, match="role"):
                router.add_worker(servers[0].addr, "decode")
            # a correct add becomes dispatchable
            extra = WorkerServer("decode", params, cfg, max_len=32,
                                 cache_layout="paged", block_size=4,
                                 max_slots=2)
            _start(extra)
            router.add_worker(extra.addr, "decode")
            assert len(router._decode) == 2
            router.remove_worker(extra.addr)
            assert len(router._decode) == 1
            extra.stop()
        finally:
            router.close(shutdown_workers=True)
            for s in servers:
                s.stop()


# ---------------------------------------------------------------------------
# the full loop over in-process workers
# ---------------------------------------------------------------------------


class TestControllerLoop:
    def test_scale_up_under_backpressure_then_drain_idle(self, model):
        """The closed loop end to end: a request flood trips the grow
        signal (spawn via the hook), outputs stay token-identical to a
        single engine, and the idle fleet drains back to min — with
        chip-seconds accrued throughout."""
        cfg, params = model
        made = []

        def mk_decode(_pool):
            s = WorkerServer("decode", params, cfg, max_len=32,
                             cache_layout="paged", block_size=4,
                             max_slots=2)
            _start(s)
            made.append(s)
            return s, s.addr

        pf = WorkerServer("prefill", params, cfg, max_len=32)
        _start(pf)
        d0, _ = mk_decode("decode")
        router = Router([pf.addr], [d0.addr], max_worker_queue=2)
        ctrl = PoolController(router, spawn=mk_decode,
                              min_decode=1, max_decode=2,
                              scale_up_after=2, scale_down_after=2,
                              cooldown_ticks=1, tick_interval_s=0.0)
        rng = np.random.RandomState(14)
        prompts = [rng.randint(0, 128, (4 + i,)) for i in range(8)]
        single = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               cache_layout="paged", block_size=4)
        for p in prompts:
            single.submit(p, max_new_tokens=6)
        ref = {}
        while not single.idle:
            for r in single.step():
                ref[tuple(r.prompt.tolist())] = r.tokens.tolist()
        try:
            for p in prompts:
                router.submit(p, max_new_tokens=6)
            out = router.run(max_wall_s=120, on_step=ctrl.maybe_tick)
            st = ctrl.stats()
            assert {tuple(r.prompt.tolist()): r.tokens.tolist()
                    for r in out} == ref
            assert any(a["action"] == "spawn" for a in st["actions"])
            # idle: sustained shrink drains back to min (a drain may
            # already have fired in the run's quiet tail)
            for _ in range(10):
                ctrl.tick()
            st = ctrl.stats()
            assert st["pool_size"]["decode"] == 1
            assert st["last_action"]["action"] == "drain"
            assert st["chip_seconds"] > 0
        finally:
            ctrl.close()
            router.close(shutdown_workers=True)
            pf.stop()
            for s in made:
                s.stop()

    def test_controller_telemetry_and_dash_row(self, model):
        """controller.* series land in the registry; serve_dash
        renders the controller row from a scrape carrying them and
        hides it otherwise."""
        import importlib.util
        import os

        cfg, params = model
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "serve_dash", os.path.join(repo, "tools",
                                       "serve_dash.py"))
        dash = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(dash)
        om = dash.load_openmetrics_module()

        reg = obs.configure(export_port=0)
        try:
            pf = WorkerServer("prefill", params, cfg, max_len=32)
            _start(pf)
            dc = WorkerServer("decode", params, cfg, max_len=32,
                              cache_layout="paged", block_size=4,
                              max_slots=2)
            _start(dc)
            router = Router([pf.addr], [dc.addr])
            ctrl = PoolController(router, spawn=lambda p: (None, ""),
                                  min_decode=1, max_decode=2,
                                  tick_interval_s=0.0)
            ctrl.tick()
            out = io.StringIO()
            snap = dash.one_frame(om, reg.exporter.url, out=out)
            text = out.getvalue()
            assert snap["controller_pools"] == {"decode": 1.0,
                                                "prefill": 1.0}
            assert "controller pools" in text
            assert "decode:1" in text and "prefill:1" in text
            router.close(shutdown_workers=True)
            pf.stop()
            dc.stop()
        finally:
            obs.shutdown()

    def test_dash_rows_hidden_without_series(self, model):
        """No controller, no chunked engine -> neither row renders."""
        import importlib.util
        import os

        cfg, params = model
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "serve_dash", os.path.join(repo, "tools",
                                       "serve_dash.py"))
        dash = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(dash)
        om = dash.load_openmetrics_module()
        reg = obs.configure(export_port=0)
        try:
            eng = ServingEngine(params, cfg, max_slots=1, max_len=32)
            eng.submit([1, 2, 3], max_new_tokens=2)
            while not eng.idle:
                eng.step()
            out = io.StringIO()
            snap = dash.one_frame(om, reg.exporter.url, out=out)
            text = out.getvalue()
            assert snap["controller_pools"] is None
            assert snap["prefill_chunks_total"] is None
            assert "controller pools" not in text
            assert "prefill progress" not in text
        finally:
            obs.shutdown()

    def test_dash_prefill_progress_row_renders(self, model):
        """A chunked engine mid-prefill exports the progress gauges
        and the dash renders the column."""
        import importlib.util
        import os

        cfg, params = model
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "serve_dash", os.path.join(repo, "tools",
                                       "serve_dash.py"))
        dash = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(dash)
        om = dash.load_openmetrics_module()
        reg = obs.configure(export_port=0)
        try:
            rng = np.random.RandomState(15)
            eng = ServingEngine(params, cfg, max_slots=2, max_len=64,
                                cache_layout="paged", block_size=8,
                                chunk_tokens=8)
            eng.submit(rng.randint(0, 128, (40,)), max_new_tokens=2)
            eng.step()                     # admit + first chunk only
            out = io.StringIO()
            snap = dash.one_frame(om, reg.exporter.url, out=out)
            text = out.getvalue()
            assert snap["prefilling"] == 1
            assert snap["prefill_chunks_total"] == 5
            assert snap["prefill_chunks_done"] >= 1
            assert "prefill progress" in text
            while not eng.idle:
                eng.step()
        finally:
            obs.shutdown()


# ---------------------------------------------------------------------------
# deferred-attach scale-up (ISSUE 17)
# ---------------------------------------------------------------------------


class _FakeProc:
    """Reap target for a pending spawn: the controller must call
    stop() on a handle it gives up on."""

    def __init__(self):
        self.stopped = False

    def stop(self):
        self.stopped = True


class _FakePending:
    """A spawn_worker_async handle with a scripted READY handshake:
    poll() returns None for ``ready_after - 1`` calls, then "ready"
    (or "dead" when ``die``)."""

    def __init__(self, addr, ready_after=2, die=False):
        self.role = "decode"
        self.proc = _FakeProc()
        self.addr = None
        self.metrics = None
        self.ready_ms = None
        self.error = None
        self.timeout_s = 120.0
        self._final_addr = addr
        self._ready_after = int(ready_after)
        self._die = die
        self.polls = 0
        self._t0 = time.perf_counter()

    @property
    def age_s(self):
        return time.perf_counter() - self._t0

    def poll(self):
        self.polls += 1
        if self.polls < self._ready_after:
            return None
        if self._die:
            self.error = "worker exploded before READY"
            return "dead"
        self.addr = self._final_addr
        self.ready_ms = 1234.5
        return "ready"


def _async_ctrl(hints, pendings, **kw):
    """_stub_ctrl's deferred twin: spawn_async= hands out scripted
    pending handles instead of blocking on a READY line."""
    router = _StubRouter(hints)
    kw.setdefault("min_decode", 1)
    kw.setdefault("max_decode", 3)
    kw.setdefault("scale_up_after", 2)
    kw.setdefault("scale_down_after", 2)
    kw.setdefault("cooldown_ticks", 1)
    kw.setdefault("tick_interval_s", 0.0)
    queue = list(pendings)
    launched = []

    def spawn_async(pool):
        pw = queue.pop(0)
        launched.append(pw)
        return pw

    ctrl = PoolController(router, spawn_async=spawn_async, **kw)
    return router, ctrl, launched


class TestDeferredAttach:
    def test_spawn_started_then_attach_with_ready_ms(self):
        """The tentpole pin: _scale_up returns IMMEDIATELY with a
        spawn_started record; the attach lands on a LATER tick, as its
        own action, carrying the worker-reported ready_ms."""
        pw = _FakePending("new1", ready_after=3)
        router, ctrl, _ = _async_ctrl([1] * 8, [pw])
        ctrl.tick()
        sig = ctrl.tick()                 # streak=2 -> spawn_started
        acts = [a["action"] for a in sig["actions"]]
        assert acts == ["spawn_started"]
        assert len(router._decode) == 1   # nothing attached yet
        # warming: polled once per tick until READY on the 3rd poll
        attach = None
        for _ in range(4):
            sig = ctrl.tick()
            got = [a for a in sig["actions"] if a["action"] == "attach"]
            if got:
                attach = got[0]
                break
        assert attach is not None and attach["addr"] == "new1"
        assert attach["ready_ms"] == 1234.5
        assert [w.addr for w in router._decode] == ["d0", "new1"]
        assert ctrl.stats()["pending_spawns"]["decode"] == 0

    def test_tick_never_blocks_on_spawn(self):
        """A pending handle that NEVER reports READY must not stall
        the loop: every tick completes and keeps polling."""
        pw = _FakePending("never", ready_after=10 ** 9)
        router, ctrl, _ = _async_ctrl([1] * 6, [pw])
        t0 = time.perf_counter()
        for _ in range(6):
            ctrl.tick()
        assert time.perf_counter() - t0 < 1.0
        assert pw.polls >= 4              # polled every tick post-spawn
        st = ctrl.stats()
        assert st["pending_spawns"]["decode"] == 1
        assert st["warming"] and st["warming"][0]["pool"] == "decode"
        assert st["warming"][0]["timeout_s"] == 120.0

    def test_pending_counts_toward_size_no_double_spawn(self):
        """The hint persisting through a slow warmup must not stack a
        second spawn: warming members count toward the pool bound."""
        slow = _FakePending("slow", ready_after=10 ** 9)
        spare = _FakePending("spare")
        router, ctrl, launched = _async_ctrl(
            [1] * 10, [slow, spare], max_decode=2)
        for _ in range(10):
            ctrl.tick()
        assert len(launched) == 1         # size 1 live + 1 pending = hi
        assert ctrl.stats()["pending_spawns"]["decode"] == 1

    def test_dead_before_ready_reaped_never_attached(self):
        pw = _FakePending("doa", ready_after=2, die=True)
        router, ctrl, _ = _async_ctrl([1] * 8, [pw])
        ctrl.tick()
        ctrl.tick()                       # spawn_started
        failed = None
        for _ in range(3):
            sig = ctrl.tick()
            got = [a for a in sig["actions"]
                   if a["action"] == "spawn_failed"]
            if got:
                failed = got[0]
                break
        assert failed is not None
        assert "exploded" in failed["error"]
        assert pw.proc.stopped            # reaped
        assert [w.addr for w in router._decode] == ["d0"]
        assert ctrl.stats()["pending_spawns"]["decode"] == 0

    def test_pending_burns_chip_seconds_from_launch(self):
        pw = _FakePending("warm", ready_after=10 ** 9)
        router, ctrl, _ = _async_ctrl([1] * 4, [pw])
        for _ in range(3):
            ctrl.tick()
            time.sleep(0.02)
        before = ctrl.stats()["chip_seconds"]
        time.sleep(0.02)
        ctrl.tick()
        after = ctrl.stats()["chip_seconds"]
        # 2 live workers + 1 pending: the pending one's chip counts,
        # so the per-tick increment covers 3 members, not 2
        assert after - before > 0.02 * 3 * 0.9

    def test_legacy_spawn_hook_stays_synchronous(self):
        """A spawn= hook (in-process test servers, no READY line to
        poll) must keep the blocking semantics: the worker is attached
        in the SAME tick, recorded as "spawn"."""
        router, ctrl = _stub_ctrl([1] * 2)
        ctrl.tick()
        sig = ctrl.tick()
        assert [a["action"] for a in sig["actions"]] == ["spawn"]
        assert len(router._decode) == 2

    def test_defer_spawn_false_restores_blocking_process_path(
            self, monkeypatch):
        """defer_spawn=False (the bench baseline) routes _scale_up
        through the blocking _spawn_process."""
        router = _StubRouter([1] * 2)
        calls = []
        ctrl = PoolController(
            router, defer_spawn=False,
            worker_flags={"decode": ["--flag"]},
            min_decode=1, max_decode=3, scale_up_after=2,
            scale_down_after=2, cooldown_ticks=1, tick_interval_s=0.0)
        monkeypatch.setattr(
            ctrl, "_spawn",
            lambda pool: calls.append(pool) or (_FakeProc(), "blk1"))
        ctrl.tick()
        sig = ctrl.tick()
        assert calls == ["decode"]
        assert [a["action"] for a in sig["actions"]] == ["spawn"]

    def test_spawn_and_spawn_async_together_rejected(self):
        router = _StubRouter([])
        with pytest.raises(ValueError, match="not both"):
            PoolController(router, spawn=lambda p: None,
                           spawn_async=lambda p: None)

    def test_close_reaps_pending(self):
        pw = _FakePending("warm", ready_after=10 ** 9)
        router, ctrl, _ = _async_ctrl([1] * 2, [pw])
        ctrl.tick()
        ctrl.tick()                       # spawn_started
        ctrl.close()
        assert pw.proc.stopped
        assert ctrl.stats()["pending_spawns"]["decode"] == 0

    def test_dash_warming_row_renders_then_hides(self):
        """ISSUE 17 satellite: a pending spawn exports the per-pool
        warming gauges and serve_dash renders the READY countdown
        row; after the attach the gauges zero and the row hides."""
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "serve_dash", os.path.join(repo, "tools",
                                       "serve_dash.py"))
        dash = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(dash)
        om = dash.load_openmetrics_module()
        reg = obs.configure(export_port=0)
        try:
            pw = _FakePending("new1", ready_after=4)
            router, ctrl, _ = _async_ctrl([1] * 8, [pw])
            ctrl.tick()
            ctrl.tick()                   # spawn_started -> warming
            time.sleep(0.05)
            ctrl.tick()                   # refresh the age gauge
            out = io.StringIO()
            snap = dash.one_frame(om, reg.exporter.url, out=out)
            text = out.getvalue()
            assert snap["controller_pending"] == 1
            w = snap["controller_warming"]["decode"]
            assert w["timeout_s"] == 120.0 and w["age_s"] > 0
            assert "warming decode" in text
            assert "READY deadline in" in text
            for _ in range(4):            # poll to READY + attach
                ctrl.tick()
            out = io.StringIO()
            snap = dash.one_frame(om, reg.exporter.url, out=out)
            assert snap["controller_pending"] == 0
            assert snap["controller_warming"] is None
            assert "warming decode" not in out.getvalue()
        finally:
            obs.shutdown()
