"""Determinism / race-condition analog tests.

Reference: tests/distributed/DDP/ddp_race_condition_test.py stresses the
grad-hook/bucket machinery for races.  Under jit there are no hooks or
streams to race, but the invariant it protects — two identical
distributed steps produce identical results — is still the thing to pin:
a regression here would mean a nondeterministic collective order or an
unintended RNG dependence.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.amp.frontend import make_train_step
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel.mesh import data_parallel_mesh

shard_map = jax.shard_map


def _problem(seed=0):
    rs = np.random.RandomState(seed)
    params = {"w1": jnp.asarray(rs.randn(16, 32) * 0.1, jnp.float32),
              "w2": jnp.asarray(rs.randn(32, 8) * 0.1, jnp.float32)}
    x = jnp.asarray(rs.randn(16, 16), jnp.float32)
    y = jnp.asarray(rs.randn(16, 8), jnp.float32)

    def loss_fn(p, x, y):
        h = jnp.tanh(x @ p["w1"].astype(x.dtype))
        return jnp.mean((h @ p["w2"].astype(x.dtype) - y) ** 2)

    return params, loss_fn, x, y


def test_ddp_step_bitwise_deterministic():
    """The same sharded AMP step on the same state must be bitwise
    reproducible across invocations AND across fresh compilations."""
    params, loss_fn, x, y = _problem()
    mesh = data_parallel_mesh()

    def build():
        init, step = make_train_step(
            loss_fn, fused_adam(lr=1e-2), "O2", axis_name="dp")

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(), P("dp"), P("dp")),
            out_specs=(P(), P()),
        )
        def sharded(state, xb, yb):
            new_state, metrics = step(state, xb, yb)
            # the local loss is per-shard; pmean it so the output is
            # provably replicated (the state already is: grads pmean'd)
            return (new_state.master_params,
                    jax.lax.pmean(metrics["loss"], "dp"))

        return init(params), sharded

    s1, f1 = build()
    s2, f2 = build()
    mp1, l1 = f1(s1, x, y)
    mp2, l2 = f2(s2, x, y)
    for a, b in zip(jax.tree_util.tree_leaves(mp1),
                    jax.tree_util.tree_leaves(mp2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(l1) == float(l2)

    # and re-running the SAME compiled fn on the same inputs
    mp3, _ = f1(s2, x, y)
    for a, b in zip(jax.tree_util.tree_leaves(mp1),
                    jax.tree_util.tree_leaves(mp3)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grads_identical_across_ranks():
    """Post-allreduce grads must be identical on every dp rank (the
    invariant the reference's master-params distributed test checks by
    comparing rank checkpoints, run_rocm_distributed.sh:10-14)."""
    params, loss_fn, x, y = _problem(seed=1)
    mesh = data_parallel_mesh()

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), P("dp"), P("dp")),
        out_specs=P("dp"))
    def per_rank_grads(p, xb, yb):
        g = jax.grad(loss_fn)(p, xb, yb)
        g = jax.tree_util.tree_map(
            lambda v: jax.lax.pmean(v, "dp"), g)
        # stack my copy so the caller sees every rank's value
        return jax.tree_util.tree_map(lambda v: v[None], g)

    stacked = per_rank_grads(params, x, y)
    for leaf in jax.tree_util.tree_leaves(stacked):
        arr = np.asarray(leaf)
        for r in range(1, arr.shape[0]):
            np.testing.assert_array_equal(arr[0], arr[r])
