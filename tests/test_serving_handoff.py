"""Cluster KV handoff: wire protocol, serialization, inject parity
(ISSUE 9).

The disaggregation contract everything else stands on: a prompt's KV
extracted from one cache, framed over the wire, and injected into
another MUST leave greedy decode token-identical (raw wire, both
layouts, fp32 and bf16 caches) — including across a ragged
mid-generation seam, where per-row lengths are not block-aligned.
"""

import socket

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import (
    decode_step, extract_kv, init_kv_cache, inject_kv, prefill)
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.serving import ServingEngine
from apex_tpu.serving.batching import (
    default_buckets, pad_prompt, pick_bucket)
from apex_tpu.serving.cluster import protocol
from apex_tpu.serving.cluster.handoff import (
    decode_kv, encode_kv, wire_bytes)


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


# ---------------------------------------------------------------------------
# the socket protocol
# ---------------------------------------------------------------------------


class TestProtocol:
    def test_roundtrip_header_and_blobs(self):
        a, b = socket.socketpair()
        try:
            blobs = [b"\x00" * 1000, b"xyz", b""]
            n = protocol.send_msg(a, {"op": "x", "v": [1, 2]}, blobs)
            header, got = protocol.recv_msg(b)
            assert header == {"op": "x", "v": [1, 2]}
            assert got == blobs
            assert n > 1003
        finally:
            a.close()
            b.close()

    def test_clean_close_is_none_midframe_raises(self):
        a, b = socket.socketpair()
        a.close()
        assert protocol.recv_msg(b) is None       # boundary EOF
        b.close()
        a, b = socket.socketpair()
        try:
            a.sendall(b"\x00\x00\x00\xff")        # declares 255 bytes
            a.sendall(b"{")                       # ...sends one
            a.close()
            with pytest.raises(protocol.ProtocolError,
                               match="mid-frame"):
                protocol.recv_msg(b)
        finally:
            b.close()

    def test_malformed_header_raises(self):
        for payload in (b"not json", b"[1, 2]"):
            a, b = socket.socketpair()
            try:
                import struct

                a.sendall(struct.pack("!I", len(payload)) + payload)
                with pytest.raises(protocol.ProtocolError):
                    protocol.recv_msg(b)
            finally:
                a.close()
                b.close()

    def test_stdlib_only_by_path(self):
        """protocol.py's dependency-free contract: it must load by
        file path in a process where jax and numpy are unimportable
        (the tools/ path-loading discipline)."""
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        path = os.path.join(repo, "apex_tpu", "serving", "cluster",
                            "protocol.py")
        code = (
            "import sys, importlib.util\n"
            "sys.modules['jax'] = None\n"
            "sys.modules['numpy'] = None\n"
            f"spec = importlib.util.spec_from_file_location("
            f"'_proto', {path!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "print('loaded', m.MAX_HEADER > 0)\n")
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=60)
        assert out.returncode == 0, out.stderr
        assert "loaded True" in out.stdout

    def test_oversized_declaration_refused(self):
        a, b = socket.socketpair()
        try:
            import json
            import struct

            hdr = json.dumps(
                {"op": "kv",
                 "_blobs": [protocol.MAX_MESSAGE]}).encode()
            a.sendall(struct.pack("!I", len(hdr)) + hdr)
            with pytest.raises(protocol.ProtocolError,
                               match="MAX_MESSAGE"):
                protocol.recv_msg(b)
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# wire serialization
# ---------------------------------------------------------------------------


class TestWireFormat:
    @pytest.mark.parametrize("cache_dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("wire", ["raw", "bf16", "int8"])
    def test_encode_decode_roundtrip(self, cache_dtype, wire):
        rng = np.random.RandomState(0)
        shape = (2, 7, 4, 16)
        k = jnp.asarray(rng.randn(*shape), jnp.dtype(cache_dtype))
        v = jnp.asarray(rng.randn(*shape), jnp.dtype(cache_dtype))
        header, blobs = encode_kv(k, v, wire_dtype=wire)
        k2, v2 = decode_kv(header, blobs)
        assert k2.shape == shape and jnp.dtype(k2.dtype) == k.dtype
        if wire == "raw" or (wire == "bf16"
                             and cache_dtype == "bfloat16"):
            # bit-exact forms: raw always; bf16 wire on a bf16 cache
            # is a no-op cast
            assert bytes(np.asarray(k).tobytes()) == bytes(k2.tobytes())
            assert bytes(np.asarray(v).tobytes()) == bytes(v2.tobytes())
        else:
            np.testing.assert_allclose(
                np.asarray(k, np.float32), np.asarray(k2, np.float32),
                rtol=0, atol=0.05)

    def test_wire_bytes_ordering(self):
        """The compression the wire formats exist for: int8 < bf16 <
        raw on an fp32 cache."""
        k = jnp.asarray(np.random.RandomState(1).randn(2, 8, 4, 16),
                        jnp.float32)
        sizes = {w: wire_bytes(encode_kv(k, k, wire_dtype=w)[1])
                 for w in ("raw", "bf16", "int8")}
        assert sizes["int8"] < sizes["bf16"] < sizes["raw"]
        assert sizes["bf16"] == sizes["raw"] // 2

    def test_torn_handoff_rejected(self):
        k = jnp.ones((2, 4, 4, 16), jnp.float32)
        header, blobs = encode_kv(k, k)
        with pytest.raises(ValueError, match="declares"):
            decode_kv(header, [blobs[0][:-8], blobs[1]])
        with pytest.raises(ValueError):
            decode_kv(dict(header, cache_dtype="int64"), blobs)
        with pytest.raises(ValueError):
            decode_kv(dict(header, shape=[2, 4]), blobs)
        with pytest.raises(ValueError):
            encode_kv(k, k, wire_dtype="fp8")


# ---------------------------------------------------------------------------
# extract / inject across layouts
# ---------------------------------------------------------------------------


class TestExtractInject:
    @pytest.mark.parametrize("layout", ["contiguous", "paged"])
    def test_roundtrip_identity(self, model, layout):
        cfg, params = model
        prompt = np.random.RandomState(2).randint(0, 128, (1, 9))
        cache = init_kv_cache(cfg, 1, 32, cache_layout=layout,
                              block_size=4)
        _, cache = prefill(params, jnp.asarray(prompt), cfg,
                           cache=cache)
        k, v = extract_kv(cache, 9)
        assert k.shape == (2, 9, 4, 16)
        dst = init_kv_cache(cfg, 1, 32, cache_layout=layout,
                            block_size=4)
        dst = inject_kv(dst, k, v)
        assert int(dst["pos"][0]) == 9
        k2, v2 = extract_kv(dst, 9)
        assert bool(jnp.all(k == k2)) and bool(jnp.all(v == v2))

    def test_errors(self, model):
        cfg, _ = model
        cache = init_kv_cache(cfg, 1, 16, cache_layout="paged",
                              block_size=4)
        with pytest.raises(ValueError, match="blocks"):
            extract_kv(cache, 99)
        with pytest.raises(ValueError, match="length"):
            extract_kv(cache, 0)
        with pytest.raises(ValueError, match="max_len"):
            inject_kv(init_kv_cache(cfg, 1, 8),
                      jnp.zeros((2, 9, 4, 16)), jnp.zeros((2, 9, 4, 16)))

    def test_unmapped_table_entries_refused(self, model):
        """A length that reaches UNMAPPED (sentinel) table entries must
        refuse, never clamp-gather another request's pool pages
        (extract) or silently drop writes while pos claims them
        (inject)."""
        cfg, _ = model
        cache = init_kv_cache(cfg, 1, 16, cache_layout="paged",
                              block_size=4)
        nb = cache["k"].shape[1]
        # engine-style table: only the first 2 blocks mapped
        tables = np.full((1, 4), nb, np.int32)
        tables[0, :2] = [0, 1]
        cache = dict(cache, block_tables=jnp.asarray(tables))
        k, v = extract_kv(cache, 8)              # mapped range: fine
        with pytest.raises(ValueError, match="unmapped"):
            extract_kv(cache, 9)                 # third block: sentinel
        with pytest.raises(ValueError, match="unmapped"):
            inject_kv(cache, jnp.zeros((2, 9, 4, 16)),
                      jnp.zeros((2, 9, 4, 16)))
        assert k.shape == (2, 8, 4, 16) and v.shape == (2, 8, 4, 16)


# ---------------------------------------------------------------------------
# engine injection parity: the acceptance pin
# ---------------------------------------------------------------------------


def _remote_prefill(params, cfg, prompt, max_len, cache_dtype,
                    scratch_layout="paged"):
    """What a prefill worker does, engine-bucket-identically: one
    bucket-shaped flash prefill + greedy first token + extraction."""
    buckets = tuple(sorted(default_buckets(max_len)))
    n = int(prompt.size)
    bucket = pick_bucket(n, buckets)
    padded = jnp.asarray(pad_prompt(prompt, bucket)[None])
    lens = jnp.asarray([n], jnp.int32)
    if scratch_layout == "paged":
        scratch = init_kv_cache(cfg, 1, bucket, cache_dtype=cache_dtype,
                                cache_layout="paged", block_size=4)
        logits, cache = prefill(params, padded, cfg, prompt_lens=lens,
                                cache=scratch)
    else:
        logits, cache = prefill(params, padded, cfg, prompt_lens=lens,
                                max_len=bucket, cache_dtype=cache_dtype)
    first = int(jnp.argmax(logits[0]))
    k, v = extract_kv(cache, n)
    return np.asarray(k), np.asarray(v), first


class TestEngineInjectionParity:
    @pytest.mark.parametrize("layout", ["contiguous", "paged"])
    @pytest.mark.parametrize("cache_dtype",
                             [jnp.float32, jnp.bfloat16])
    def test_raw_wire_token_identical(self, model, layout, cache_dtype):
        """extract → wire (raw) → inject, then decode: greedy outputs
        must equal a single engine that prefilled locally — on both
        layouts, fp32 AND bf16 caches."""
        cfg, params = model
        rng = np.random.RandomState(4)
        prompts = [rng.randint(0, 128, (n,)) for n in (5, 9)]
        kw = dict(max_slots=2, max_len=32, cache_layout=layout,
                  block_size=4, cache_dtype=cache_dtype)

        ref_eng = ServingEngine(params, cfg, **kw)
        for p in prompts:
            ref_eng.submit(p, max_new_tokens=5)
        ref = {}
        while not ref_eng.idle:
            for r in ref_eng.step():
                ref[tuple(r.prompt.tolist())] = r.tokens.tolist()

        eng = ServingEngine(params, cfg, **kw)
        for p in prompts:
            k, v, first = _remote_prefill(
                params, cfg, p, 32, jnp.dtype(cache_dtype),
                scratch_layout=("paged" if layout == "contiguous"
                                else "contiguous"))  # CROSS-layout
            hdr, blobs = encode_kv(k, v, wire_dtype="raw")
            k2, v2 = decode_kv(hdr, blobs)
            eng.submit_prefilled(p, k2, v2, first, max_new_tokens=5)
        out = {}
        while not eng.idle:
            for r in eng.step():
                out[tuple(r.prompt.tolist())] = r.tokens.tolist()
        assert out == ref

    def test_quantized_wire_decodes_but_may_diverge(self, model):
        """int8 wire: the engine accepts and decodes it (shapes,
        lifecycle); token parity is NOT claimed — that's the parity
        knob's documented trade."""
        cfg, params = model
        p = np.random.RandomState(5).randint(0, 128, (7,))
        k, v, first = _remote_prefill(params, cfg, p, 32, jnp.float32)
        hdr, blobs = encode_kv(k, v, wire_dtype="int8")
        k2, v2 = decode_kv(hdr, blobs)
        eng = ServingEngine(params, cfg, max_slots=1, max_len=32)
        eng.submit_prefilled(p, k2, v2, first, max_new_tokens=4)
        out = []
        while not eng.idle:
            out.extend(eng.step())
        assert len(out) == 1 and out[0].tokens.size == 4

    def test_shape_mismatch_refused(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, max_slots=1, max_len=32)
        bad = np.zeros((2, 5, 4, 8), np.float32)     # wrong dh
        with pytest.raises(ValueError, match="geometry"):
            eng.submit_prefilled(np.arange(5) + 1, bad, bad, 0,
                                 max_new_tokens=4)

    def test_preempted_injection_resumes_locally(self, model):
        """A preempted injected request drops its handoff and resumes
        through LOCAL prefill — still token-identical (raw wire), and
        the blocks ledger stays clean."""
        cfg, params = model
        rng = np.random.RandomState(6)
        # a pool sized to force preemption: 2 lanes want more blocks
        # than exist once decode grows
        kw = dict(max_slots=2, max_len=32, cache_layout="paged",
                  block_size=4, num_blocks=7, reserve_blocks=0)
        prompts = [rng.randint(0, 128, (8,)), rng.randint(0, 128, (8,))]

        ref_eng = ServingEngine(params, cfg, **kw)
        for p in prompts:
            ref_eng.submit(p, max_new_tokens=6)
        ref = {}
        while not ref_eng.idle:
            for r in ref_eng.step():
                ref[tuple(r.prompt.tolist())] = r.tokens.tolist()

        eng = ServingEngine(params, cfg, **kw)
        for p in prompts:
            k, v, first = _remote_prefill(params, cfg, p, 32,
                                          jnp.float32)
            eng.submit_prefilled(p, k, v, first, max_new_tokens=6)
        out = {}
        while not eng.idle:
            for r in eng.step():
                out[tuple(r.prompt.tolist())] = r.tokens.tolist()
        assert out == ref
        assert eng.stats()["blocks_in_use"] == 0
        assert eng.stats()["preemptions"] >= 1


# ---------------------------------------------------------------------------
# the ragged mid-generation seam
# ---------------------------------------------------------------------------


class TestMidGenerationSeam:
    @pytest.mark.parametrize("src,dst", [("paged", "contiguous"),
                                         ("contiguous", "paged")])
    def test_ragged_seam_cross_layout(self, model, src, dst):
        """Hand off MID-GENERATION, ragged, across layouts: rows at
        non-block-aligned lengths extract, cross the wire, inject into
        the OTHER layout, and continue bitwise-identically to never
        having moved."""
        cfg, params = model
        rng = np.random.RandomState(7)
        lens = jnp.asarray([5, 8], jnp.int32)
        prompt = jnp.asarray(rng.randint(0, 128, (2, 8)), jnp.int32)

        def greedy_steps(cache, tok, steps):
            toks = []
            for _ in range(steps):
                logits, cache = decode_step(params, tok, cache, cfg)
                tok = jnp.argmax(logits, -1).astype(jnp.int32)
                toks.append(np.asarray(tok).tolist())
            return cache, tok, toks

        cache = init_kv_cache(cfg, 2, 32, cache_layout=src,
                              block_size=4)
        logits, cache = prefill(params, prompt, cfg, prompt_lens=lens,
                                cache=cache)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        cache, tok, _ = greedy_steps(cache, tok, 3)
        # rows now at pos 8 and 11 — 11 % 4 != 0: the seam splits a
        # block mid-page
        assert [int(p) for p in cache["pos"]] == [8, 11]

        moved = init_kv_cache(cfg, 2, 32, cache_layout=dst,
                              block_size=4)
        for row in range(2):
            n = int(cache["pos"][row])
            k, v = extract_kv(cache, n, row=row)
            hdr, blobs = encode_kv(np.asarray(k), np.asarray(v))
            k2, v2 = decode_kv(hdr, blobs)
            moved = inject_kv(moved, k2, v2, row=row)

        _, _, cont = greedy_steps(moved, tok, 4)
        _, _, ref = greedy_steps(cache, tok, 4)
        assert cont == ref


# ---------------------------------------------------------------------------
# the stats admission signals (satellite)
# ---------------------------------------------------------------------------


class TestStatsSignals:
    def test_queued_by_class_and_headroom(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, max_slots=1, max_len=32,
                            cache_layout="paged", block_size=4,
                            reserve_blocks=2)
        st = eng.stats()
        assert st["queued_by_class"] == {}
        assert st["free_block_headroom"] == st["blocks_free"] - 2
        for cls in ("interactive", "interactive", "batch"):
            eng.submit([1, 2, 3], max_new_tokens=2, slo_class=cls)
        st = eng.stats()
        # flat keys unchanged for existing consumers
        assert st["queued"] == 3
        assert st["queued_by_class"] == {"interactive": 2, "batch": 1}
        while not eng.idle:
            eng.step()

    def test_contiguous_headroom_is_free_lanes(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, max_slots=3, max_len=32)
        assert eng.stats()["free_block_headroom"] == 3
