"""HF GPT-2 weight import: logits must match the torch forward.

This is the strongest single architecture cross-check in the suite: the
same weights through transformers' torch GPT-2 and through apex_tpu's
``gpt_forward`` must produce float-tolerance-equal logits.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from tools.import_hf import config_from_hf, params_from_hf  # noqa: E402


def _hf_model(n_layer=2, n_embd=64, n_head=4, vocab=100, n_pos=32):
    cfg = transformers.GPT2Config(
        n_layer=n_layer, n_embd=n_embd, n_head=n_head,
        vocab_size=vocab, n_positions=n_pos,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    return transformers.GPT2LMHeadModel(cfg).eval()


class TestImportHF:
    def test_logits_match_torch(self):
        hf = _hf_model()
        cfg = config_from_hf(hf.config, compute_dtype=jnp.float32)
        assert cfg.vocab_size == 128     # 100 padded to 128
        params = params_from_hf(hf.state_dict(), cfg)

        from apex_tpu.models.transformer_lm import gpt_forward

        rng = np.random.RandomState(0)
        tokens = rng.randint(0, 100, (2, 32))
        with torch.no_grad():
            want = hf(torch.asarray(tokens)).logits.numpy()
        got = np.asarray(
            jax.jit(lambda p, t: gpt_forward(p, t, cfg))(
                params, jnp.asarray(tokens, jnp.int32)))
        np.testing.assert_allclose(
            got[:, :, :100], want, atol=2e-4, rtol=2e-4)

    def test_unequal_heads_and_longer_model(self):
        hf = _hf_model(n_layer=3, n_embd=48, n_head=3, vocab=64, n_pos=16)
        cfg = config_from_hf(hf.config, compute_dtype=jnp.float32,
                             vocab_pad_multiple=64)
        params = params_from_hf(hf.state_dict(), cfg)

        from apex_tpu.models.transformer_lm import gpt_forward

        tokens = np.arange(16)[None] % 64
        with torch.no_grad():
            want = hf(torch.asarray(tokens)).logits.numpy()
        got = np.asarray(gpt_forward(
            params, jnp.asarray(tokens, jnp.int32), cfg))
        np.testing.assert_allclose(
            got[:, :, :64], want, atol=2e-4, rtol=2e-4)

    def test_vocab_too_small_raises(self):
        hf = _hf_model()
        cfg = config_from_hf(hf.config, compute_dtype=jnp.float32,
                             vocab_size=64)
        with pytest.raises(ValueError, match="smaller than"):
            params_from_hf(hf.state_dict(), cfg)
