"""DistributedFusedAdam (ZeRO-2) vs replicated FusedAdam.

Reference test pattern: apex/contrib/test/optimizers/test_dist_adam.py —
DistributedFusedAdam must track an (unsharded) Adam run step for step.
Here the oracle is our own make_train_step + fused_adam on one device.
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.amp.frontend import make_train_step
from apex_tpu.contrib.optimizers import (
    make_distributed_adam_train_step,
)
from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    _combine_bits,
    _split_bits,
)
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel.mesh import create_mesh


def make_problem(seed=0, d_in=40, d_h=24, d_out=8):
    rng = np.random.RandomState(seed)
    params = {
        "w1": jnp.asarray(rng.randn(d_in, d_h) * 0.1, jnp.float32),
        "b1": jnp.zeros((d_h,), jnp.float32),
        "w2": jnp.asarray(rng.randn(d_h, d_out) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rng.randn(16, d_in), jnp.float32)
    y = jnp.asarray(rng.randn(16, d_out), jnp.float32)

    def loss_fn(p, x, y):
        h = jnp.tanh(x @ p["w1"].astype(x.dtype) + p["b1"].astype(x.dtype))
        return jnp.mean((h @ p["w2"].astype(x.dtype) - y) ** 2)

    return params, loss_fn, x, y


class TestBitPacking:
    def test_split_combine_roundtrip(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(4096) * np.exp(
            rng.uniform(-20, 20, 4096)), jnp.float32)
        bf, rem = _split_bits(x)
        back = _combine_bits(bf, rem)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


class TestZero2:
    def test_matches_replicated_fused_adam(self):
        params, loss_fn, x, y = make_problem()
        mesh = create_mesh()    # dp=8

        # oracle: replicated O0 fp32 fused adam
        init_ref, step_ref = make_train_step(
            loss_fn, fused_adam(lr=1e-2), "O0")
        sref = init_ref(params)

        init_z, step_z = make_distributed_adam_train_step(
            loss_fn, mesh, lr=1e-2, amp="O0")
        sz = init_z(params)

        for i in range(5):
            sref, mref = step_ref(sref, x, y)
            sz, mz = step_z(sz, x, y)
            np.testing.assert_allclose(
                float(mz["loss"]), float(mref["loss"]), rtol=1e-5)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(sz.params[k]), np.asarray(sref.params[k]),
                atol=1e-5, err_msg=k)
        assert int(sz.step) == 5

    def test_store_param_remainders_tracks_fp32_master(self):
        params, loss_fn, x, y = make_problem(seed=2)
        mesh = create_mesh()
        init_a, step_a = make_distributed_adam_train_step(
            loss_fn, mesh, lr=1e-2, amp="O5")
        init_b, step_b = make_distributed_adam_train_step(
            loss_fn, mesh, lr=1e-2, amp="O5",
            store_param_remainders=True)
        sa, sb = init_a(params), init_b(params)
        for _ in range(4):
            sa, ma_m = step_a(sa, x, y)
            sb, mb_m = step_b(sb, x, y)
        # packing invariant: the bf16 params ARE the high 16 bits of the
        # reconstructed fp32 master, exactly
        mb = _combine_bits(_flat_bf(sb), sb.master_shard)
        bits = np.asarray(jax.lax.bitcast_convert_type(mb, jnp.uint32))
        hi = np.asarray(jax.lax.bitcast_convert_type(
            _flat_bf(sb), jnp.uint16)).astype(np.uint32) << 16
        np.testing.assert_array_equal(bits >> 16, hi >> 16)
        # and the trajectory coarsely tracks the fp32-master mode
        # (truncated vs rounded compute params diverge chaotically, so
        # this is a sanity band, not a parity check)
        np.testing.assert_allclose(
            np.asarray(mb), np.asarray(sa.master_shard), atol=5e-2)
        assert np.isfinite(float(mb_m["loss"]))
        assert np.all(np.isfinite(np.asarray(mb)))

    def test_overflow_skip(self):
        params, loss_fn, x, y = make_problem(seed=3)
        mesh = create_mesh()
        init_z, step_z = make_distributed_adam_train_step(
            loss_fn, mesh, lr=1e-2, amp="O5", loss_scale="dynamic")
        sz = init_z(params)
        sz, _ = step_z(sz, x, y)
        master_before = np.asarray(sz.master_shard)
        scale_before = float(sz.loss_scale_state.loss_scale)
        bad = x.at[0, 0].set(jnp.inf)
        sz, m = step_z(sz, bad, y)
        assert bool(m["overflow"])
        np.testing.assert_array_equal(np.asarray(sz.master_shard),
                                      master_before)
        assert float(sz.loss_scale_state.loss_scale) == scale_before / 2
        assert int(sz.step) == 1

    def test_non_float_leaves_preserved(self):
        params, loss_fn, x, y = make_problem(seed=5)
        params["lookup"] = jnp.arange(10, dtype=jnp.int32)  # int table
        mesh = create_mesh()

        def loss_with_table(p, x, y):
            # the int leaf participates (as gather indices) but must not
            # be Adam-updated or cast
            return loss_fn(p, x, y) + 0.0 * jnp.sum(
                p["w1"][p["lookup"] % p["w1"].shape[0], 0])

        init_z, step_z = make_distributed_adam_train_step(
            loss_with_table, mesh, lr=1e-2, amp="O5")
        sz = init_z(params)
        assert sz.params["lookup"].dtype == jnp.int32
        sz, _ = step_z(sz, x, y)
        assert sz.params["lookup"].dtype == jnp.int32
        np.testing.assert_array_equal(np.asarray(sz.params["lookup"]),
                                      np.arange(10))

    def test_grad_clip(self):
        params, loss_fn, x, y = make_problem(seed=4)
        mesh = create_mesh()
        # huge clip threshold == no-op: must match the unclipped run
        init_a, step_a = make_distributed_adam_train_step(
            loss_fn, mesh, lr=1e-2, amp="O0")
        init_b, step_b = make_distributed_adam_train_step(
            loss_fn, mesh, lr=1e-2, amp="O0", grad_clip_norm=1e9)
        sa, sb = init_a(params), init_b(params)
        sa, _ = step_a(sa, x, y)
        sb, _ = step_b(sb, x, y)
        np.testing.assert_allclose(np.asarray(sb.master_shard),
                                   np.asarray(sa.master_shard), atol=1e-7)
        # tiny clip threshold must change the trajectory
        init_c, step_c = make_distributed_adam_train_step(
            loss_fn, mesh, lr=1e-2, amp="O0", grad_clip_norm=1e-3)
        sc = init_c(params)
        sc, _ = step_c(sc, x, y)
        assert float(np.max(np.abs(
            np.asarray(sc.v_shard) - np.asarray(sa.v_shard)))) > 0


def _flat_bf(state):
    from jax.flatten_util import ravel_pytree
    flat, _ = ravel_pytree(state.params)
    pad = state.m_shard.shape[0] - flat.shape[0]
    return jnp.pad(flat, (0, pad))
