"""Hardware (non-interpret) Pallas kernel tests — `pytest -m tpu`.

The CPU suite runs every kernel in interpret mode; real-TPU tiling bugs
(e.g. the round-1 softmax lane bug fixed in f3e44b8) only surface when
Mosaic compiles the kernel.  These tests re-run the core kernel parity
checks non-interpret; they self-skip unless a TPU is attached:

    APEX_TPU_TEST_ON_TPU=1 PYTHONPATH=/root/repo:/root/.axon_site \
        python -m pytest tests/test_on_tpu_kernels.py -m tpu -q

(the env var tells tests/conftest.py to keep the real chip instead of
forcing the CPU mesh; verified green on v5e, round 2.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

on_real_tpu = any(d.platform == "tpu" for d in jax.devices())

pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(not on_real_tpu, reason="needs a real TPU chip"),
]


def test_flash_attention_parity_on_chip():
    from apex_tpu.ops.flash_attention import flash_attention, mha_reference

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(2, 256, 4, 64), jnp.float32) * 0.5
    k = jnp.asarray(rs.randn(2, 256, 4, 64), jnp.float32) * 0.5
    v = jnp.asarray(rs.randn(2, 256, 4, 64), jnp.float32) * 0.5
    got = flash_attention(q, k, v, causal=True)
    want = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=5e-3, rtol=5e-3)


def test_flash_dropout_statistics_on_chip():
    from apex_tpu.ops.flash_attention import flash_attention

    rs = np.random.RandomState(1)
    b, s, n, d = 1, 256, 2, 128
    q = jnp.asarray(rs.randn(b, s, n, d), jnp.float32) * 0.5
    k = jnp.asarray(rs.randn(b, s, n, d), jnp.float32) * 0.5
    v = jnp.asarray(np.tile(np.eye(s)[None, :, None, :d], (b, 1, n, 1)),
                    jnp.float32)
    out = flash_attention(q, k, v, dropout_p=0.4,
                          dropout_rng=jax.random.PRNGKey(3))
    dense = flash_attention(q, k, v)
    ratio = np.asarray(out, np.float64) / np.maximum(
        np.asarray(dense, np.float64), 1e-30)
    zero_frac = 1.0 - (ratio > 0.5).mean()
    assert abs(zero_frac - 0.4) < 0.02


def test_layer_norm_kernel_on_chip():
    from apex_tpu.ops.layer_norm import fused_layer_norm

    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(64, 1024), jnp.float32)
    w = jnp.asarray(1 + 0.1 * rs.randn(1024), jnp.float32)
    b = jnp.asarray(0.1 * rs.randn(1024), jnp.float32)
    got = fused_layer_norm(x, w, b)
    mu = np.asarray(x).mean(-1, keepdims=True)
    var = np.asarray(x).var(-1, keepdims=True)
    want = (np.asarray(x) - mu) / np.sqrt(var + 1e-5)
    want = want * np.asarray(w) + np.asarray(b)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)


def test_softmax_kernels_on_chip():
    from apex_tpu.ops.softmax import (
        scaled_softmax, scaled_upper_triang_masked_softmax)

    rs = np.random.RandomState(3)
    s = jnp.asarray(rs.randn(2, 4, 256, 256), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(scaled_softmax(s, 0.5)),
        np.asarray(jax.nn.softmax(np.asarray(s) * 0.5, axis=-1)),
        atol=2e-5, rtol=2e-5)
    got = np.asarray(scaled_upper_triang_masked_softmax(s, 0.5))
    mask = np.triu(np.ones((256, 256), bool), 1)
    ref = np.where(mask[None, None], -1e30, np.asarray(s) * 0.5)
    ref = np.asarray(jax.nn.softmax(ref, axis=-1))
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)


# (test_flat_adam_kernel_on_chip was deleted in round 5 along with the
# Pallas flat Adam kernel it Mosaic-validated: the round-5 win-or-delete
# sweep measured it 1.82x the XLA fused update at its best block size.
# The XLA flat update that replaced it has no Mosaic surface; its
# numerics are covered by tests/test_optimizers.py.)


def test_xentropy_kernel_on_chip():
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

    rs = np.random.RandomState(5)
    logits = jnp.asarray(rs.randn(64, 512), jnp.float32)
    labels = jnp.asarray(rs.randint(0, 512, (64,)), jnp.int32)
    got = softmax_cross_entropy_loss(logits, labels)
    lse = np.log(np.exp(np.asarray(logits)).sum(-1))
    want = lse - np.asarray(logits)[np.arange(64), np.asarray(labels)]
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


def test_rope_kernel_on_chip():
    from apex_tpu.ops.rope import fused_apply_rotary_pos_emb

    rs = np.random.RandomState(6)
    s, d = 128, 64
    t = jnp.asarray(rs.randn(s, 2, 4, d), jnp.float32)  # [s,b,n,d]
    inv = 1.0 / (10000 ** (np.arange(0, d, 2) / d))
    pos = np.arange(s)[:, None] * inv[None, :]
    freqs = jnp.asarray(
        np.concatenate([pos, pos], -1)[:, None, None, :], jnp.float32)
    got = np.asarray(fused_apply_rotary_pos_emb(t, freqs))
    cos = np.cos(np.concatenate([pos, pos], -1))[:, None, None, :]
    sin = np.sin(np.concatenate([pos, pos], -1))[:, None, None, :]
    x = np.asarray(t)
    rot = np.concatenate([-x[..., d // 2:], x[..., : d // 2]], -1)
    want = x * cos + rot * sin
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_swiglu_kernel_on_chip():
    from apex_tpu.ops.swiglu import fused_bias_swiglu

    rs = np.random.RandomState(7)
    y = jnp.asarray(rs.randn(64, 2 * 256), jnp.float32)
    b = jnp.asarray(rs.randn(2 * 256), jnp.float32)
    got = np.asarray(fused_bias_swiglu(y, b))
    yb = np.asarray(y) + np.asarray(b)
    gate, up = yb[:, :256], yb[:, 256:]
    silu = gate / (1.0 + np.exp(-gate))
    np.testing.assert_allclose(got, silu * up, atol=2e-5, rtol=2e-5)


def test_packed_segment_attention_on_chip():
    """Round-3 varlen kernel: packed rows vs per-sequence oracle with the
    block-sparse skip active on real hardware."""
    from apex_tpu.ops.flash_attention import (
        flash_attention_packed, mha_reference)

    rs = np.random.RandomState(3)
    lengths = [100, 156, 120]
    total = sum(lengths) + 8          # pad tail
    q = jnp.asarray(rs.randn(total, 4, 64), jnp.float32) * 0.5
    k = jnp.asarray(rs.randn(total, 4, 64), jnp.float32) * 0.5
    v = jnp.asarray(rs.randn(total, 4, 64), jnp.float32) * 0.5
    cu = jnp.asarray(np.cumsum([0] + lengths), jnp.int32)
    out = jax.jit(lambda q, k, v: flash_attention_packed(
        q, k, v, cu, causal=True))(q, k, v)
    start = 0
    for L in lengths:
        want = mha_reference(
            q[None, start:start + L], k[None, start:start + L],
            v[None, start:start + L], causal=True)[0]
        np.testing.assert_allclose(
            np.asarray(out[start:start + L]), np.asarray(want),
            atol=5e-3, rtol=5e-3)
        start += L
    # pad queries produce exact zeros (l==0 sentinel)
    np.testing.assert_array_equal(
        np.asarray(out[sum(lengths):]), 0.0)


def test_flash_retuned_blocks_on_chip():
    """s1024 path uses the 1024x1024 tiles (round-3 retune) — verify the
    numerics at the exact block-crossover shapes, fwd and bwd."""
    from apex_tpu.ops.flash_attention import flash_attention, mha_reference

    rs = np.random.RandomState(4)
    for s in (1024, 1536):           # >=1024 triggers the big tiles
        q = jnp.asarray(rs.randn(1, s, 2, 64), jnp.float32) * 0.5
        k = jnp.asarray(rs.randn(1, s, 2, 64), jnp.float32) * 0.5
        v = jnp.asarray(rs.randn(1, s, 2, 64), jnp.float32) * 0.5
        f = jax.jit(jax.grad(lambda q, k, v: flash_attention(
            q, k, v, causal=True).astype(jnp.float32).sum(),
            argnums=(0, 1, 2)))
        r = jax.jit(jax.grad(lambda q, k, v: mha_reference(
            q, k, v, causal=True).astype(jnp.float32).sum(),
            argnums=(0, 1, 2)))
        for a, b in zip(f(q, k, v), r(q, k, v)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-2, rtol=1e-2)


def test_lm_head_ce_on_chip():
    """Chunked fused head+CE vs the two-stage composition on hardware."""
    from apex_tpu.ops.lm_head_ce import lm_head_cross_entropy
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

    rs = np.random.RandomState(5)
    n, h, v = 512, 128, 1024
    hidden = jnp.asarray(rs.randn(n, h) * 0.5, jnp.bfloat16)
    head = jnp.asarray(rs.randn(v, h) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(rs.randint(0, v, (n,)), jnp.int32)

    def fused(hd, he):
        return lm_head_cross_entropy(hd, he, labels, chunk=128).mean()

    def ref(hd, he):
        logits = jnp.einsum("nh,vh->nv", hd, he,
                            preferred_element_type=jnp.float32)
        return softmax_cross_entropy_loss(logits, labels, 0.0, None).mean()

    lf, gf = jax.jit(jax.value_and_grad(fused, argnums=(0, 1)))(hidden, head)
    lr, gr = jax.jit(jax.value_and_grad(ref, argnums=(0, 1)))(hidden, head)
    np.testing.assert_allclose(float(lf), float(lr), rtol=2e-2)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=2e-2, rtol=2e-2)


def test_fused_flash_backward_on_chip(monkeypatch):
    """Round-4 fused single-pass backward vs the split kernels and the
    XLA reference, compiled by Mosaic (non-interpret) at the BERT-class
    short-key shape."""
    from apex_tpu.ops.flash_attention import flash_attention, mha_reference

    rs = np.random.RandomState(4)
    q = jnp.asarray(rs.randn(2, 512, 4, 64), jnp.float32) * 0.5
    k = jnp.asarray(rs.randn(2, 512, 4, 64), jnp.float32) * 0.5
    v = jnp.asarray(rs.randn(2, 512, 4, 64), jnp.float32) * 0.5
    kpm = jnp.asarray(np.arange(512)[None, :] >= np.array(
        [384, 512])[:, None])

    def grads(causal):
        return jax.grad(lambda *a: jnp.sum(flash_attention(
            *a, causal=causal, key_padding_mask=kpm)),
            argnums=(0, 1, 2))(q, k, v)

    for causal in (True, False):
        monkeypatch.setenv("APEX_TPU_FLASH_BWD", "fused")
        g_fused = grads(causal)
        monkeypatch.setenv("APEX_TPU_FLASH_BWD", "split")
        g_split = grads(causal)
        monkeypatch.delenv("APEX_TPU_FLASH_BWD")
        g_ref = jax.grad(lambda *a: jnp.sum(mha_reference(
            *a, causal=causal, key_padding_mask=kpm)),
            argnums=(0, 1, 2))(q, k, v)
        for gf, gs, gr, nm in zip(g_fused, g_split, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), atol=5e-3, rtol=5e-3,
                err_msg=f"fused d{nm} causal={causal}")
            np.testing.assert_allclose(
                np.asarray(gs), np.asarray(gr), atol=5e-3, rtol=5e-3,
                err_msg=f"split d{nm} causal={causal}")


def test_ln_backward_split_partials_on_chip(monkeypatch):
    """Round-4 per-block-partials LN backward under Mosaic at a
    multi-block shape."""
    from apex_tpu.ops.layer_norm import fused_layer_norm, layer_norm_ref

    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(4096, 768), jnp.bfloat16)
    w = jnp.asarray(1.0 + 0.1 * rs.randn(768), jnp.float32)
    b = jnp.asarray(0.1 * rs.randn(768), jnp.float32)

    def f(x_, w_, b_):
        return jnp.sum(fused_layer_norm(x_, w_, b_).astype(jnp.float32))

    g_ref = jax.grad(
        lambda x_, w_, b_: jnp.sum(
            layer_norm_ref(x_, w_, b_).astype(jnp.float32)),
        argnums=(0, 1, 2))(x, w, b)
    # per-gradient tolerances: dx elements are ~0.1 (a blanket atol=0.5
    # would pass an all-zero dx); dw/db are ~row-count sums where rtol
    # dominates and bf16 accumulation needs the absolute slack
    tols = {"dx": dict(atol=1e-2, rtol=2e-2),
            "dw": dict(atol=0.5, rtol=2e-2),
            "db": dict(atol=0.5, rtol=2e-2)}
    for mode in ("pallas",):
        monkeypatch.setenv("APEX_TPU_LN_BWD", mode)
        g = jax.grad(f, argnums=(0, 1, 2))(x, w, b)
        monkeypatch.delenv("APEX_TPU_LN_BWD")
        for a, r, nm in zip(g, g_ref, ("dx", "dw", "db")):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(r, np.float32),
                err_msg=f"{mode} {nm}", **tols[nm])


def test_grouped_kv_flash_on_chip(monkeypatch):
    """GQA-aware flash under Mosaic: the grouped index maps (fwd + dq),
    the 4-D dkv accumulation grid, AND the fused kernel's cross-row
    group accumulation only ever ran in interpret mode until a chip is
    attached — tiling/layout bugs surface here."""
    from apex_tpu.ops.flash_attention import flash_attention, mha_reference

    rs = np.random.RandomState(9)
    q = jnp.asarray(rs.randn(2, 256, 8, 64), jnp.float32) * 0.5
    k = jnp.asarray(rs.randn(2, 256, 2, 64), jnp.float32) * 0.5
    v = jnp.asarray(rs.randn(2, 256, 2, 64), jnp.float32) * 0.5
    got = flash_attention(q, k, v, causal=True)
    want = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=5e-3, rtol=5e-3)

    g2 = jax.grad(lambda *a: jnp.sum(mha_reference(*a, causal=True)),
                  argnums=(0, 1, 2))(q, k, v)
    for mode in ("split", "fused"):
        monkeypatch.setenv("APEX_TPU_FLASH_BWD", mode)
        g1 = jax.grad(lambda *a: jnp.sum(flash_attention(
            *a, causal=True)), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            assert a.shape == b.shape, name
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-3, rtol=5e-3,
                err_msg=f"grouped {mode} d{name} on chip")


def test_ring_attention_on_chip():
    """Ring attention's Pallas chunk kernels under Mosaic: single-chip
    mesh (ring of 1 falls back to plain flash; with >1 local devices the
    real ring path runs)."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.ops.flash_attention import mha_reference
    from apex_tpu.parallel.mesh import create_mesh
    from apex_tpu.parallel.ring_attention import ring_attention

    ndev = len(jax.devices())
    sp = min(ndev, 4)
    mesh = create_mesh(sp=sp)
    rs = np.random.RandomState(6)
    q = jnp.asarray(rs.randn(1, 512, 2, 64), jnp.float32) * 0.5
    k = jnp.asarray(rs.randn(1, 512, 2, 64), jnp.float32) * 0.5
    v = jnp.asarray(rs.randn(1, 512, 2, 64), jnp.float32) * 0.5

    import functools
    f = jax.jit(jax.shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp")))
    got = f(q, k, v)
    want = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=5e-3, rtol=5e-3)
