"""BERT family tests (reference run_bert_minimal_test.py pattern at toy
scale: forward shapes, loss behavior, masking semantics, LAMB training)."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.bert import (
    bert_forward,
    bert_pretrain_loss,
    init_bert_params,
    make_bert_train_step,
)
from apex_tpu.models.config import TransformerConfig
from apex_tpu.optimizers import fused_lamb


def tiny_cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 32)
    kw.setdefault("attn_mask_type", "padding")
    kw.setdefault("compute_dtype", jnp.float32)
    return TransformerConfig(**kw)


def batch(cfg, b=4, s=16, seed=0, mask_frac=0.15):
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(rng.randint(3, cfg.vocab_size, (b, s)), jnp.int32)
    mlm = np.full((b, s), -1)
    pos = rng.rand(b, s) < mask_frac
    mlm[pos] = rng.randint(3, cfg.vocab_size, pos.sum())
    nsp = jnp.asarray(rng.randint(0, 2, (b,)), jnp.int32)
    tt = jnp.asarray((np.arange(s)[None] >= s // 2).astype(np.int32)
                     .repeat(b, 0))
    am = jnp.ones((b, s), jnp.int32)
    return tokens, jnp.asarray(mlm), nsp, tt, am


class TestForward:
    def test_shapes(self):
        cfg = tiny_cfg()
        params = init_bert_params(jax.random.PRNGKey(0), cfg)
        tokens, mlm, nsp, tt, am = batch(cfg)
        lm_logits, bin_logits = bert_forward(
            params, tokens, cfg, tokentype_ids=tt, attention_mask=am)
        assert lm_logits.shape == (4, 16, cfg.vocab_size)
        assert bin_logits.shape == (4, 2)

    def test_bidirectional_not_causal(self):
        cfg = tiny_cfg()
        params = init_bert_params(jax.random.PRNGKey(1), cfg)
        tokens, _, _, tt, am = batch(cfg)
        lm1, _ = bert_forward(params, tokens, cfg, tokentype_ids=tt,
                              attention_mask=am)
        # changing the LAST token must affect EARLIER positions
        tokens2 = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab_size)
        lm2, _ = bert_forward(params, tokens2, cfg, tokentype_ids=tt,
                              attention_mask=am)
        assert float(jnp.max(jnp.abs(lm1[:, 0] - lm2[:, 0]))) > 1e-6

    def test_padding_tokens_isolated(self):
        cfg = tiny_cfg()
        params = init_bert_params(jax.random.PRNGKey(2), cfg)
        tokens, _, _, tt, _ = batch(cfg)
        am = jnp.ones(tokens.shape, jnp.int32).at[:, -4:].set(0)
        lm1, _ = bert_forward(params, tokens, cfg, tokentype_ids=tt,
                              attention_mask=am)
        tokens2 = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab_size)
        lm2, _ = bert_forward(params, tokens2, cfg, tokentype_ids=tt,
                              attention_mask=am)
        np.testing.assert_allclose(np.asarray(lm1[:, :-4]),
                                   np.asarray(lm2[:, :-4]), atol=1e-5)

    def test_tokentype_changes_output(self):
        cfg = tiny_cfg()
        params = init_bert_params(jax.random.PRNGKey(3), cfg)
        tokens, _, _, tt, am = batch(cfg)
        lm1, _ = bert_forward(params, tokens, cfg, tokentype_ids=tt,
                              attention_mask=am)
        lm2, _ = bert_forward(params, tokens, cfg,
                              tokentype_ids=1 - tt, attention_mask=am)
        assert float(jnp.max(jnp.abs(lm1 - lm2))) > 1e-6


class TestLoss:
    def test_ignored_labels_do_not_contribute(self):
        cfg = tiny_cfg()
        params = init_bert_params(jax.random.PRNGKey(4), cfg)
        tokens, mlm, nsp, tt, am = batch(cfg)
        l1 = bert_pretrain_loss(params, tokens, mlm, nsp, cfg,
                                tokentype_ids=tt, attention_mask=am)
        # change labels only at ignored (-1) positions → loss unchanged
        mlm2 = jnp.where(mlm < 0, -7, mlm)
        l2 = bert_pretrain_loss(params, tokens, mlm2, nsp, cfg,
                                tokentype_ids=tt, attention_mask=am)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)

    def test_random_init_loss_near_log_vocab(self):
        cfg = tiny_cfg()
        params = init_bert_params(jax.random.PRNGKey(5), cfg)
        tokens, mlm, nsp, tt, am = batch(cfg)
        loss = bert_pretrain_loss(params, tokens, mlm, nsp, cfg,
                                  tokentype_ids=tt, attention_mask=am)
        # mlm ~ log V, nsp ~ log 2
        expect = np.log(cfg.vocab_size) + np.log(2)
        assert abs(float(loss) - expect) < 1.5


class TestTrainStep:
    def test_lamb_pretrain_loss_decreases(self):
        cfg = tiny_cfg(compute_dtype=jnp.bfloat16)
        init, step = make_bert_train_step(
            cfg, fused_lamb(lr=1e-2), "O5")
        state = init(jax.random.PRNGKey(0))
        tokens, mlm, nsp, tt, am = batch(cfg, b=8)
        losses = []
        for _ in range(12):
            state, m = step(state, tokens, mlm, nsp, tt, am)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses
