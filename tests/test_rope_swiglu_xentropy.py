"""RoPE (4 layouts), bias+SwiGLU, fused xentropy numerics.

Reference analogs: tests/L0/run_transformer/test_fused_rope.py,
test_fused_bias_swiglu.py; apex/contrib/test/xentropy/test_label_smoothing.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.ops.rope import (
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_2d,
    fused_apply_rotary_pos_emb_cached,
    fused_apply_rotary_pos_emb_thd,
)
from apex_tpu.ops.swiglu import fused_bias_swiglu
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss


def _np_rotate_half(x):
    h = x.shape[-1] // 2
    return np.concatenate([-x[..., h:], x[..., :h]], axis=-1)


def _np_rope(t, freqs):
    d2 = freqs.shape[-1]
    cos, sin = np.cos(freqs), np.sin(freqs)
    out = t[..., :d2] * cos + _np_rotate_half(t[..., :d2]) * sin
    if d2 < t.shape[-1]:
        out = np.concatenate([out, t[..., d2:]], axis=-1)
    return out


class TestRoPE:
    @pytest.mark.parametrize("d2", [64, 32])   # full and partial rotation
    def test_sbhd_matches_numpy(self, d2):
        rng = np.random.RandomState(0)
        s, b, h, d = 16, 2, 4, 64
        t = rng.randn(s, b, h, d).astype(np.float32)
        freqs = rng.rand(s, 1, 1, d2).astype(np.float32) * 3
        y = fused_apply_rotary_pos_emb(jnp.asarray(t), jnp.asarray(freqs))
        np.testing.assert_allclose(np.asarray(y), _np_rope(t, freqs),
                                   atol=1e-5)

    def test_cached_matches_plain(self):
        rng = np.random.RandomState(1)
        t = rng.randn(8, 2, 2, 32).astype(np.float32)
        freqs = rng.rand(8, 1, 1, 32).astype(np.float32)
        y1 = fused_apply_rotary_pos_emb(jnp.asarray(t), jnp.asarray(freqs))
        y2 = fused_apply_rotary_pos_emb_cached(
            jnp.asarray(t), jnp.cos(jnp.asarray(freqs)),
            jnp.sin(jnp.asarray(freqs))
        )
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-6)

    def test_gradient_orthogonal_with_duplicated_freqs(self):
        # standard RoPE: freqs = concat(θ, θ) — rotation is orthogonal
        rng = np.random.RandomState(2)
        t = jnp.asarray(rng.randn(6, 1, 2, 32), jnp.float32)
        theta = rng.rand(6, 1, 1, 16).astype(np.float32)
        freqs = jnp.asarray(np.concatenate([theta, theta], -1))
        dy = jnp.asarray(rng.randn(6, 1, 2, 32), jnp.float32)
        g = jax.grad(
            lambda t_: jnp.sum(fused_apply_rotary_pos_emb(t_, freqs) * dy)
        )(t)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(g)), np.linalg.norm(np.asarray(dy)),
            rtol=1e-5,
        )
        back = fused_apply_rotary_pos_emb(g, freqs)
        np.testing.assert_allclose(np.asarray(back), np.asarray(dy),
                                   atol=1e-5)

    def test_gradient_finite_difference_general_freqs(self):
        # non-duplicated freqs: check the VJP against finite differences
        rng = np.random.RandomState(5)
        t = rng.randn(4, 1, 1, 8).astype(np.float32)
        freqs = (rng.rand(4, 1, 1, 8) * 3).astype(np.float32)
        dy = rng.randn(4, 1, 1, 8).astype(np.float32)

        def f(t_):
            return float(jnp.sum(
                fused_apply_rotary_pos_emb(jnp.asarray(t_),
                                           jnp.asarray(freqs))
                * jnp.asarray(dy)
            ))

        g = jax.grad(
            lambda t_: jnp.sum(
                fused_apply_rotary_pos_emb(t_, jnp.asarray(freqs))
                * jnp.asarray(dy)
            )
        )(jnp.asarray(t))
        eps = 1e-3
        for idx in [(0, 0, 0, 0), (1, 0, 0, 5), (3, 0, 0, 7)]:
            tp, tm = t.copy(), t.copy()
            tp[idx] += eps
            tm[idx] -= eps
            num = (f(tp) - f(tm)) / (2 * eps)
            np.testing.assert_allclose(float(g[idx]), num, rtol=2e-2,
                                       atol=1e-3)

    def test_thd_packed_positions(self):
        rng = np.random.RandomState(3)
        lens = [5, 3, 8]
        total = sum(lens)
        cu = np.cumsum([0] + lens).astype(np.int32)
        t = rng.randn(total, 2, 32).astype(np.float32)
        freqs = rng.rand(max(lens), 1, 1, 32).astype(np.float32)
        y = fused_apply_rotary_pos_emb_thd(
            jnp.asarray(t), jnp.asarray(cu), jnp.asarray(freqs)
        )
        # reference: each sequence is rotated from position 0
        expect = np.concatenate([
            _np_rope(
                t[cu[i]:cu[i + 1]],                     # (len, h, d)
                freqs[:lens[i], 0, :, :],               # (len, 1, d2)
            )
            for i in range(len(lens))
        ])
        np.testing.assert_allclose(np.asarray(y), expect, atol=1e-5)

    def test_2d_splits_height_width(self):
        rng = np.random.RandomState(4)
        b, H, W, h, d = 2, 4, 3, 2, 32
        t = rng.randn(b, H * W, h, d).astype(np.float32)
        fh = rng.rand(1, H, 1, d // 2).astype(np.float32)
        fw = rng.rand(1, W, 1, d // 2).astype(np.float32)
        y = fused_apply_rotary_pos_emb_2d(
            jnp.asarray(t), H, W,
            jnp.cos(jnp.asarray(fh)), jnp.sin(jnp.asarray(fh)),
            jnp.cos(jnp.asarray(fw)), jnp.sin(jnp.asarray(fw)),
        )
        t5 = t.reshape(b, H, W, h, d)
        exp_h = t5[..., : d // 2] * np.cos(fh[:, :, None, :, :]) + \
            _np_rotate_half(t5[..., : d // 2]) * np.sin(fh[:, :, None, :, :])
        exp_w = t5[..., d // 2:] * np.cos(fw[:, None, :, :, :]) + \
            _np_rotate_half(t5[..., d // 2:]) * np.sin(fw[:, None, :, :, :])
        expect = np.concatenate([exp_h, exp_w], -1).reshape(b, H * W, h, d)
        np.testing.assert_allclose(np.asarray(y), expect, atol=1e-5)


class TestBiasSwiGLU:
    def test_matches_torch(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 6, 128).astype(np.float32)
        b = rng.randn(128).astype(np.float32)
        y = fused_bias_swiglu(jnp.asarray(x), jnp.asarray(b))

        tx = torch.tensor(x, requires_grad=True)
        tb = torch.tensor(b, requires_grad=True)
        ty_in = tx + tb
        t1, t2 = ty_in.chunk(2, dim=-1)
        ty = torch.nn.functional.silu(t1) * t2
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   atol=1e-6)

        dy = rng.randn(4, 6, 64).astype(np.float32)
        gx, gb = jax.grad(
            lambda x_, b_: jnp.sum(fused_bias_swiglu(x_, b_) * jnp.asarray(dy)),
            argnums=(0, 1),
        )(jnp.asarray(x), jnp.asarray(b))
        ty.backward(torch.tensor(dy))
        np.testing.assert_allclose(np.asarray(gx), tx.grad.numpy(), atol=1e-5)
        np.testing.assert_allclose(np.asarray(gb), tb.grad.numpy(), atol=1e-4)

    def test_no_bias_and_odd_dim(self):
        x = jnp.ones((2, 8))
        y = fused_bias_swiglu(x)
        assert y.shape == (2, 4)
        with pytest.raises(ValueError):
            fused_bias_swiglu(jnp.ones((2, 7)))


class TestXentropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_matches_torch_cross_entropy(self, smoothing):
        rng = np.random.RandomState(0)
        logits = rng.randn(16, 50).astype(np.float32)
        labels = rng.randint(1, 50, size=(16,))
        loss = softmax_cross_entropy_loss(
            jnp.asarray(logits), jnp.asarray(labels), smoothing=smoothing,
            padding_idx=-1,
        )
        tl = torch.tensor(logits, requires_grad=True)
        ref = torch.nn.functional.cross_entropy(
            tl, torch.tensor(labels), reduction="none",
            label_smoothing=smoothing,
        )
        np.testing.assert_allclose(np.asarray(loss), ref.detach().numpy(),
                                   atol=1e-5, rtol=1e-5)

        g = jax.grad(
            lambda x_: jnp.sum(
                softmax_cross_entropy_loss(x_, jnp.asarray(labels),
                                           smoothing=smoothing,
                                           padding_idx=-1)
            )
        )(jnp.asarray(logits))
        ref.sum().backward()
        np.testing.assert_allclose(np.asarray(g), tl.grad.numpy(), atol=1e-5)

    def test_padding_idx_zeroes_loss_and_grad(self):
        rng = np.random.RandomState(1)
        logits = rng.randn(8, 20).astype(np.float32)
        labels = np.array([0, 3, 0, 5, 7, 0, 1, 2])
        loss = softmax_cross_entropy_loss(
            jnp.asarray(logits), jnp.asarray(labels), padding_idx=0
        )
        ln = np.asarray(loss)
        assert (ln[labels == 0] == 0).all()
        assert (ln[labels != 0] > 0).all()
        g = jax.grad(
            lambda x_: jnp.sum(
                softmax_cross_entropy_loss(x_, jnp.asarray(labels),
                                           padding_idx=0)
            )
        )(jnp.asarray(logits))
        gn = np.asarray(g)
        assert (gn[labels == 0] == 0).all()
        assert np.abs(gn[labels != 0]).max() > 0


class TestDenseMLP:
    def test_fused_dense_gelu_dense(self):
        from apex_tpu.fused_dense import FusedDenseGeluDense

        x = jnp.asarray(np.random.RandomState(0).randn(4, 16), jnp.float32)
        mod = FusedDenseGeluDense(in_features=16, intermediate_features=32,
                                  out_features=8)
        params = mod.init(jax.random.PRNGKey(0), x)
        y = mod.apply(params, x)
        assert y.shape == (4, 8)

    def test_mlp_matches_torch(self):
        from apex_tpu.mlp import MLP

        rng = np.random.RandomState(0)
        x = rng.randn(6, 10).astype(np.float32)
        mod = MLP(mlp_sizes=(10, 20, 5), activation="relu")
        params = mod.init(jax.random.PRNGKey(0), jnp.asarray(x))
        y = mod.apply(params, jnp.asarray(x))

        w0 = np.asarray(params["params"]["kernel_0"])
        b0 = np.asarray(params["params"]["bias_0"])
        w1 = np.asarray(params["params"]["kernel_1"])
        b1 = np.asarray(params["params"]["bias_1"])
        expect = np.maximum(x @ w0 + b0, 0) @ w1 + b1
        np.testing.assert_allclose(np.asarray(y), expect, atol=1e-5)

    def test_mlp_validation(self):
        from apex_tpu.mlp import mlp_function

        with pytest.raises(ValueError):
            mlp_function(jnp.ones((2, 4)), [jnp.ones((4, 4))], None,
                         activation="tanh")
