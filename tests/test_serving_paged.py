"""Paged serving engine: block manager, block-budget admission,
prefix sharing, preempt→resume, and the HBM-scaling acceptance pin."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import generate
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.serving import (
    BlockManager, ServingEngine, blocks_for, prefix_block_hashes)


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestBlockManager:
    def test_alloc_free_reuse(self):
        mgr = BlockManager(3, 4)
        a, b, c = mgr.alloc(), mgr.alloc(), mgr.alloc()
        assert {a, b, c} == {0, 1, 2}
        assert mgr.alloc() is None and mgr.n_free == 0
        assert mgr.decref(b)
        assert mgr.n_free == 1 and mgr.n_in_use == 2
        assert mgr.alloc() == b                   # defrag-free reuse
        with pytest.raises(ValueError, match="not allocated"):
            mgr.decref(99)

    def test_refcounted_prefix_sharing(self):
        mgr = BlockManager(4, 4)
        blk = mgr.alloc()
        mgr.publish_prefix(123, blk)
        assert mgr.share_prefix(123) == blk
        assert mgr.refcount(blk) == 2 and mgr.n_shared == 1
        assert not mgr.decref(blk)                # one owner left
        assert mgr.decref(blk)                    # last owner frees
        assert mgr.lookup_prefix(123) is None     # unpublished on free
        assert mgr.share_prefix(123) is None

    def test_ensure_private_cow(self):
        mgr = BlockManager(3, 4)
        blk = mgr.alloc()
        assert mgr.ensure_private(blk) == (blk, False)   # already private
        mgr.incref(blk)
        fresh, copied = mgr.ensure_private(blk)
        assert copied and fresh != blk
        assert mgr.refcount(blk) == 1 and mgr.refcount(fresh) == 1
        # exhausted pool: CoW reports (None, True) so the caller preempts
        mgr.incref(blk)
        mgr.alloc()                                # last free block gone
        assert mgr.ensure_private(blk) == (None, True)

    def test_prefix_block_hashes_chain(self):
        toks = np.arange(12, dtype=np.int32)
        h = prefix_block_hashes(toks, 4)
        assert len(h) == 3                         # full blocks only
        # chained: a different FIRST block changes every later hash
        other = toks.copy()
        other[0] += 1
        h2 = prefix_block_hashes(other, 4)
        assert h[0] != h2[0] and h[1] != h2[1] and h[2] != h2[2]
        # identical prefix, divergent tail: shared prefix hashes match
        div = toks.copy()
        div[9] += 1
        h3 = prefix_block_hashes(div, 4)
        assert h3[0] == h[0] and h3[1] == h[1] and h3[2] != h[2]

    def test_blocks_for(self):
        assert blocks_for(0, 8) == 0
        assert blocks_for(1, 8) == 1
        assert blocks_for(8, 8) == 1
        assert blocks_for(9, 8) == 2


class TestPagedEngineParity:
    def test_mixed_lengths_match_generate(self, model):
        """The contiguous-engine oracle test, paged edition: more
        requests than lanes, ragged lengths, greedy — every response
        token-identical to generate()."""
        cfg, params = model
        rng = np.random.RandomState(0)
        lens = [3, 7, 5]
        new = 6
        prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
                   for n in lens]
        batch = np.zeros((len(lens), max(lens)), np.int32)
        for i, p in enumerate(prompts):
            batch[i, : len(p)] = p
        want = np.asarray(generate(
            params, jnp.asarray(batch), cfg, max_new_tokens=new,
            prompt_lens=jnp.asarray(lens)))
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,), cache_layout="paged",
                               block_size=8)
        resps = engine.run([dict(prompt=p, max_new_tokens=new)
                            for p in prompts])
        assert [r.request_id for r in resps] == [0, 1, 2]
        for r, n in zip(resps, lens):
            np.testing.assert_array_equal(
                r.tokens, want[r.request_id, n: n + new],
                err_msg=f"request {r.request_id}")
        assert engine.idle
        assert engine.stats()["blocks_in_use"] == 0   # all freed

    def test_bf16_pool_and_stats(self, model):
        cfg, params = model
        rng = np.random.RandomState(3)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,), cache_layout="paged",
                               block_size=8, cache_dtype=jnp.bfloat16)
        assert engine.cache["k"].dtype == jnp.bfloat16
        st = engine.stats()
        assert st["cache_layout"] == "paged"
        assert st["num_blocks"] == 2 * 4          # max_slots * ceil(32/8)
        resps = engine.run([
            dict(prompt=rng.randint(0, cfg.vocab_size, (5,)),
                 max_new_tokens=4, temperature=0.9),
            dict(prompt=rng.randint(0, cfg.vocab_size, (5,)),
                 max_new_tokens=4),
        ])
        assert len(resps) == 2
        assert engine.stats()["blocks_free"] == 8

    def test_submit_rejects_uncompletable_request(self, model):
        cfg, params = model
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               cache_layout="paged", block_size=4,
                               num_blocks=4, reserve_blocks=0)
        with pytest.raises(ValueError, match="never run to completion"):
            engine.submit(np.arange(10), max_new_tokens=10)


class TestPrefixSharing:
    def test_identical_system_prompts_share_blocks(self, model):
        """Three requests with the same 17-token prompt at bs=8: the 2
        full prompt blocks are physically shared by the two later
        admissions (4 saved blocks), the partial tail stays private —
        and decode output is still exact."""
        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        rng = np.random.RandomState(5)
        sysp = rng.randint(0, cfg.vocab_size, (17,)).astype(np.int32)
        want = np.asarray(generate(params, jnp.asarray(sysp[None]), cfg,
                                   max_new_tokens=4))[0, 17:]
        reg = telemetry.configure()
        try:
            engine = ServingEngine(params, cfg, max_slots=3, max_len=32,
                                   prompt_buckets=(32,),
                                   cache_layout="paged", block_size=8)
            for _ in range(3):
                engine.submit(sysp, max_new_tokens=4)
            engine._admit()
            st = engine.stats()
            # 3 requests x (2 full + 1 tail) logical blocks on only
            # 2 + 3x1 physical allocations
            assert st["prefix_shared_blocks"] == 4, st
            assert st["blocks_in_use"] == 5, st
            resps = engine.run([])
            for r in resps:
                np.testing.assert_array_equal(r.tokens, want)
            assert engine.stats()["blocks_in_use"] == 0
            summ = reg.summary()
            assert summ["gauges"]["serving.prefix_shared_blocks"] == 0.0
        finally:
            telemetry.shutdown()

    def test_divergent_prompts_do_not_share(self, model):
        cfg, params = model
        rng = np.random.RandomState(6)
        a = rng.randint(0, cfg.vocab_size, (16,)).astype(np.int32)
        b = a.copy()
        b[0] += 1                                  # first block differs
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(16,),
                               cache_layout="paged", block_size=8)
        engine.submit(a, max_new_tokens=2)
        engine.submit(b, max_new_tokens=2)
        engine._admit()
        assert engine.stats()["prefix_shared_blocks"] == 0


class TestPreemption:
    def test_preempt_resume_greedy_parity(self, model):
        """The acceptance pin: greedy output must survive a
        preempt→resume cycle token-for-token (resume replays
        prompt+generated through the batched flash prefill)."""
        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        rng = np.random.RandomState(7)
        p1 = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        p2 = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        reg = telemetry.configure()
        try:
            # 6 blocks of 4: both admit (2 blocks each), both outgrow
            # the pool mid-decode -> the youngest gets preempted
            engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                                   prompt_buckets=(8,),
                                   cache_layout="paged", block_size=4,
                                   num_blocks=6, reserve_blocks=0)
            resps = engine.run([dict(prompt=p1, max_new_tokens=10),
                                dict(prompt=p2, max_new_tokens=10)])
            assert reg.counter("serving.preemptions").value >= 1
            for r, p in zip(resps, (p1, p2)):
                solo = np.asarray(generate(
                    params, jnp.asarray(p[None]), cfg,
                    max_new_tokens=10))[0, 6:]
                np.testing.assert_array_equal(
                    r.tokens, solo, err_msg=f"request {r.request_id}")
            assert engine.idle
            assert engine.stats()["blocks_in_use"] == 0
        finally:
            telemetry.shutdown()

    def test_preemption_frees_blocks_and_requeues(self, model):
        cfg, params = model
        rng = np.random.RandomState(8)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,), cache_layout="paged",
                               block_size=4, num_blocks=6,
                               reserve_blocks=0)
        engine.submit(rng.randint(0, cfg.vocab_size, (8,)),
                      max_new_tokens=12)
        engine.submit(rng.randint(0, cfg.vocab_size, (8,)),
                      max_new_tokens=12)
        engine._admit()
        assert engine.stats()["blocks_in_use"] == 4
        # drive decode until the pool forces a preemption
        saw_preempt = False
        for _ in range(30):
            engine.step()
            if engine.stats()["queued"] and engine.stats()["active"]:
                saw_preempt = True
                # the youngest (request 1) was evicted with progress
                assert engine._queue[0].request_id == 1
                assert engine._queue[0].resume_tokens
                break
        assert saw_preempt
        resps = engine.run([])
        assert sorted(r.request_id for r in resps) == [0, 1]
        assert all(r.tokens.size == 12 for r in resps)
        # every admission (initial + each resume) samples its first
        # token from prefill logits, so a preempted request's decode
        # steps must discount one token per preemption
        by_id = {r.request_id: r for r in resps}
        assert by_id[0].decode_steps == 11
        preempts = engine.stats()["preemptions"]
        assert preempts >= 1
        assert by_id[1].decode_steps == 11 - preempts


class TestAdmitUnwind:
    @pytest.mark.parametrize("layout", ["contiguous", "paged"])
    def test_prefill_failure_leaks_nothing_drops_nothing(
            self, model, layout, monkeypatch):
        """ISSUE 6 satellite: a prefill raising mid-``_admit_one`` (a
        transient device OOM / XLA error) must neither leak the claimed
        slot or blocks nor drop the request — the engine stays
        drainable and a retry serves the request normally."""
        import apex_tpu.serving.engine as engine_mod

        cfg, params = model
        rng = np.random.RandomState(11)
        prompt = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        want = np.asarray(generate(params, jnp.asarray(prompt[None]), cfg,
                                   max_new_tokens=4))[0, 6:]
        kw = dict(cache_layout="paged", block_size=4) \
            if layout == "paged" else {}
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,), **kw)
        rid = engine.submit(prompt, max_new_tokens=4)

        real_prefill = engine_mod.prefill
        boom = {"armed": True}

        def flaky_prefill(*a, **k):
            if boom["armed"]:
                boom["armed"] = False
                raise RuntimeError("injected transient prefill failure")
            return real_prefill(*a, **k)

        monkeypatch.setattr(engine_mod, "prefill", flaky_prefill)
        with pytest.raises(RuntimeError, match="injected"):
            engine.step()
        # nothing leaked: every lane free again, every block back in
        # the pool (shared-prefix publications unwound with them)
        assert engine._pool.n_free == 2
        if layout == "paged":
            assert engine._mgr.n_in_use == 0
            assert engine.stats()["blocks_in_use"] == 0
        # and the request was not dropped: still at the queue front
        assert engine.stats()["queued"] == 1
        assert engine._queue[0].request_id == rid
        # the retry (prefill healthy again) serves it token-exactly
        resps = engine.run([])
        assert [r.request_id for r in resps] == [rid]
        np.testing.assert_array_equal(resps[0].tokens, want)
        assert engine.idle
        assert engine._pool.n_free == 2

    def test_post_prefill_failure_unwinds_blocks(
            self, model, monkeypatch):
        """A raise AFTER the prefill but before the slot handoff (a
        telemetry sink, the HBM sample) must unwind the claimed blocks
        too — they are attached to no ``_Slot`` yet, so nothing else
        would ever free them."""
        import apex_tpu.serving.engine as engine_mod

        cfg, params = model
        rng = np.random.RandomState(12)
        prompt = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,),
                               cache_layout="paged", block_size=4)
        rid = engine.submit(prompt, max_new_tokens=4)

        real_hist = engine_mod._telemetry.histogram
        boom = {"armed": True}

        def flaky_histogram(name, *a, **k):
            if name == "serving.prefill_ms" and boom["armed"]:
                boom["armed"] = False
                raise RuntimeError("injected post-prefill failure")
            return real_hist(name, *a, **k)

        monkeypatch.setattr(engine_mod._telemetry, "histogram",
                            flaky_histogram)
        with pytest.raises(RuntimeError, match="post-prefill"):
            engine.step()
        assert engine._mgr.n_in_use == 0
        assert engine._pool.n_free == 2
        assert engine._queue[0].request_id == rid
        resps = engine.run([])
        assert [r.request_id for r in resps] == [rid]
        assert engine._mgr.n_in_use == 0


class TestHBMScaling:
    def test_paged_admits_2x_requests_at_matched_pool_bytes(self, model):
        """The acceptance pin of the whole layout change: at MATCHED KV
        bytes, the block pool must carry ≥ 2× the concurrent requests
        of the slot layout under a long-prompt starvation mix — because
        slot admission reserves max_len per request while paged
        admission reserves only the blocks actually touched.  Also
        exercises the serving.blocks_in_use telemetry stream."""
        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        rng = np.random.RandomState(9)
        S, M, bs = 2, 64, 8
        pool_tokens = S * M                        # slot-layout KV bytes
        # the starvation mix: one long-prompt request pinning a lane
        # for many steps + a stream of short requests
        reqs = [dict(prompt=rng.randint(0, cfg.vocab_size, (40,)),
                     max_new_tokens=16)]
        reqs += [dict(prompt=rng.randint(0, cfg.vocab_size, (4,)),
                      max_new_tokens=4) for _ in range(6)]

        def high_water(engine):
            for kw in reqs:
                engine.submit(**kw)
            hw = 0
            while not engine.idle:
                engine.step()
                hw = max(hw, engine.stats()["active"])
            return hw

        slot_eng = ServingEngine(params, cfg, max_slots=S, max_len=M)
        slot_hw = high_water(slot_eng)
        assert slot_hw <= S                        # slots cap it at 2

        reg = telemetry.configure()
        try:
            paged_eng = ServingEngine(
                params, cfg, max_slots=4 * S, max_len=M,
                cache_layout="paged", block_size=bs,
                num_blocks=pool_tokens // bs)      # same KV bytes
            paged_hw = high_water(paged_eng)
            assert paged_hw >= 2 * slot_hw, (paged_hw, slot_hw)
            summ = reg.summary()
            blocks_seen = summ["gauges"]["serving.blocks_in_use"]
            assert blocks_seen == 0.0              # drained at the end
            # and the stream actually moved while requests were live
            hw_blocks = max(
                reg.gauge("serving.blocks_in_use").value, 0)
            assert "serving.blocks_free" in summ["gauges"]
        finally:
            telemetry.shutdown()

    def test_cache_bytes_scale_with_blocks_not_slots(self, model):
        """Direct byte accounting: doubling max_slots leaves the paged
        pool untouched, while the slot layout doubles."""
        cfg, params = model

        def kv_bytes(engine):
            return (engine.cache["k"].size + engine.cache["v"].size
                    ) * engine.cache["k"].dtype.itemsize

        slot2 = ServingEngine(params, cfg, max_slots=2, max_len=64)
        slot4 = ServingEngine(params, cfg, max_slots=4, max_len=64)
        assert kv_bytes(slot4) == 2 * kv_bytes(slot2)
        paged2 = ServingEngine(params, cfg, max_slots=2, max_len=64,
                               cache_layout="paged", block_size=8,
                               num_blocks=16)
        paged4 = ServingEngine(params, cfg, max_slots=4, max_len=64,
                               cache_layout="paged", block_size=8,
                               num_blocks=16)
        assert kv_bytes(paged4) == kv_bytes(paged2)
        # and at the default num_blocks the pool is byte-parity with
        # the slot layout (same worst case, now divisible)
        paged_dflt = ServingEngine(params, cfg, max_slots=2, max_len=64,
                                   cache_layout="paged", block_size=8)
        assert kv_bytes(paged_dflt) == kv_bytes(slot2)
