"""conv_bias_relu contrib ops + Megatron batch samplers.

Reference patterns: apex/contrib/test/conv_bias_relu/ (fused op vs
composed torch ops + gradcheck) and Megatron data_samplers behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.conv_bias_relu import (
    ConvBias,
    ConvBiasMaskReLU,
    ConvBiasReLU,
    ConvFrozenScaleBiasReLU,
)
from apex_tpu.transformer._data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)


def _data(seed=0, b=2, hw=8, cin=4, cout=6, k=3):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(b, hw, hw, cin), jnp.float32)
    w = jnp.asarray(rs.randn(k, k, cin, cout) * 0.2, jnp.float32)
    bias = jnp.asarray(rs.randn(cout), jnp.float32)
    return x, w, bias


def _ref_conv(x, w, stride=1, padding=1):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(padding, padding)] * 2,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class TestConvBiasReLU:
    def test_conv_bias_relu(self):
        x, w, b = _data()
        got = ConvBiasReLU(x, w, b)
        want = jax.nn.relu(_ref_conv(x, w) + b)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)
        assert np.any(np.asarray(got) == 0)  # relu actually clips

    def test_conv_bias_no_relu_and_stride(self):
        x, w, b = _data(1)
        got = ConvBias(x, w, b, padding=0, stride=2)
        want = _ref_conv(x, w, stride=2, padding=0) + b
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)

    def test_conv_bias_mask_relu(self):
        x, w, b = _data(2)
        y = _ref_conv(x, w) + b
        mask = jnp.asarray(
            np.random.RandomState(0).rand(*y.shape) > 0.5)
        got = ConvBiasMaskReLU(x, w, b, mask)
        want = jax.nn.relu(y * mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)

    def test_conv_frozen_scale_bias_relu(self):
        x, w, b = _data(3)
        scale = jnp.asarray(
            1 + 0.2 * np.random.RandomState(1).randn(w.shape[-1]),
            jnp.float32)
        got = ConvFrozenScaleBiasReLU(x, w, scale, b)
        want = jax.nn.relu(_ref_conv(x, w) * scale + b)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)

    def test_grads(self):
        x, w, b = _data(4)
        gx, gw, gb = jax.grad(
            lambda *a: jnp.sum(ConvBiasReLU(*a) ** 2),
            argnums=(0, 1, 2))(x, w, b)
        rx, rw, rb = jax.grad(
            lambda x, w, b: jnp.sum(
                jax.nn.relu(_ref_conv(x, w) + b) ** 2),
            argnums=(0, 1, 2))(x, w, b)
        for g, r in ((gx, rx), (gw, rw), (gb, rb)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       atol=1e-5)


class TestMegatronSamplers:
    def test_sequential_disjoint_ranks_and_order(self):
        out = {}
        for rank in range(2):
            s = MegatronPretrainingSampler(
                total_samples=20, consumed_samples=0,
                local_minibatch_size=3, data_parallel_rank=rank,
                data_parallel_size=2)
            out[rank] = list(s)
        # each global batch of 6 is split 3/3 between the ranks, in order
        assert out[0][0] == [0, 1, 2] and out[1][0] == [3, 4, 5]
        assert out[0][1] == [6, 7, 8] and out[1][1] == [9, 10, 11]
        flat = sorted(i for r in out.values() for b in r for i in b)
        assert flat == list(range(18))  # last partial dropped

    def test_sequential_resume_and_drop_last(self):
        s = MegatronPretrainingSampler(
            total_samples=10, consumed_samples=6,
            local_minibatch_size=2, data_parallel_rank=0,
            data_parallel_size=1, drop_last=False)
        assert list(s) == [[6, 7], [8, 9]]

    def test_random_disjoint_and_epoch_deterministic(self):
        def batches(rank):
            s = MegatronPretrainingRandomSampler(
                total_samples=24, consumed_samples=0,
                local_minibatch_size=3, data_parallel_rank=rank,
                data_parallel_size=2)
            return list(s)

        b0, b1 = batches(0), batches(1)
        i0 = {i for b in b0 for i in b}
        i1 = {i for b in b1 for i in b}
        assert not (i0 & i1), "ranks must draw disjoint buckets"
        # same epoch seed -> identical shuffle
        assert batches(0) == b0

    def test_random_resume_skips_consumed(self):
        full = MegatronPretrainingRandomSampler(
            total_samples=24, consumed_samples=0,
            local_minibatch_size=3, data_parallel_rank=0,
            data_parallel_size=2)
        resumed = MegatronPretrainingRandomSampler(
            total_samples=24, consumed_samples=6,
            local_minibatch_size=3, data_parallel_rank=0,
            data_parallel_size=2)
        assert list(resumed) == list(full)[1:]
