"""Refcounted HBM LoRA slab pool (ISSUE 20): the ledger discipline.

Pins the adapter-pool invariants the multi-tenant fast path leans on:
the capacity knob's env-override/suffix/off grammar, registration
geometry guards, the acquire/release refcount ledger (hit = bump,
miss = page-in, pinned-full = admission blocks), LRU eviction only at
zero refs, and — the headline — that ``census()`` stays a TRUE
partition (every slot exactly one of free / pinned / evictable)
through a randomized churn storm."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.lora import adapter_bytes, init_lora_adapter
from apex_tpu.serving.adapter_pool import (
    AdapterPool, resolve_adapter_pool_bytes)


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def cfg():
    return _cfg()


def _adapters(cfg, n, rank=4, seed=0):
    return {aid: init_lora_adapter(jax.random.PRNGKey(seed + aid), cfg,
                                   rank=rank, b_std=0.02)
            for aid in range(1, n + 1)}


def _pool(cfg, n, slots=None, **kw):
    pool = AdapterPool(cfg, slots=slots, **kw)
    for aid, ad in _adapters(cfg, n).items():
        pool.register(aid, ad)
    return pool


class TestResolveKnob:
    def test_env_beats_caller(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_ADAPTER_POOL_BYTES", "4096")
        assert resolve_adapter_pool_bytes(None) == 4096
        assert resolve_adapter_pool_bytes(1 << 30) == 4096

    def test_suffixes_and_off(self, monkeypatch):
        monkeypatch.delenv("APEX_TPU_ADAPTER_POOL_BYTES",
                           raising=False)
        assert resolve_adapter_pool_bytes("256m") == 256 * (1 << 20)
        assert resolve_adapter_pool_bytes("2g") == 2 * (1 << 30)
        assert resolve_adapter_pool_bytes("off") is None
        assert resolve_adapter_pool_bytes("0") is None
        assert resolve_adapter_pool_bytes(None) is None
        for off in ("off", "0", " OFF "):
            monkeypatch.setenv("APEX_TPU_ADAPTER_POOL_BYTES", off)
            assert resolve_adapter_pool_bytes(1 << 20) is None

    def test_malformed_env_warns_by_name_and_falls_back(
            self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_ADAPTER_POOL_BYTES", "lots")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert resolve_adapter_pool_bytes(8192) == 8192
        assert any("APEX_TPU_ADAPTER_POOL_BYTES" in str(x.message)
                   for x in w)

    def test_nonpositive_caller_value_raises(self):
        with pytest.raises(ValueError, match="pool_bytes"):
            resolve_adapter_pool_bytes(0)


class TestRegistration:
    def test_id_zero_is_reserved(self, cfg):
        pool = AdapterPool(cfg, slots=2)
        ad = init_lora_adapter(jax.random.PRNGKey(1), cfg, rank=4)
        with pytest.raises(ValueError, match="no-adapter sentinel"):
            pool.register(0, ad)
        with pytest.raises(ValueError, match="start at 1"):
            pool.register(-3, ad)

    def test_geometry_mismatch_refused_at_the_door(self, cfg):
        pool = _pool(cfg, 1, slots=2)
        odd = init_lora_adapter(jax.random.PRNGKey(9), cfg, rank=8)
        with pytest.raises(ValueError, match="uniform geometry"):
            pool.register(2, odd)

    def test_resident_reregister_refused(self, cfg):
        pool = _pool(cfg, 1, slots=2)
        pool.acquire(1)
        fresh = init_lora_adapter(jax.random.PRNGKey(7), cfg, rank=4)
        with pytest.raises(ValueError, match="resident"):
            pool.register(1, fresh)

    def test_unregistered_acquire_raises(self, cfg):
        pool = _pool(cfg, 1, slots=2)
        with pytest.raises(KeyError, match="not registered"):
            pool.acquire(99)


class TestLedger:
    def test_lane_index_is_slot_plus_one(self, cfg):
        """0 stays the traced no-adapter id, so a resident slot s maps
        to lane slab index s + 1."""
        pool = _pool(cfg, 2, slots=2)
        assert pool.acquire(0) == 0
        lanes = {pool.acquire(1), pool.acquire(2)}
        assert lanes == {1, 2}

    def test_hit_bumps_miss_pages_in(self, cfg):
        pool = _pool(cfg, 2, slots=2)
        lane = pool.acquire(1)
        assert (pool.hits, pool.misses) == (0, 1)
        assert pool.acquire(1) == lane       # resident: refcount bump
        assert (pool.hits, pool.misses) == (1, 1)
        st = pool.stats()
        assert st["pinned_refs"] == 2 and st["resident"] == 1

    def test_pinned_full_blocks_admission(self, cfg):
        pool = _pool(cfg, 3, slots=2)
        pool.acquire(1)
        pool.acquire(2)
        assert pool.acquire(3) is None       # every slot pinned
        pool.release(1)                      # zero refs -> evictable
        assert pool.acquire(3) is not None
        assert pool.evictions == 1

    def test_lru_evicts_least_recent_zero_ref(self, cfg):
        pool = _pool(cfg, 3, slots=2)
        pool.acquire(1)
        pool.acquire(2)
        pool.release(1)
        pool.release(2)                      # LRU order: 1 then 2
        pool.acquire(3)                      # must evict 1, keep 2
        ids = set(pool.resident_ids())
        assert ids == {2, 3}

    def test_warm_resident_survives_release(self, cfg):
        """At zero refs the adapter STAYS resident — the warm-slab
        property the router's affinity scoring steers toward."""
        pool = _pool(cfg, 1, slots=2)
        pool.acquire(1)
        pool.release(1)
        assert pool.resident_ids() == [1]
        pool.acquire(1)
        assert pool.hits == 1 and pool.misses == 1

    def test_release_without_acquire_is_a_corrupt_ledger(self, cfg):
        pool = _pool(cfg, 2, slots=2)
        with pytest.raises(RuntimeError, match="ledger"):
            pool.release(1)
        pool.acquire(1)
        pool.release(1)
        with pytest.raises(RuntimeError, match="ledger"):
            pool.release(1)
        pool.release(0)                      # aid 0 is always a no-op

    def test_pool_bytes_fixes_slot_count(self, cfg):
        ads = _adapters(cfg, 2)
        per = adapter_bytes(ads[1])
        pool = AdapterPool(cfg, pool_bytes=3 * per + per // 2)
        for aid, ad in ads.items():
            pool.register(aid, ad)
        pool.acquire(1)
        assert pool.n_slots == 3

    def test_pool_smaller_than_one_adapter_raises(self, cfg):
        pool = AdapterPool(cfg, pool_bytes=8)
        pool.register(1, init_lora_adapter(jax.random.PRNGKey(1), cfg,
                                           rank=4))
        with pytest.raises(ValueError, match="cannot hold"):
            pool.acquire(1)

    def test_slab_values_track_residency(self, cfg):
        """A page-in writes the adapter's scaled factors into its slot;
        eviction re-scatters zeros — the traced step must never read a
        stale tenant's weights through a recycled slot."""
        pool = _pool(cfg, 2, slots=1)
        lane = pool.acquire(1)
        slab = pool.slabs()["qkv"]["b"]      # [L, G, r, out]
        got = np.asarray(slab[:, lane - 1])
        ad = pool._registry[1]
        want = np.asarray(ad.b["qkv"] * ad.scaling)
        np.testing.assert_allclose(got, want, rtol=1e-6)
        pool.release(1)
        lane2 = pool.acquire(2)              # evicts 1, reuses the slot
        assert lane2 == lane and pool.evictions == 1
        got2 = np.asarray(pool.slabs()["qkv"]["b"][:, lane2 - 1])
        ad2 = pool._registry[2]
        np.testing.assert_allclose(
            got2, np.asarray(ad2.b["qkv"] * ad2.scaling), rtol=1e-6)


class TestCensusPartition:
    def test_partition_holds_under_randomized_churn(self, cfg):
        """The headline ledger gate: through hundreds of interleaved
        acquire/release/evict transitions, every slot stays exactly one
        of free / pinned / evictable and the LRU mirror never drifts."""
        pool = _pool(cfg, 6, slots=3)
        rng = np.random.RandomState(20)
        held = []                            # multiset of live pins
        for _ in range(300):
            if held and rng.rand() < 0.45:
                aid = held.pop(rng.randint(len(held)))
                pool.release(aid)
            else:
                aid = int(rng.randint(1, 7))
                if pool.acquire(aid) is not None:
                    held.append(aid)
            counts = pool.census()           # raises on any violation
            assert counts["pinned"] == len(set(held))
        for aid in held:
            pool.release(aid)
        counts = pool.census()
        assert counts["pinned"] == 0
        assert pool.stats()["pinned_refs"] == 0
        assert pool.evictions >= 1           # the storm actually churned

    def test_inventory_is_count_bounded(self, cfg):
        pool = _pool(cfg, 4, slots=3)
        for aid in (1, 2, 3):
            pool.acquire(aid)
        assert len(pool.resident_ids()) <= AdapterPool.INVENTORY_N
        assert set(pool.resident_ids()) == {1, 2, 3}
        st = pool.stats()
        assert st["resident_ids"] == pool.resident_ids()
        assert st["pool_bytes"] == 3 * st["adapter_bytes"]
