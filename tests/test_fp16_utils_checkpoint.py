"""Legacy fp16_utils layer + checkpoint/resume round-trips.

Reference analogs: tests/L0/run_fp16util (master/model param helpers),
run_amp/test_checkpointing.py (amp state_dict round-trip preserving the
loss scaler), and the ADLR AutoResume hook shape.
"""


import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.fp16_utils import (
    DynamicLossScaler,
    FP16_Optimizer,
    network_to_half,
    prep_param_lists,
    master_params_to_model_params,
)
from apex_tpu.optimizers import fused_adam
from apex_tpu.utils.checkpoint import (
    AutoResume,
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)


def params():
    rng = np.random.RandomState(0)
    return {
        "dense": {"kernel": jnp.asarray(rng.randn(8, 4), jnp.float32)},
        "bn": {"scale": jnp.ones((4,), jnp.float32)},
    }


class TestFp16Util:
    def test_network_to_half_keeps_norms_fp32(self):
        half = network_to_half(params())
        assert half["dense"]["kernel"].dtype in (jnp.float16, jnp.bfloat16)
        assert half["bn"]["scale"].dtype == jnp.float32

    def test_prep_and_copyback(self):
        p = params()
        model, master = prep_param_lists(p)
        assert master["dense"]["kernel"].dtype == jnp.float32
        back = master_params_to_model_params(master, model)
        assert back["dense"]["kernel"].dtype == model["dense"]["kernel"].dtype


class TestFP16Optimizer:
    def test_training_and_overflow(self):
        p = params()
        # modest init scale: fp16 grads overflow at the 2^16 default until
        # the scaler backs off (realistic, but noisy for this test)
        opt = FP16_Optimizer(fused_adam(lr=1e-2), p,
                             dynamic_loss_scale=True,
                             dynamic_loss_args={"init_scale": 128.0})
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(16, 8), jnp.float32)
        y = jnp.asarray(rng.randn(16, 4), jnp.float32)

        def loss_fn(mp, x, y):
            h = x.astype(jnp.float32) @ mp["dense"]["kernel"].astype(
                jnp.float32) * mp["bn"]["scale"]
            return jnp.mean((h - y) ** 2)

        losses = []
        for i in range(20):
            loss, grads = jax.value_and_grad(
                lambda mp: opt.scale_loss(loss_fn(mp, x, y)))(
                    opt.model_params)
            skipped = opt.step(grads)
            assert not skipped
            losses.append(float(loss) / opt.loss_scale)
        assert losses[-1] < losses[0]

        # overflow: inf grads → step skipped, scale halved
        scale = opt.loss_scale
        master_before = np.asarray(opt.master_params["dense"]["kernel"])
        bad = jax.tree_util.tree_map(
            lambda g: g.at[(0,) * g.ndim].set(jnp.inf), grads)
        assert opt.step(bad) is True
        assert opt.loss_scale == scale / 2
        np.testing.assert_array_equal(
            np.asarray(opt.master_params["dense"]["kernel"]), master_before)

    def test_state_dict_roundtrip(self):
        opt = FP16_Optimizer(fused_adam(lr=1e-2), params(),
                             dynamic_loss_scale=True)
        d = opt.state_dict()
        opt2 = FP16_Optimizer(fused_adam(lr=1e-2), params(),
                              dynamic_loss_scale=True)
        opt2.load_state_dict(d)
        assert opt2.loss_scale == opt.loss_scale


class TestDynamicLossScaler:
    def test_window_doubling(self):
        s = DynamicLossScaler(init_scale=4.0, scale_window=2)
        assert s.loss_scale == 4.0
        assert s.update_scale(overflow=False) is False
        assert s.update_scale(overflow=False) is False
        assert s.loss_scale == 8.0          # window hit → doubled
        assert s.update_scale(overflow=True) is True
        assert s.loss_scale == 4.0          # halved on overflow


class TestCheckpoint:
    def test_train_state_roundtrip(self, tmp_path):
        init, step = amp.make_train_step(
            lambda p, x: jnp.sum((x @ p["w"]) ** 2),
            fused_adam(lr=1e-3), "O5")
        state = init({"w": jnp.ones((4, 4), jnp.float32)})
        x = jnp.ones((2, 4), jnp.float32)
        state, _ = step(state, x)
        state, _ = step(state, x)

        d = str(tmp_path / "ckpt")
        save_checkpoint(d, int(state.step), state)
        assert latest_step(d) == 2

        fresh = init({"w": jnp.ones((4, 4), jnp.float32)})
        restored = restore_checkpoint(d, fresh)
        assert int(restored.step) == 2
        np.testing.assert_array_equal(
            np.asarray(restored.master_params["w"]),
            np.asarray(state.master_params["w"]))
        # resumed training continues cleanly
        restored, m = step(restored, x)
        assert int(restored.step) == 3

    def test_autoresume(self, tmp_path):
        f = str(tmp_path / "term")
        ar = AutoResume(termination_file=f).init()
        assert not ar.termination_requested()
        open(f, "w").close()
        assert ar.termination_requested()
        ar.request_resume()
        assert not ar.termination_requested()


class TestAsyncSaver:
    def test_async_save_restore_roundtrip(self, tmp_path):
        import jax.numpy as jnp

        from apex_tpu.utils.checkpoint import (
            async_saver, latest_step, restore_checkpoint)

        state = {"w": jnp.arange(12.0).reshape(3, 4),
                 "step": jnp.asarray(7)}
        with async_saver() as saver:
            for step in (1, 2, 3):
                s = {"w": state["w"] + step, "step": jnp.asarray(step)}
                saver.save(str(tmp_path), step, s)
            # saves overlap the loop; exit waits for durability
        assert latest_step(str(tmp_path)) == 3
        got = restore_checkpoint(str(tmp_path), state)
        np.testing.assert_allclose(np.asarray(got["w"]),
                                   np.asarray(state["w"]) + 3)
        assert int(got["step"]) == 3

    def test_save_returns_before_wait(self, tmp_path):
        """The save call itself must not block on the disk write: it
        returns a path immediately; wait() makes it durable."""
        import os
        import jax.numpy as jnp

        from apex_tpu.utils.checkpoint import async_saver

        big = {"x": jnp.ones((256, 256))}
        saver = async_saver()
        try:
            path = saver.save(str(tmp_path), 1, big)
            assert path.endswith("step_1")
            saver.wait()
            assert os.path.isdir(path)
        finally:
            saver.close()

    def test_async_save_survives_donation(self, tmp_path):
        """The train loop donates state buffers to the next step; the
        async save must snapshot to host BEFORE returning or the
        background write would read invalidated device memory."""
        import jax
        import jax.numpy as jnp

        from apex_tpu.utils.checkpoint import (
            async_saver, restore_checkpoint)

        step = jax.jit(lambda s: jax.tree_util.tree_map(
            lambda x: x * 2.0 + 1.0, s), donate_argnums=0)

        state = {"w": jnp.full((128, 128), 3.0)}
        with async_saver() as saver:
            state = step(state)                 # w = 7
            saver.save(str(tmp_path), 1, state)
            expect = np.asarray(state["w"]).copy()
            for _ in range(5):                  # donates + overwrites
                state = step(state)
        got = restore_checkpoint(
            str(tmp_path), {"w": jnp.zeros((128, 128))}, step=1)
        np.testing.assert_allclose(np.asarray(got["w"]), expect)
