"""Data-parallel layer tests on the virtual 8-device CPU mesh.

Reference analogs: tests/distributed/synced_batchnorm/two_gpu_unit_test.py
(SyncBN vs single-device BN ground truth), tests/distributed/DDP tests
(grads identical across ranks), tests/L0/run_amp/test_larc.py,
contrib clip_grad tests.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import optimizers as opt
from apex_tpu.parallel import (
    DistributedDataParallel,
    SyncBatchNorm,
    allreduce_gradients,
    clip_grad_norm,
    create_mesh,
    data_parallel_mesh,
    larc,
    make_ddp_train_step,
)

shard_map = jax.shard_map


def test_create_mesh_shapes():
    mesh = create_mesh(tp=2, pp=2)
    assert mesh.shape == {"pp": 2, "dp": 2, "sp": 1, "ep": 1, "tp": 2}
    with pytest.raises(ValueError):
        create_mesh(tp=3)
    with pytest.raises(ValueError):
        create_mesh(dp=3, tp=2)


def test_allreduce_gradients_options():
    mesh = data_parallel_mesh()

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
    )
    def avg(g):
        return allreduce_gradients({"w": g}, "dp")["w"]

    g = jnp.arange(8.0)
    out = avg(g)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
    )
    def summed(g):
        return allreduce_gradients(
            {"w": g}, "dp", gradient_average=False
        )["w"]

    np.testing.assert_allclose(np.asarray(summed(g)), np.full(8, 28.0))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
    )
    def predivided(g):
        return allreduce_gradients(
            {"w": g}, "dp", gradient_predivide_factor=8.0,
            allreduce_always_fp32=True,
        )["w"]

    np.testing.assert_allclose(np.asarray(predivided(g)), np.full(8, 3.5),
                               rtol=1e-6)


def test_ddp_wrapper_grads_match_fullbatch():
    mesh = data_parallel_mesh()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 4), jnp.float32)
    y = jnp.asarray(rng.randn(16, 2), jnp.float32)
    params = {"w": jnp.asarray(rng.randn(4, 2), jnp.float32)}

    def loss_fn(p, xb, yb):
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    # single-device full batch
    g_full = jax.grad(loss_fn)(params, x, y)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), P("dp"), P("dp")),
        out_specs=P(),
    )
    def sharded_grads(p, xb, yb):
        ddp = DistributedDataParallel(loss_fn)
        return jax.grad(ddp)(p, xb, yb)

    g_ddp = sharded_grads(params, x, y)
    np.testing.assert_allclose(np.asarray(g_ddp["w"]),
                               np.asarray(g_full["w"]), atol=1e-6)


def test_make_ddp_train_step_end_to_end():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, 8), jnp.float32)
    w_true = rng.randn(8, 2).astype(np.float32)
    y = x @ jnp.asarray(w_true)          # realizable → loss can reach ~0
    params = {"w": jnp.asarray(rng.randn(8, 2) * 0.3, jnp.float32)}

    def loss_fn(p, xb, yb):
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    init, step = make_ddp_train_step(
        loss_fn, opt.fused_adam(lr=0.05), "O2", batch_axes=2
    )
    state = init(params)
    _, m0 = step(state, x, y)
    for _ in range(120):
        state, m = step(state, x, y)
    # first couple of steps skip while the fp16 loss scale settles
    assert float(m["loss"]) < float(m0["loss"]) * 0.35


def test_sync_batchnorm_matches_fullbatch_bn():
    """SyncBN over 8 shards == plain BN over the full batch (the exact
    invariant tests/distributed/synced_batchnorm checks)."""
    mesh = data_parallel_mesh()
    rng = np.random.RandomState(2)
    x = rng.randn(16, 6, 6, 4).astype(np.float32)

    bn = SyncBatchNorm(num_features=4, axis_name=None)
    variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x))
    y_full, _ = bn.apply(
        variables, jnp.asarray(x), mutable=["batch_stats"]
    )

    sbn = SyncBatchNorm(num_features=4, axis_name="dp")
    svars = sbn.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), P("dp")),
        out_specs=(P("dp"), P()),
    )
    def apply_sharded(v, xb):
        yb, mut = sbn.apply(v, xb, mutable=["batch_stats"])
        return yb, mut["batch_stats"]

    y_sync, stats = apply_sharded(svars, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y_sync), np.asarray(y_full),
                               atol=1e-5)

    # running stats must equal full-batch stats
    full_mean = x.mean(axis=(0, 1, 2))
    np.testing.assert_allclose(
        np.asarray(stats["mean"]), 0.1 * full_mean, atol=1e-6
    )


def test_sync_batchnorm_eval_uses_running_stats():
    x = jnp.asarray(np.random.RandomState(3).randn(4, 4).astype(np.float32))
    bn = SyncBatchNorm(num_features=4, axis_name=None)
    v = bn.init(jax.random.PRNGKey(0), x)
    y = bn.apply(v, x, use_running_average=True)
    # fresh stats: mean 0 var 1 → identity (affine is 1/0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-3)


def test_larc_clip_and_eager():
    p = {"w": jnp.asarray([3.0, 4.0])}          # ||p|| = 5
    g = {"w": jnp.asarray([0.6, 0.8])}          # ||g|| = 1
    lr = 0.1
    inner = opt.fused_sgd(lr=lr)
    tx = larc(inner, lr=lr, trust_coefficient=0.02, clip=True)
    state = tx.init(p)
    u, _ = tx.update(g, state, p)
    # adaptive_lr = 0.02*5/1 = 0.1 → alr/lr = 1 → clip to 1 → plain SGD
    np.testing.assert_allclose(np.asarray(u["w"]),
                               -lr * np.asarray(g["w"]), atol=1e-6)

    tx2 = larc(inner, lr=lr, trust_coefficient=0.001, clip=False)
    u2, _ = tx2.update(g, tx2.init(p), p)
    # eager: grads scaled by 0.001*5/1 = 0.005
    np.testing.assert_allclose(np.asarray(u2["w"]),
                               -lr * 0.005 * np.asarray(g["w"]), atol=1e-7)


def test_clip_grad_norm():
    g = {"a": jnp.asarray([3.0, 0.0]), "b": jnp.asarray([0.0, 4.0])}
    clipped, total = clip_grad_norm(g, max_norm=1.0)
    np.testing.assert_allclose(float(total), 5.0, rtol=1e-5)
    cn = np.sqrt(sum(float(jnp.sum(v ** 2))
                     for v in jax.tree_util.tree_leaves(clipped)))
    np.testing.assert_allclose(cn, 1.0, rtol=1e-4)

    # under the norm → untouched
    same, total2 = clip_grad_norm(g, max_norm=10.0)
    np.testing.assert_allclose(np.asarray(same["a"]), [3.0, 0.0], rtol=1e-6)

    # inf norm
    _, tinf = clip_grad_norm(g, max_norm=1.0, norm_type=float("inf"))
    np.testing.assert_allclose(float(tinf), 4.0)

    # nonfinite poisoning
    bad = {"a": jnp.asarray([jnp.inf])}
    poisoned, _ = clip_grad_norm(bad, 1.0, error_if_nonfinite=True)
    assert not np.isfinite(np.asarray(poisoned["a"])).any()
