"""Tier-1 coverage for apex_tpu.observability (ISSUE 1 tentpole).

Covers: the disabled no-op fast path (asserted structurally — singleton
identity — not by wall-clock), registry/sink record schema, span +
StepTimer protocols, the AMP/optimizer/collective/pipeline
instrumentation, and the acceptance smoke loop: a tiny AMP train loop
with telemetry enabled produces a JSONL file containing loss-scale,
grad-norm and span records that tools/telemetry_report.py summarizes.
"""

import importlib.util
import io
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.observability as obs
from apex_tpu.observability.metrics import NOOP_METRIC

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    # every test leaves the process back on the no-op fast path
    yield
    obs.shutdown()


def _records(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


# ---------------------------------------------------------------------------
# no-op fast path (the zero-overhead-when-disabled acceptance criterion)
# ---------------------------------------------------------------------------


class TestDisabledFastPath:
    def test_disabled_by_default(self):
        assert not obs.enabled()
        assert obs.registry() is None

    def test_metric_helpers_return_shared_noop_singleton(self):
        assert obs.counter("a") is NOOP_METRIC
        assert obs.gauge("b") is NOOP_METRIC
        assert obs.histogram("c") is NOOP_METRIC
        # and the singleton's methods are inert
        obs.counter("a").inc(5)
        obs.gauge("b").set(1.0)
        obs.histogram("c").observe(2.0)
        obs.event("e", detail="ignored")

    def test_span_takes_no_timestamp_when_disabled(self):
        s = obs.span("nope")
        with s:
            # disabled fast path: the entry is a bare None marker — no
            # perf_counter read, no TraceAnnotation
            assert s._thread_stack() == [None]
        assert s._thread_stack() == []

    def test_span_reentrant_records_every_level(self, tmp_path):
        # ContextDecorator shares one instance across calls: recursion
        # must record one span per level, not clobber the outer timer
        path = tmp_path / "t.jsonl"
        obs.configure(jsonl_path=str(path))
        try:
            @obs.span("rec")
            def f(n):
                if n:
                    f(n - 1)

            f(2)
        finally:
            obs.shutdown()
        recs = [json.loads(line) for line in open(path)]
        assert sum(r["type"] == "span" and r["name"] == "rec"
                   for r in recs) == 3

    def test_instrumentation_entry_points_are_noops(self):
        from apex_tpu.amp.scaler import record_scaler_step
        from apex_tpu.optimizers._common import record_opt_norms
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            record_schedule_telemetry)

        obs.record_step_metrics({"loss": 1.0})
        record_scaler_step({"loss_scale": 1.0, "overflow": False})
        record_opt_norms(opt_state=None)
        record_schedule_telemetry("1f1b", n_micro=4, n_stages=2, ticks=5)
        assert not obs.enabled()

    def test_tight_loop_unconfigured_shares_one_noop(self):
        """ISSUE 4 satellite: instrument a tight loop with telemetry
        unconfigured and assert every public helper — including the
        detector-feeding entry points — hands back the SHARED no-op
        (one singleton across all iterations, i.e. no per-call
        allocation of metric objects) and materializes no registry,
        detector bank, or recorder as a side effect."""
        from apex_tpu.amp.scaler import record_scaler_step

        assert not obs.enabled()
        hot_span = obs.span("hot")           # constructed once, reused
        returned = set()
        for i in range(1000):
            returned.add(id(obs.counter("c")))
            returned.add(id(obs.gauge("g")))
            returned.add(id(obs.histogram("h")))
            # inert singleton methods + void helpers
            obs.counter("c").inc()
            obs.gauge("g").set(i)
            obs.histogram("h").observe(i)
            assert obs.event("e", step=i) is None
            assert obs.set_step(i) is None
            with hot_span:
                pass
            # the detector/recorder feeds fast-path out before any work
            assert obs.record_step_metrics(
                {"loss": 1.0, "step": i}) is None
            assert record_scaler_step(
                {"loss_scale": 1.0, "overflow": False}) is None
        assert returned == {id(NOOP_METRIC)}
        assert obs.registry() is None        # nothing materialized
        assert hot_span._thread_stack() == []

    def test_sample_device_memory_disabled_emits_nothing(self):
        # emit path requires a registry; unconfigured it must neither
        # create one nor raise
        obs.sample_device_memory()
        assert not obs.enabled()


# ---------------------------------------------------------------------------
# registry + sinks
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_jsonl_records_and_schema_version(self, tmp_path):
        path = tmp_path / "t.jsonl"
        reg = obs.configure(jsonl_path=str(path), tags={"run": "unit"})
        assert obs.enabled() and obs.registry() is reg
        reg.counter("c").inc()
        reg.counter("c").inc(2)
        reg.gauge("g").set(3.5)
        reg.histogram("h").observe(1.0)
        reg.histogram("h").observe(2.0)
        reg.event("ev", reason="x")
        obs.shutdown()
        recs = _records(path)
        assert all(r["schema_version"] == obs.SCHEMA_VERSION for r in recs)
        assert all("t" in r for r in recs)
        assert recs[0]["type"] == "meta"
        assert recs[0]["tags"]["run"] == "unit"
        counter_recs = [r for r in recs
                        if r["type"] == "counter" and r["name"] == "c"]
        assert counter_recs and counter_recs[-1]["value"] == 3
        assert [r["value"] for r in recs if r["type"] == "gauge"] == [3.5]
        assert [r["value"] for r in recs
                if r["type"] == "observe"] == [1.0, 2.0]
        assert any(r["type"] == "event" and r["data"] == {"reason": "x"}
                   for r in recs)

    def test_get_or_create_returns_same_object(self):
        reg = obs.configure()
        assert reg.counter("x") is reg.counter("x")
        assert reg.gauge("x") is reg.gauge("x")
        assert reg.histogram("x") is reg.histogram("x")

    def test_histogram_summary_quantiles(self):
        reg = obs.configure()
        h = reg.histogram("lat")
        for v in (0.1, 0.2, 0.3, 0.4, 0.5):
            h.observe(v)
        s = h.summary()
        assert s["count"] == 5
        assert s["p50"] == pytest.approx(0.3)
        assert s["p95"] == pytest.approx(0.5)
        assert s["max"] == pytest.approx(0.5)

    def test_stderr_summary_sink(self, tmp_path, capsys):
        obs.configure(stderr_summary=True)
        obs.counter("my.counter").inc(7)
        obs.gauge("my.gauge").set(1.25)
        obs.shutdown()
        err = capsys.readouterr().err
        assert "telemetry summary" in err
        assert "my.counter" in err and "7" in err
        assert "my.gauge" in err

    def test_reconfigure_closes_previous_registry(self, tmp_path):
        p1, p2 = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        obs.configure(jsonl_path=str(p1))
        obs.counter("only_in_a").inc()
        obs.configure(jsonl_path=str(p2))   # implicit shutdown of #1
        obs.shutdown()
        assert any(r.get("name") == "only_in_a" for r in _records(p1))
        assert not any(r.get("name") == "only_in_a" for r in _records(p2))


# ---------------------------------------------------------------------------
# spans + StepTimer
# ---------------------------------------------------------------------------


class TestSpans:
    def test_span_context_and_decorator(self, tmp_path):
        path = tmp_path / "t.jsonl"
        obs.configure(jsonl_path=str(path))

        with obs.span("ctx"):
            pass

        @obs.span("deco")
        def work():
            return 42

        assert work() == 42
        obs.shutdown()
        spans = {r["name"] for r in _records(path) if r["type"] == "span"}
        assert {"ctx", "deco"} <= spans

    def test_span_fence_on_device_value(self):
        reg = obs.configure()
        x = jnp.ones((8,)) * 2
        with obs.span("fenced", fence_on=x):
            y = x * 3   # noqa: F841 — async dispatch inside the span
        h = reg.histogram("fenced", record_type="span")
        assert h.count == 1 and h.total > 0

    def test_step_timer_carry_protocol(self):
        reg = obs.configure()
        calls = []

        def fn(carry):
            n = 0 if carry is None else carry[0] + 1
            calls.append(n)
            return n, jnp.asarray(float(n))

        timer = obs.StepTimer("unit", warmup=2, iters=3)
        avg = timer.time(fn)
        assert avg >= 0.0
        assert len(calls) == 5          # 2 warmup + 3 timed
        assert timer.last[0] == 4       # state threads through the carry
        h = reg.histogram("step.unit", record_type="span")
        assert h.count == 1

    def test_step_timer_fixed_args_protocol(self):
        obs.configure()
        calls = []

        def fn(x):
            calls.append(1)
            return x * 2

        avg = obs.StepTimer("fx", warmup=1, iters=4).time_call(
            fn, jnp.ones((2,)))
        assert avg >= 0.0 and len(calls) == 5

    def test_step_timer_works_with_telemetry_disabled(self):
        # the bench path must not require configuration
        assert not obs.enabled()
        avg = obs.StepTimer("off", warmup=1, iters=2).time(
            lambda c: (0, jnp.asarray(1.0)))
        assert avg >= 0.0

    def test_fence_handles_trees_and_python_scalars(self):
        obs.fence(jnp.ones((4, 4)))
        obs.fence({"a": jnp.asarray(1.0), "b": 2})
        obs.fence(3.5)
        obs.fence(())   # empty tree: nothing to fence


# ---------------------------------------------------------------------------
# subsystem instrumentation
# ---------------------------------------------------------------------------


class TestStepStamping:
    def test_external_set_step_is_never_clobbered(self):
        """A loop resumed at step 50k that drives obs.set_step itself
        must not be re-stamped 1, 2, 3... by the auto-increment
        fallback when its step fn returns no 'step' key."""
        reg = obs.configure()
        for i in range(3):
            obs.set_step(50000 + i)
            obs.record_step_metrics({"loss": 1.0})   # no 'step' key
            assert reg.step == 50000 + i
        obs.shutdown()

    def test_auto_increment_without_any_declaration(self):
        reg = obs.configure()
        for expect in (1, 2, 3):
            obs.record_step_metrics({"loss": 1.0})
            assert reg.step == expect
        obs.shutdown()

    def test_scaler_records_carry_current_step(self, tmp_path):
        """record_scaler_step runs BEFORE record_step_metrics in the
        canonical loop; its amp.* records and thrash feed must carry
        THIS step's index (adopted from the metrics dict), not the
        previous one."""
        import json

        from apex_tpu.amp.scaler import record_scaler_step

        path = tmp_path / "t.jsonl"
        reg = obs.configure(jsonl_path=str(path))
        record_scaler_step({"loss_scale": 1024.0, "overflow": False,
                            "step": 7})
        assert reg.step == 7
        obs.record_step_metrics({"loss": 1.0, "step": 7})
        obs.shutdown()
        recs = [json.loads(line) for line in open(path)]
        amp_recs = [r for r in recs if r.get("name") == "amp.loss_scale"]
        assert amp_recs and all(r["step"] == 7 for r in amp_recs)


class TestAmpScalerTelemetry:
    def test_scale_change_event_and_counters(self, tmp_path):
        from apex_tpu.amp.scaler import record_scaler_step

        path = tmp_path / "t.jsonl"
        reg = obs.configure(jsonl_path=str(path))
        record_scaler_step({"loss_scale": jnp.asarray(65536.0),
                            "overflow": jnp.asarray(False)})
        record_scaler_step({"loss_scale": jnp.asarray(32768.0),
                            "overflow": jnp.asarray(True)})
        record_scaler_step({"loss_scale": jnp.asarray(32768.0),
                            "overflow": jnp.asarray(False)})
        assert reg.counter("amp.overflow_count").value == 1
        assert reg.counter("amp.skipped_steps").value == 1
        assert reg.gauge("amp.loss_scale").value == 32768.0
        obs.shutdown()
        recs = _records(path)
        events = [r for r in recs if r["type"] == "event"
                  and r["name"] == "amp.loss_scale_change"]
        assert len(events) == 1     # only the actual change, not step 3
        assert events[0]["data"]["old"] == 65536.0
        assert events[0]["data"]["new"] == 32768.0
        assert events[0]["data"]["overflow"] is True


class TestOptimizerNormTelemetry:
    def test_fused_adam_wrapped_state_carries_norms(self):
        from apex_tpu.optimizers import fused_adam
        from apex_tpu.optimizers._common import (
            NormTelemetryState, latest_norms, record_opt_norms)

        tx = fused_adam(lr=1e-3, norm_telemetry=True)
        params = {"w": jnp.ones((4,), jnp.float32)}
        state = tx.init(params)
        assert isinstance(state, NormTelemetryState)
        grads = {"w": jnp.full((4,), 2.0, jnp.float32)}
        _, state = tx.update(grads, state, params)
        norms = latest_norms(state)
        assert norms["grad_norm"] == pytest.approx(4.0)   # sqrt(4*2^2)
        assert norms["update_norm"] > 0
        assert norms["param_norm"] == pytest.approx(2.0)  # sqrt(4*1)
        assert norms["update_to_param_ratio"] == pytest.approx(
            norms["update_norm"] / norms["param_norm"], rel=1e-5)
        reg = obs.configure()
        record_opt_norms(state)
        assert reg.gauge("optim.grad_norm").value == pytest.approx(4.0)

    def test_fused_lamb_norm_telemetry_flag(self):
        from apex_tpu.optimizers import fused_lamb
        from apex_tpu.optimizers._common import (
            NormTelemetryState, latest_norms)

        tx = fused_lamb(lr=1e-3, norm_telemetry=True)
        params = {"w": jnp.ones((3,), jnp.float32)}
        state = tx.init(params)
        _, state = tx.update({"w": jnp.ones((3,), jnp.float32)},
                             state, params)
        assert isinstance(state, NormTelemetryState)
        assert latest_norms(state)["grad_norm"] > 0

    def test_unwrapped_state_by_default(self):
        from apex_tpu.optimizers import fused_adam
        from apex_tpu.optimizers._common import latest_norms
        from apex_tpu.optimizers.fused_adam import AdamState

        state = fused_adam(lr=1e-3).init({"w": jnp.ones((2,))})
        assert isinstance(state, AdamState)
        assert latest_norms(state) is None


class TestCollectivesTelemetry:
    def test_pmap_psum_counts_calls_and_bytes(self):
        from apex_tpu.utils.collectives import grad_sum

        reg = obs.configure()
        n = jax.local_device_count()
        x = jnp.arange(float(n * 4)).reshape(n, 4)
        out = jax.pmap(lambda v: grad_sum(v, "dp"), axis_name="dp")(x)
        np.testing.assert_allclose(
            np.asarray(out)[0], np.asarray(x).sum(0))
        # trace-time accounting: one psum emitted for the one f32[4] leaf
        assert reg.counter("collectives.psum.calls").value >= 1
        assert reg.counter("collectives.psum.bytes").value >= 4 * 4

    def test_flag_or_counts_pmax(self):
        from apex_tpu.utils.collectives import flag_or

        reg = obs.configure()
        n = jax.local_device_count()
        flags = jnp.zeros((n,), bool).at[0].set(True)
        out = jax.pmap(lambda f: flag_or(f, "dp"), axis_name="dp")(flags)
        assert bool(np.asarray(out).all())
        assert reg.counter("collectives.pmax.calls").value >= 1

    def test_counted_nonpsum_family(self):
        """all_gather / ppermute / all_to_all / psum_scatter were
        invisible to collectives.* until the counted wrappers — the
        comm/ and ring paths route through these."""
        from apex_tpu.utils import collectives as coll

        reg = obs.configure()
        n = jax.local_device_count()
        x = jnp.arange(float(n * 4)).reshape(n, 4)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def f(v):
            g = coll.all_gather(v, "dp", axis=0, tiled=True)
            p = coll.ppermute(v, "dp", perm)
            s = coll.psum_scatter(g, "dp", scatter_dimension=0,
                                  tiled=True)
            a = coll.all_to_all(g.reshape(n, -1), "dp", 0, 0, tiled=True)
            return g.sum() + p.sum() + s.sum() + a.sum()

        jax.pmap(f, axis_name="dp")(x)
        for kind, nbytes in (("all_gather", 4 * 4),
                             ("ppermute", 4 * 4),
                             ("psum_scatter", n * 4 * 4),
                             ("all_to_all", n * 4 * 4)):
            assert reg.counter(f"collectives.{kind}.calls").value >= 1, kind
            assert reg.counter(f"collectives.{kind}.bytes").value >= nbytes, \
                kind

    def test_ring_counters_and_hop_invariant(self):
        """collectives.ring.*: each ring loop books one call and exactly
        n−1 hops (the dryrun tp_overlap acceptance invariant)."""
        import functools

        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.ops import collective_matmul as cm

        reg = obs.configure()
        n = jax.local_device_count()
        mesh = Mesh(np.asarray(jax.devices()), ("tp",))
        c0 = reg.counter("collectives.ring.calls").value
        h0 = reg.counter("collectives.ring.hops").value
        jax.shard_map(
            functools.partial(cm.ring_all_gather, axis_name="tp"),
            mesh=mesh, in_specs=P("tp"), out_specs=P())(
                jnp.arange(float(n * 2)).reshape(n * 2, 1))
        calls = reg.counter("collectives.ring.calls").value - c0
        hops = reg.counter("collectives.ring.hops").value - h0
        assert calls == 1 and hops == n - 1
        assert reg.counter("collectives.ring.bytes").value > 0


class TestPipelineTelemetry:
    def test_schedule_bubble_accounting(self):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            record_schedule_telemetry)

        reg = obs.configure()
        record_schedule_telemetry("1f1b", n_micro=8, n_stages=4, ticks=11)
        assert reg.counter("pipeline.1f1b.invocations").value == 1
        assert reg.gauge("pipeline.1f1b.bubble_ticks_per_stage").value == 3
        assert reg.gauge("pipeline.1f1b.bubble_fraction").value == \
            pytest.approx(3 / 11)
        assert reg.gauge("pipeline.1f1b.ticks").value == 11

    def test_megatron_timers_feed_registry(self):
        from apex_tpu.transformer.pipeline_parallel._timers import Timer

        reg = obs.configure()
        t = Timer("fwd")
        t.start()
        t.stop()
        h = reg.histogram("pipeline.timer.fwd", record_type="span")
        assert h.count == 1 and h.total >= 0


# ---------------------------------------------------------------------------
# the acceptance smoke loop: tiny AMP train loop -> JSONL -> report tool
# ---------------------------------------------------------------------------


def _load_report():
    spec = importlib.util.spec_from_file_location(
        "telemetry_report",
        os.path.join(REPO, "tools", "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_smoke_loop(path, steps=3):
    """Tiny GPT-ish AMP-O2 train loop (amp.frontend path — runs on any
    jax) with telemetry on: spans around each step, scaler + norm + step
    metrics recorded at the step boundary."""
    from apex_tpu.amp.frontend import make_train_step
    from apex_tpu.amp.scaler import record_scaler_step
    from apex_tpu.optimizers import fused_adam

    obs.configure(jsonl_path=str(path))
    rng = np.random.RandomState(0)
    params = {"emb": jnp.asarray(rng.randn(64, 16) * 0.02, jnp.float32),
              "w": jnp.asarray(rng.randn(16, 64) * 0.02, jnp.float32)}
    tokens = jnp.asarray(rng.randint(0, 64, (4, 8)), jnp.int32)

    def loss_fn(p, toks):
        h = p["emb"][toks]                      # [b, s, d]
        logits = (h @ p["w"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        tgt = jnp.roll(toks, -1, axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, tgt[..., None], axis=-1))

    init, step = make_train_step(loss_fn, fused_adam(lr=1e-3), "O2",
                                 norm_telemetry=True)
    state = init(params)
    for _ in range(steps):
        with obs.span("train_step"):
            state, metrics = step(state, tokens)
            obs.fence(metrics["loss"])   # span measures the step, not dispatch
        record_scaler_step(metrics)
        obs.record_step_metrics(metrics)
    obs.shutdown()
    return state


def test_smoke_train_loop_telemetry_jsonl(tmp_path):
    """The ISSUE 1 acceptance loop: telemetry enabled -> the JSONL file
    contains loss-scale, grad-norm and span records, and
    tools/telemetry_report.py summarizes them."""
    path = tmp_path / "telemetry.jsonl"
    _run_smoke_loop(path, steps=3)
    recs = _records(path)
    assert all("schema_version" in r for r in recs)
    kinds = {(r.get("type"), r.get("name")) for r in recs}
    assert ("gauge", "amp.loss_scale") in kinds          # loss-scale
    assert ("gauge", "train.grad_norm") in kinds         # grad-norm
    assert ("span", "train_step") in kinds               # spans
    assert ("gauge", "train.loss") in kinds
    assert sum(1 for r in recs
               if r.get("type") == "span"
               and r.get("name") == "train_step") == 3

    report = _load_report()
    out = io.StringIO()
    report.print_report(
        report.summarize(report.load_records([str(path)], out=out)),
        out=out)
    text = out.getvalue()
    assert "train_step" in text
    assert "amp.loss_scale" in text
    assert "train.grad_norm" in text


def test_smoke_loop_disabled_takes_noop_path(tmp_path):
    """Same loop with telemetry disabled: the per-step overhead is the
    no-op fast path — asserted structurally (nothing configured, metric
    helpers still hand out the shared singleton mid-loop), not by
    wall-clock."""
    from apex_tpu.amp.frontend import make_train_step
    from apex_tpu.amp.scaler import record_scaler_step
    from apex_tpu.optimizers import fused_adam

    assert not obs.enabled()
    params = {"w": jnp.ones((8, 8), jnp.float32)}
    x = jnp.ones((2, 8), jnp.float32)
    init, step = make_train_step(
        lambda p, xx: jnp.mean((xx @ p["w"]) ** 2),
        fused_adam(lr=1e-3), "O2")
    state = init(params)
    for _ in range(2):
        with obs.span("train_step"):
            state, metrics = step(state, x)
        record_scaler_step(metrics)
        obs.record_step_metrics(metrics)
        assert obs.counter("anything") is NOOP_METRIC
    assert not obs.enabled()
    # and no stray telemetry file appeared
    assert list(tmp_path.iterdir()) == []


def test_gpt_smoke_train_loop_telemetry(tmp_path):
    """Full make_gpt_train_step variant of the acceptance loop (tiny
    GPT-125M-family config on CPU).  The mesh-based model stack needs
    jax.shard_map/typeof; skip on runtimes without them (the
    amp.frontend smoke loop above covers the telemetry path there)."""
    try:
        from apex_tpu.models.config import gpt_125m
        from apex_tpu.models.gpt import make_gpt_train_step
    except Exception as e:   # pragma: no cover - old-jax environments
        pytest.skip(f"GPT stack unavailable on this jax: {e}")
    from apex_tpu.amp.scaler import record_scaler_step
    from apex_tpu.optimizers import fused_adam

    path = tmp_path / "telemetry.jsonl"
    obs.configure(jsonl_path=str(path))
    cfg = gpt_125m(num_layers=1, hidden_size=32, num_attention_heads=2,
                   vocab_size=128, max_position_embeddings=16)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)),
                         jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)),
                         jnp.int32)
    try:
        init, step = make_gpt_train_step(
            cfg, fused_adam(lr=1e-4), "O2", norm_telemetry=True)
        state = init(jax.random.PRNGKey(0))
        for _ in range(2):
            with obs.span("train_step"):
                state, metrics = step(state, tokens, labels)
            record_scaler_step(metrics)
            obs.record_step_metrics(metrics)
    except AttributeError as e:   # pragma: no cover - old-jax environments
        pytest.skip(f"GPT stack unavailable on this jax: {e}")
    obs.shutdown()
    kinds = {(r.get("type"), r.get("name")) for r in _records(path)}
    assert ("gauge", "amp.loss_scale") in kinds
    assert ("gauge", "train.grad_norm") in kinds
    assert ("span", "train_step") in kinds
