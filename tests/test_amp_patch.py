"""O1 per-op cast patching tests (amp/patch.py — the trace-time analog
of the reference's monkey-patch engine, apex/amp/wrap.py)."""

import jax
import jax.numpy as jnp

from apex_tpu.amp.frontend import make_train_step
from apex_tpu.amp.patch import amp_patch_scope
from apex_tpu.optimizers import fused_sgd


class TestPatchScope:
    def test_matmul_casts_down_inside_scope(self):
        a = jnp.ones((4, 4), jnp.float32)
        with amp_patch_scope(jnp.bfloat16):
            out = jnp.matmul(a, a)
        assert out.dtype == jnp.bfloat16
        assert jnp.matmul(a, a).dtype == jnp.float32  # restored

    def test_softmax_casts_up_inside_scope(self):
        x = jnp.ones((4, 4), jnp.bfloat16)
        with amp_patch_scope(jnp.bfloat16):
            out = jax.nn.softmax(x)
        assert out.dtype == jnp.float32
        assert jax.nn.softmax(x).dtype == jnp.bfloat16  # restored

    def test_exception_safe_restore(self):
        orig = jnp.matmul
        try:
            with amp_patch_scope():
                raise RuntimeError("boom")
        except RuntimeError:
            pass
        assert jnp.matmul is orig

    def test_reentrant(self):
        a = jnp.ones((2, 2), jnp.float32)
        with amp_patch_scope(jnp.bfloat16):
            with amp_patch_scope(jnp.bfloat16):
                out = jnp.matmul(a, a)
            # inner exit must not unpatch the outer scope
            out2 = jnp.matmul(a, a)
        assert out.dtype == jnp.bfloat16
        assert out2.dtype == jnp.bfloat16
        assert jnp.matmul(a, a).dtype == jnp.float32

    def test_non_float_args_pass_through(self):
        with amp_patch_scope(jnp.bfloat16):
            out = jnp.cumsum(jnp.arange(4))
        assert out.dtype == jnp.int32


class TestO1StepUsesPatch:
    def test_o1_matmuls_run_in_compute_dtype(self):
        """Inside an O1 step the (undecorated) user matmul must execute
        in the compute dtype; O0 must keep fp32."""
        seen = {}

        def loss_fn(p, x):
            y = jnp.matmul(x, p["w"])
            seen.setdefault("dtype", y.dtype)
            return jnp.mean(jax.nn.softmax(y) ** 2)

        params = {"w": jnp.ones((8, 8), jnp.float32)}
        x = jnp.ones((2, 8), jnp.float32)

        from apex_tpu.amp.policy import _effective, policy_for_opt_level

        expect = _effective(policy_for_opt_level("O1").compute_dtype)
        init, step = make_train_step(loss_fn, fused_sgd(lr=0.1), "O1")
        step(init(params), x)
        assert seen["dtype"] == expect  # fp16 (bf16 on real TPU)

        seen.clear()
        init0, step0 = make_train_step(loss_fn, fused_sgd(lr=0.1), "O0")
        step0(init0(params), x)
        assert seen["dtype"] == jnp.float32
