"""Bottleneck + spatial parallelism tests.

Mirrors the reference halo/bottleneck tests
(apex/contrib/test/bottleneck/, "halo exchanger" CI suite): the
spatially-split block must produce bitwise-close outputs and grads to
the unsplit block.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from apex_tpu.contrib.bottleneck import (
    bottleneck_forward,
    init_bottleneck_params,
    spatial_bottleneck_forward,
)
from apex_tpu.contrib.peer_memory import HaloExchanger1d, halo_exchange_1d


def spatial_mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("spatial",))


class TestHaloExchange:
    def test_matches_manual_neighbor_slices(self):
        n = 4
        mesh = spatial_mesh(n)
        x = jnp.arange(4 * 8 * 2 * 3, dtype=jnp.float32).reshape(4, 8, 2, 3)
        # shard H (=8) into 4 shards of 2 rows
        xs = x.transpose(1, 0, 2, 3)  # put H first for sharding clarity

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(None, "spatial"),
            out_specs=P(None, "spatial"))
        def run(xloc):
            # xloc [n, 2, w, c]
            return halo_exchange_1d(xloc, 1, "spatial", dim=1)[:, 1:-1]

        out = run(x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def test_halos_filled_and_edges_zero(self):
        n = 4
        mesh = spatial_mesh(n)
        x = jnp.arange(1 * 8 * 2 * 1, dtype=jnp.float32).reshape(1, 8, 2, 1)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(None, "spatial"),
            out_specs=P(None, "spatial"))
        def run(xloc):
            h = halo_exchange_1d(xloc, 1, "spatial", dim=1)
            return h.reshape(1, -1, 2, 1)  # [1, 4*(2+2), 2, 1] stacked

        out = np.asarray(run(x)).reshape(4, 4, 2, 1)
        full = np.asarray(x).reshape(4, 2, 2, 1)  # global rows per shard
        for r in range(4):
            lo = np.zeros((1, 2, 1)) if r == 0 else full[r - 1, -1:]
            hi = np.zeros((1, 2, 1)) if r == 3 else full[r + 1, :1]
            np.testing.assert_array_equal(out[r, :1], lo, f"rank {r} lo")
            np.testing.assert_array_equal(out[r, 1:3], full[r])
            np.testing.assert_array_equal(out[r, 3:], hi, f"rank {r} hi")

    def test_exchanger_class_shim(self):
        n = 2
        mesh = spatial_mesh(n)
        x = jnp.arange(1 * 12 * 2 * 1, dtype=jnp.float32).reshape(1, 12, 2, 1)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(None, "spatial"),
            out_specs=P(None, "spatial"))
        def run(xloc):
            # xloc already carries 1-row halo slots at each edge
            ex = HaloExchanger1d("spatial", 1)
            return ex(xloc)

        out = run(x)
        assert out.shape == x.shape


class TestSpatialBottleneck:
    def _setup(self, stride=1, cin=8, cmid=4, cout=8, h=16, w=8, b=2,
               seed=0):
        params = init_bottleneck_params(
            jax.random.PRNGKey(seed), cin, cmid, cout, stride)
        # non-trivial frozen BN stats
        rs = np.random.RandomState(seed)
        for bn in ("bn1", "bn2", "bn3", "bn_ds"):
            if bn in params:
                c = params[bn]["weight"].shape[0]
                params[bn]["running_mean"] = jnp.asarray(
                    rs.randn(c) * 0.1, jnp.float32)
                params[bn]["running_var"] = jnp.asarray(
                    1.0 + 0.1 * rs.rand(c), jnp.float32)
                params[bn]["weight"] = jnp.asarray(
                    1.0 + 0.1 * rs.randn(c), jnp.float32)
                params[bn]["bias"] = jnp.asarray(
                    0.1 * rs.randn(c), jnp.float32)
        x = jnp.asarray(rs.randn(b, h, w, cin), jnp.float32)
        return params, x

    def test_spatial_matches_unsplit(self):
        params, x = self._setup()
        mesh = spatial_mesh(4)
        ref = bottleneck_forward(params, x)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(), P(None, "spatial")),
            out_specs=P(None, "spatial"))
        def run(p, xloc):
            return spatial_bottleneck_forward(p, xloc)

        out = run(params, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)

    def test_spatial_matches_unsplit_with_downsample_stride(self):
        params, x = self._setup(stride=2, cin=8, cmid=4, cout=16)
        mesh = spatial_mesh(4)
        ref = bottleneck_forward(params, x, stride=2)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(), P(None, "spatial")),
            out_specs=P(None, "spatial"))
        def run(p, xloc):
            return spatial_bottleneck_forward(p, xloc, stride=2)

        out = run(params, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)

    def test_spatial_grads_match_unsplit(self):
        params, x = self._setup()
        mesh = spatial_mesh(4)

        def ref_loss(p, xx):
            return jnp.sum(bottleneck_forward(p, xx) ** 2)

        ref_gp, ref_gx = jax.grad(ref_loss, argnums=(0, 1))(params, x)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(), P(None, "spatial")),
            out_specs=(P(), P(None, "spatial")))
        def run(p, xloc):
            def loss(pp, xl):
                return jnp.sum(spatial_bottleneck_forward(pp, xl) ** 2)
            # SPMD-AD: p is replicated (non-varying), so jax inserts the
            # cross-shard psum on its cotangent automatically
            gp, gx = jax.grad(loss, argnums=(0, 1))(p, xloc)
            return gp, gx

        gp, gx = run(params, x)
        np.testing.assert_allclose(
            np.asarray(gx), np.asarray(ref_gx), atol=1e-4, rtol=1e-4)
        for name in ("conv1", "conv2", "conv3"):
            np.testing.assert_allclose(
                np.asarray(gp[name]), np.asarray(ref_gp[name]),
                atol=1e-4, rtol=1e-4, err_msg=name)
