"""ISSUE 7 coverage: mergeable sketches, OpenMetrics exposition, and
the live exporter lifecycle.

Covers: LogBucketSketch algebra (exact merge — associative,
commutative, count-preserving; bounded-relative-error quantiles;
serialization round-trip; parameter-mismatch refusal), the registry's
Sketch metric kind (tags as dimensions, flush emits ``sketch``
records, no per-observation record), the OpenMetrics render/parse pair
(the parser IS the in-test line-format validator), the exporter's
endpoints (``/metrics`` parseable, ``/healthz`` flipping 503 on a
detector firing, ``/statusz``, 404), teardown (thread exits on
shutdown, configure re-entry closes the old server), and the
zero-overhead contract (a fresh unconfigured process never imports the
exporter module or starts its thread — asserted from a subprocess).
"""

import contextlib
import json
import logging
import math
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import pytest

import apex_tpu.observability as obs
from apex_tpu.observability import openmetrics
from apex_tpu.observability.metrics import NOOP_METRIC
from apex_tpu.observability.sketches import LogBucketSketch


@pytest.fixture(autouse=True)
def _clean_telemetry():
    yield
    obs.shutdown()


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode("utf-8")


@contextlib.contextmanager
def _capture_warnings():
    """The apex_tpu logger is propagate=False (its own stderr handler),
    so caplog never sees it — attach a capturing handler directly."""
    records = []

    class _H(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = _H(level=logging.WARNING)
    logger = logging.getLogger("apex_tpu")
    logger.addHandler(h)
    try:
        yield records
    finally:
        logger.removeHandler(h)


# ---------------------------------------------------------------------------
# the sketch
# ---------------------------------------------------------------------------


class TestLogBucketSketch:
    def test_count_total_min_max_exact(self):
        s = LogBucketSketch()
        vals = [0.5, 12.0, 12.0, 700.0, 0.003, 1e9]
        for v in vals:
            s.observe(v)
        assert s.count == len(vals)
        assert s.total == pytest.approx(sum(vals))
        assert s.min == min(vals) and s.max == max(vals)

    def test_quantile_relative_error_bound(self):
        s = LogBucketSketch()
        import random

        rng = random.Random(0)
        vals = sorted(rng.uniform(0.01, 5e4) for _ in range(5000))
        for v in vals:
            s.observe(v)
        for q in (0.5, 0.95, 0.99):
            exact = vals[math.ceil(q * len(vals)) - 1]
            got = s.quantile(q)
            # reported value = bucket upper bound: >= exact, and within
            # one growth factor of it
            assert exact <= got <= exact * s.growth * (1 + 1e-9)

    def test_overflow_bucket_reports_exact_max(self):
        s = LogBucketSketch(max_value=100.0)
        s.observe(123456.0)
        assert s.quantile(0.99) == 123456.0

    def test_merge_is_exact_associative_commutative(self):
        import random

        rng = random.Random(1)
        vals = [rng.uniform(1e-4, 1e6) for _ in range(900)]
        full = LogBucketSketch()
        parts = [LogBucketSketch() for _ in range(3)]
        for i, v in enumerate(vals):
            full.observe(v)
            parts[i % 3].observe(v)
        a, b, c = parts
        # (a+b)+c
        abc = LogBucketSketch.merged(
            [LogBucketSketch.from_dict(a.to_dict()),
             LogBucketSketch.from_dict(b.to_dict()),
             LogBucketSketch.from_dict(c.to_dict())])
        # c+(b+a): different order
        cba = LogBucketSketch.merged(
            [LogBucketSketch.from_dict(c.to_dict()),
             LogBucketSketch.from_dict(b.to_dict()),
             LogBucketSketch.from_dict(a.to_dict())])
        for m in (abc, cba):
            assert m.count == full.count                 # exact counts
            assert m.counts == full.counts               # bucket-exact
            assert m.total == pytest.approx(full.total)
            for q in (0.01, 0.5, 0.95, 0.99, 1.0):
                assert m.quantile(q) == full.quantile(q)  # exactly

    def test_merge_refuses_parameter_mismatch(self):
        a = LogBucketSketch(growth=1.04)
        b = LogBucketSketch(growth=1.10)
        with pytest.raises(ValueError, match="parameter mismatch"):
            a.merge(b)

    def test_serialization_round_trip(self):
        s = LogBucketSketch()
        for v in (0.1, 3.0, 3.0, 900.0):
            s.observe(v)
        r = LogBucketSketch.from_dict(
            json.loads(json.dumps(s.to_dict())))
        assert r.counts == s.counts and r.count == s.count
        assert r.min == s.min and r.max == s.max
        assert r.quantile(0.5) == s.quantile(0.5)

    def test_empty_sketch(self):
        s = LogBucketSketch()
        assert s.quantile(0.5) == 0.0
        assert s.summary()["count"] == 0
        assert LogBucketSketch.merged([]) is None

    def test_nan_is_dropped_not_poisoning(self):
        s = LogBucketSketch()
        s.observe(float("nan"))
        s.observe(2.0)
        assert s.count == 1 and s.max == 2.0

    def test_invalid_parameters_raise(self):
        with pytest.raises(ValueError):
            LogBucketSketch(min_value=-1.0)
        with pytest.raises(ValueError):
            LogBucketSketch(growth=1.0)
        with pytest.raises(ValueError):
            LogBucketSketch(min_value=10.0, max_value=1.0)


# ---------------------------------------------------------------------------
# the registry metric kind
# ---------------------------------------------------------------------------


class TestRegistrySketch:
    def test_disabled_returns_noop_singleton(self):
        assert obs.sketch("serving.ttft_ms") is NOOP_METRIC
        obs.sketch("serving.ttft_ms").observe(1.0)   # inert

    def test_tags_are_a_dimension(self, tmp_path):
        obs.configure(jsonl_path=str(tmp_path / "t.jsonl"))
        a = obs.sketch("s", {"slo_class": "a"})
        b = obs.sketch("s", {"slo_class": "b"})
        assert a is not b
        assert obs.sketch("s", {"slo_class": "a"}) is a
        a.observe(1.0)
        assert a.summary()["count"] == 1
        assert b.summary()["count"] == 0

    def test_observations_emit_no_records_flush_emits_state(
            self, tmp_path):
        path = tmp_path / "t.jsonl"
        reg = obs.configure(jsonl_path=str(path))
        sk = obs.sketch("serving.tpot_ms", {"slo_class": "x"})
        for i in range(1000):
            sk.observe(float(i + 1))
        reg.flush()
        recs = [json.loads(l) for l in open(path)]
        # a thousand observations, zero per-observation records
        assert not [r for r in recs if r["type"] == "observe"
                    and r["name"] == "serving.tpot_ms"]
        sketches = [r for r in recs if r["type"] == "sketch"]
        assert len(sketches) == 1
        rec = sketches[0]
        assert rec["tags"] == {"slo_class": "x"}
        assert rec["schema_version"] == 3
        restored = LogBucketSketch.from_dict(rec["value"])
        assert restored.count == 1000
        assert restored.quantile(0.5) == sk.quantile(0.5)

    def test_histogram_summary_reports_truncation(self, tmp_path):
        obs.configure(jsonl_path=str(tmp_path / "t.jsonl"))
        h = obs.histogram("h")
        for i in range(10):
            h.observe(float(i))
        s = h.summary()
        assert s["observed"] == 10 and s["retained"] == 10
        assert s["truncated"] is False
        for i in range(h.WINDOW + 5):
            h.observe(float(i))
        s = h.summary()
        assert s["observed"] == h.WINDOW + 15
        assert s["retained"] == h.WINDOW
        assert s["truncated"] is True


# ---------------------------------------------------------------------------
# OpenMetrics render/parse
# ---------------------------------------------------------------------------


class TestOpenMetrics:
    def _snapshot(self):
        sk = LogBucketSketch()
        for v in (1.0, 5.0, 5.0, 80.0, 2000.0):
            sk.observe(v)
        return [
            {"kind": "counter", "name": "serving.goodput.met",
             "tags": {"slo_class": "interactive"}, "value": 7},
            {"kind": "gauge", "name": "serving.queue_depth",
             "tags": None, "value": 3.0},
            {"kind": "sketch", "name": "serving.ttft_ms",
             "tags": {"slo_class": "interactive"}, "count": sk.count,
             "sum": sk.total, "buckets": sk.cumulative_buckets()},
            {"kind": "summary", "name": "serving.prefill_ms",
             "tags": None, "observed": 12, "retained": 12,
             "truncated": False, "sum": 40.0, "p50": 3.0, "p95": 9.0,
             "max": 9.5},
        ], sk

    def test_render_parses_back(self):
        snap, sk = self._snapshot()
        text = openmetrics.render(snap)
        parsed = openmetrics.parse(text)   # strict: raises = fail
        assert parsed["eof"]
        assert parsed["types"]["serving_goodput_met"] == "counter"
        assert parsed["types"]["serving_ttft_ms"] == "histogram"
        assert parsed["types"]["serving_prefill_ms"] == "summary"
        assert openmetrics.sample_value(
            parsed, "serving_goodput_met_total",
            {"slo_class": "interactive"}) == 7
        assert openmetrics.sample_value(
            parsed, "serving_queue_depth") == 3.0
        assert openmetrics.sample_value(
            parsed, "serving_ttft_ms_count") == sk.count

    def test_scraped_quantiles_match_sketch_exactly(self):
        snap, sk = self._snapshot()
        parsed = openmetrics.parse(openmetrics.render(snap))
        buckets = openmetrics.bucket_series(
            parsed, "serving_ttft_ms", {"slo_class": "interactive"})
        for q in (0.5, 0.95):
            assert openmetrics.histogram_quantile(buckets, q) \
                == sk.quantile(q)

    def test_parser_rejects_malformed(self):
        with pytest.raises(ValueError):
            openmetrics.parse("this is not a metric line{")
        with pytest.raises(ValueError):
            openmetrics.parse("# EOF\ntrailing_metric 1\n")

    @pytest.mark.parametrize("value", [
        'a"b\\c\nd',
        "win\\network",     # backslash adjacent to 'n': a sequential
        "\\\\n",            # unescape pass would corrupt these
        "trail\\",
    ])
    def test_label_escaping_round_trips(self, value):
        text = openmetrics.render([
            {"kind": "gauge", "name": "g",
             "tags": {"k": value}, "value": 1.0}])
        parsed = openmetrics.parse(text)
        assert parsed["samples"][0][1]["k"] == value

    def test_brace_in_label_value_parses(self):
        # any string is a valid slo_class — a '}' inside a quoted label
        # value must not end the label block early
        text = openmetrics.render([
            {"kind": "counter", "name": "c",
             "tags": {"slo_class": "a}b{c"}, "value": 2}])
        parsed = openmetrics.parse(text)
        assert openmetrics.sample_value(
            parsed, "c_total", {"slo_class": "a}b{c"}) == 2

    def test_name_sanitization(self):
        assert openmetrics.sanitize_name("serving.ttft_ms") \
            == "serving_ttft_ms"
        assert openmetrics.sanitize_name("9lives") == "_9lives"


# ---------------------------------------------------------------------------
# exporter lifecycle
# ---------------------------------------------------------------------------


class TestExporterLifecycle:
    def test_endpoints_serve(self):
        reg = obs.configure(export_port=0)
        url = reg.exporter.url
        obs.counter("c").inc(3)
        obs.sketch("serving.e2e_ms", {"slo_class": "x"}).observe(10.0)
        status, text = _get(url + "/metrics")
        assert status == 200
        parsed = openmetrics.parse(text)       # the line-format validator
        assert parsed["eof"]
        assert openmetrics.sample_value(parsed, "c_total") == 3
        assert openmetrics.sample_value(
            parsed, "serving_e2e_ms_count", {"slo_class": "x"}) == 1
        status, body = _get(url + "/healthz")
        assert status == 200 and json.loads(body)["status"] == "ok"
        status, body = _get(url + "/statusz")
        doc = json.loads(body)
        assert status == 200 and doc["summary"]["counters"]["c"] == 3
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(url + "/nope")
        assert e.value.code == 404

    def test_healthz_flips_on_detector_firing(self):
        reg = obs.configure(export_port=0)
        url = reg.exporter.url
        # drive the SLO-violation detector to a firing: 8 straight
        # missed-deadline completions exceed the 25% miss-rate window
        for _ in range(8):
            reg.detectors.feed_slo("interactive", met=False)
        assert any(a.kind == "slo_violation"
                   for a in reg.detectors.anomalies)
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(url + "/healthz")
        assert e.value.code == 503
        doc = json.loads(e.value.read().decode())
        assert doc["status"] == "unhealthy"
        assert "slo_violation" in doc["kinds"]

    def test_shutdown_stops_thread_and_socket(self):
        from apex_tpu.observability.exporter import THREAD_NAME

        reg = obs.configure(export_port=0)
        url = reg.exporter.url
        assert any(t.name == THREAD_NAME for t in threading.enumerate())
        obs.shutdown()
        assert not any(t.name == THREAD_NAME
                       for t in threading.enumerate())
        with pytest.raises(Exception):
            _get(url + "/metrics", timeout=1)

    def test_reconfigure_closes_previous_exporter(self):
        from apex_tpu.observability.exporter import THREAD_NAME

        reg1 = obs.configure(export_port=0)
        port1 = reg1.exporter.port
        reg2 = obs.configure(export_port=0)
        assert reg2.exporter.port != 0
        threads = [t for t in threading.enumerate()
                   if t.name == THREAD_NAME]
        assert len(threads) == 1
        with pytest.raises(Exception):
            _get(f"http://127.0.0.1:{port1}/metrics", timeout=1)

    def test_env_var_enables_export(self):
        from apex_tpu.observability.metrics import configure_from_env

        reg = configure_from_env({"APEX_TPU_TELEMETRY_PORT": "0"})
        assert reg is not None and reg.exporter is not None
        status, text = _get(reg.exporter.url + "/metrics")
        assert status == 200 and openmetrics.parse(text)["eof"]

    def test_env_var_malformed_warns_not_crashes(self):
        from apex_tpu.observability.metrics import configure_from_env

        with _capture_warnings() as warnings:
            reg = configure_from_env(
                {"APEX_TPU_TELEMETRY_PORT": "not-a-port"})
        # the malformed port falls back to "no export"; with no other
        # output requested telemetry stays off entirely
        assert reg is None
        assert any("APEX_TPU_TELEMETRY_PORT" in w for w in warnings)

    def test_scrape_error_does_not_kill_server(self):
        reg = obs.configure(export_port=0)
        url = reg.exporter.url

        # sabotage one snapshot: a metric whose value read raises must
        # 500 that request, not the server
        from apex_tpu.observability.metrics import Counter

        class _Bomb(Counter):
            @property
            def value(self):
                raise RuntimeError("boom")

        bomb = _Bomb.__new__(_Bomb)
        bomb.name, bomb.tags = "bomb", None
        reg._metrics[("boom", "bomb", ())] = bomb
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(url + "/statusz")
        assert e.value.code == 500
        del reg._metrics[("boom", "bomb", ())]
        status, _ = _get(url + "/metrics")
        assert status == 200


UNCONFIGURED_SNIPPET = """
import sys, threading
import apex_tpu.observability as obs
import apex_tpu.serving.engine                     # the instrumented user
assert obs.registry() is None
from apex_tpu.observability.metrics import NOOP_METRIC
assert obs.sketch("s") is NOOP_METRIC              # no sketch allocation
assert "apex_tpu.observability.exporter" not in sys.modules, (
    "exporter module imported on the unconfigured path")
names = [t.name for t in threading.enumerate()]
assert not any(n == "apex-tpu-telemetry-exporter" for n in names), names
print("CLEAN")
"""


def test_unconfigured_process_never_starts_exporter():
    """The zero-overhead contract, asserted from a fresh process: no
    exporter import, no server thread, no sketch allocation — even
    with the serving engine (the heaviest instrumented user)
    imported."""
    out = subprocess.run(
        [sys.executable, "-c", UNCONFIGURED_SNIPPET],
        capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr
    assert "CLEAN" in out.stdout
