"""utils/logging coverage (ISSUE 1 satellite): RankInfoFormatter with
and without parallel_state, get_logger child-namespacing,
set_logging_level round-trip, and the print_rank_0 backendless guard."""

import logging as pylogging

import jax

import apex_tpu.utils.logging as alog


def _format(fmt="%(rank_info)s|%(message)s", msg="hello"):
    formatter = alog.RankInfoFormatter(fmt)
    record = pylogging.LogRecord(
        "apex_tpu.test", pylogging.INFO, __file__, 1, msg, None, None)
    return formatter.format(record)


class TestRankInfoFormatter:
    def test_without_parallel_state(self):
        # conftest: single process on the virtual CPU mesh
        out = _format()
        assert out.endswith("|hello")
        assert "[host 0/1]" in out

    def test_with_parallel_state(self, monkeypatch):
        from apex_tpu.transformer import parallel_state

        monkeypatch.setattr(
            parallel_state, "model_parallel_is_initialized", lambda: True)
        monkeypatch.setattr(
            parallel_state, "get_rank_info", lambda: "(tp 0/2, pp 1/2)")
        out = _format()
        assert "(tp 0/2, pp 1/2)" in out
        assert out.endswith("|hello")

    def test_survives_backendless_jax(self, monkeypatch):
        def boom():
            raise RuntimeError("no reachable backend")

        monkeypatch.setattr(jax, "process_index", boom)
        out = _format()   # rank info degrades, the message survives
        assert out.endswith("|hello")
        assert "host" not in out


class TestLoggerApi:
    def test_get_logger_child_namespacing(self):
        root = alog.get_logger()
        child = alog.get_logger("amp")
        assert root.name == "apex_tpu"
        assert child.name == "apex_tpu.amp"
        assert child.parent is root
        # same name -> same logger object (logging module registry)
        assert alog.get_logger("amp") is child
        assert alog.get_logger() is root

    def test_root_has_single_stream_handler(self):
        root = alog.get_logger()
        assert len(root.handlers) == 1
        assert isinstance(root.handlers[0].formatter,
                          alog.RankInfoFormatter)
        assert root.propagate is False

    def test_set_logging_level_round_trip(self):
        root = alog.get_logger()
        old = root.level
        try:
            alog.set_logging_level(pylogging.DEBUG)
            assert root.level == pylogging.DEBUG
            assert alog.get_logger("child").getEffectiveLevel() == \
                pylogging.DEBUG
            alog.set_logging_level(old)
            assert root.level == old
        finally:
            root.setLevel(old)


class TestPrintRank0:
    def test_prints_on_rank_0(self, capsys):
        alog.print_rank_0("visible")
        assert "visible" in capsys.readouterr().out

    def test_degrades_without_backend(self, monkeypatch, capsys):
        """ISSUE 1 satellite: jax.process_index raising (dead tunnel,
        uninitialized backend) must fall back to printing, the same
        guard RankInfoFormatter.format already applies."""

        def boom():
            raise RuntimeError("backend unreachable")

        monkeypatch.setattr(jax, "process_index", boom)
        alog.print_rank_0("still prints")
        assert "still prints" in capsys.readouterr().out

    def test_silent_on_nonzero_rank(self, monkeypatch, capsys):
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        alog.print_rank_0("suppressed")
        assert capsys.readouterr().out == ""


def test_build_root_logger_idempotent():
    # re-running the builder (e.g. on module reimport) must not stack a
    # second handler onto the shared logging-module registry entry
    fresh = alog._build_root_logger()
    assert fresh is alog.get_logger()
    assert len(fresh.handlers) == 1
