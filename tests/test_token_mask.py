"""Constrained decoding (ISSUE 20 satellite): per-request vocab
allow-masks through every sampling site.

The contract: the mask lands BEFORE temperature/top-k/top-p — so the
filtered distribution is a proper renormalization of the ALLOWED set —
on the reference chain, the fused kernel, the serving engine's
mixed-temperature sampler, and both halves of speculative decoding
(draft and verify see the same mask, so acceptance stays coherent).
Greedy pins are exact; sampled pins are distributional (χ², the
test_fused_sampling discipline)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.ops.fused_sampling import (
    apply_token_mask, filter_logits, fused_sample, sample_reference)
from apex_tpu.serving import ServingEngine

_NEG_INF = -1e30


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _mask(vocab, allowed):
    m = np.zeros((vocab,), bool)
    m[list(allowed)] = True
    return m


class TestApplyTokenMask:
    def test_greedy_argmax_restricted_to_allowed_set(self):
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(4, 32), jnp.float32)
        allowed = (3, 7, 21)
        out = np.asarray(sample_reference(
            logits, jax.random.PRNGKey(0), temperature=0.0,
            token_mask=jnp.asarray(_mask(32, allowed))))
        masked = np.asarray(logits).copy()
        masked[:, [i for i in range(32) if i not in allowed]] = _NEG_INF
        np.testing.assert_array_equal(out, masked.argmax(-1))
        assert set(out.tolist()) <= set(allowed)

    def test_per_row_masks_and_none_passthrough(self):
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(2, 16), jnp.float32)
        assert apply_token_mask(logits, None) is logits
        rows = np.zeros((2, 16), bool)
        rows[0, [1, 2]] = True
        rows[1, [9]] = True
        out = np.asarray(sample_reference(
            logits, jax.random.PRNGKey(0), temperature=0.0,
            token_mask=jnp.asarray(rows)))
        assert out[0] in (1, 2) and out[1] == 9

    def test_mask_before_filters_keeps_allowed_support(self):
        """Masking ahead of top-k is the ordering contract: the k
        survivors are the k best ALLOWED tokens, never fewer because a
        disallowed token burned a slot."""
        rng = np.random.RandomState(2)
        row = jnp.asarray(rng.randn(1, 64), jnp.float32)
        allowed = list(range(8, 16))
        m = jnp.asarray(_mask(64, allowed))
        f = np.asarray(filter_logits(apply_token_mask(row, m),
                                     top_k=4))[0]
        support = set(np.where(f > _NEG_INF / 2)[0].tolist())
        best4 = set(np.asarray(row)[0, allowed].argsort()[-4:] + 8)
        assert support == best4


class TestKernelParity:
    def test_kernel_support_stays_inside_mask(self):
        rng = np.random.RandomState(3)
        row = jnp.asarray(rng.randn(1, 160), jnp.float32) * 2
        allowed = sorted(rng.choice(160, 24, replace=False).tolist())
        m = jnp.asarray(_mask(160, allowed))
        toks = np.asarray(fused_sample(
            jnp.tile(row, (256, 1)), jax.random.PRNGKey(11),
            temperature=0.9, top_k=7, token_mask=m,
            backend="kernel"))
        f = np.asarray(filter_logits(
            apply_token_mask(row.astype(jnp.float32) / 0.9, m),
            top_k=7))[0]
        support = set(np.where(f > _NEG_INF / 2)[0].tolist())
        assert set(toks.tolist()) <= support <= set(allowed)

    def test_chi_squared_over_masked_support(self):
        """The distributional pin: n kernel draws under a mask must
        histogram as the softmax RENORMALIZED over the allowed set —
        and the disallowed set must draw exactly zero."""
        rng = np.random.RandomState(4)
        v, n = 16, 8192
        allowed = [2, 5, 11, 13]
        row = jnp.asarray(rng.randn(1, v), jnp.float32)
        m = jnp.asarray(_mask(v, allowed))
        p = np.asarray(jax.nn.softmax(
            apply_token_mask(row, m).astype(jnp.float32)))[0]
        toks = np.asarray(fused_sample(
            jnp.tile(row, (n, 1)), jax.random.PRNGKey(9),
            temperature=1.0, token_mask=m, backend="kernel"))
        counts = np.bincount(toks, minlength=v)
        live = p > 0
        assert counts[~live].sum() == 0
        chi2 = (((counts[live] - n * p[live]) ** 2)
                / (n * p[live])).sum()
        assert chi2 < 16.27, chi2      # chi2(3).ppf(0.999)


class TestEngineConstrainedDecoding:
    def _engine(self, params, cfg, **kw):
        kw.setdefault("max_slots", 2)
        kw.setdefault("max_len", 24)
        kw.setdefault("prompt_buckets", (8,))
        kw.setdefault("cache_layout", "paged")
        kw.setdefault("block_size", 4)
        kw.setdefault("num_blocks", 16)
        return ServingEngine(params, cfg, token_masks=True, **kw)

    def test_singleton_mask_forces_the_token(self, model):
        cfg, params = model
        eng = self._engine(params, cfg)
        rng = np.random.RandomState(5)
        prompt = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        resps = eng.run([dict(prompt=prompt, max_new_tokens=5,
                              token_mask_fn=lambda v: [42])])
        assert resps[0].tokens.tolist() == [42] * 5

    def test_mask_forms_agree_and_unmasked_lane_rides_along(
            self, model):
        """A bool [v] mask and an id list produce the same stream, a
        mixed batch keeps unmasked lanes on the base distribution, and
        greedy masked output lands inside the allowed set."""
        cfg, params = model
        rng = np.random.RandomState(6)
        prompts = [rng.randint(0, cfg.vocab_size, (6,)).astype(
            np.int32) for _ in range(2)]
        allowed = list(range(0, cfg.vocab_size, 3))

        eng = self._engine(params, cfg)
        got = eng.run([
            dict(prompt=prompts[0].copy(), max_new_tokens=6,
                 token_mask_fn=lambda v: allowed),
            dict(prompt=prompts[1].copy(), max_new_tokens=6)])
        by_id = {r.request_id: r for r in got}
        assert set(by_id[0].tokens.tolist()) <= set(allowed)

        free = ServingEngine(params, cfg, max_slots=2, max_len=24,
                             prompt_buckets=(8,), cache_layout="paged",
                             block_size=4, num_blocks=16)
        base = free.run([dict(prompt=prompts[1].copy(),
                              max_new_tokens=6)])
        np.testing.assert_array_equal(by_id[1].tokens, base[0].tokens)

        eng2 = self._engine(params, cfg)
        again = eng2.run([dict(
            prompt=prompts[0].copy(), max_new_tokens=6,
            token_mask_fn=lambda v: _mask(v, allowed))])
        np.testing.assert_array_equal(again[0].tokens, by_id[0].tokens)

    def test_sampled_lane_stays_inside_mask(self, model):
        cfg, params = model
        eng = self._engine(params, cfg)
        rng = np.random.RandomState(7)
        allowed = [4, 9, 17, 33, 50]
        resps = eng.run([dict(
            prompt=rng.randint(0, cfg.vocab_size, (6,)).astype(
                np.int32),
            max_new_tokens=12, temperature=1.0,
            token_mask_fn=lambda v: allowed)])
        assert set(resps[0].tokens.tolist()) <= set(allowed)

    def test_spec_decode_applies_the_same_mask_to_draft_and_target(
            self, model):
        """The spec x mask composition gate: a speculative engine under
        a mask emits exactly the spec-off masked stream (the verify
        pass scores masked logits, so a draft the mask forbids can
        never be accepted)."""
        cfg, params = model
        rng = np.random.RandomState(8)
        prompt = rng.randint(0, cfg.vocab_size, (6,)).astype(np.int32)
        allowed = list(range(0, cfg.vocab_size, 2))

        plain = self._engine(params, cfg)
        want = plain.run([dict(prompt=prompt.copy(), max_new_tokens=10,
                               token_mask_fn=lambda v: allowed)])
        spec = self._engine(params, cfg, spec="ngram")
        got = spec.run([dict(prompt=prompt.copy(), max_new_tokens=10,
                             token_mask_fn=lambda v: allowed)])
        np.testing.assert_array_equal(got[0].tokens, want[0].tokens)
        assert set(got[0].tokens.tolist()) <= set(allowed)

    def test_mask_needs_optin_and_valid_shape(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, max_slots=2, max_len=24,
                            prompt_buckets=(8,), cache_layout="paged",
                            block_size=4, num_blocks=16)
        with pytest.raises(ValueError, match="token_masks=True"):
            eng.submit(np.zeros((6,), np.int32),
                       token_mask_fn=lambda v: [1])
        opted = self._engine(params, cfg)
        with pytest.raises(ValueError, match="expected"):
            opted.submit(np.zeros((6,), np.int32),
                         token_mask_fn=lambda v: np.zeros((7,), bool))
