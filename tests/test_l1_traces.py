"""L1 convergence-trace tests.

Rebuild of the reference's L1 strategy (tests/L1/common/run_test.sh:19-40
+ compare.py): a deterministic short training run is traced (loss +
global grad norm per step); the fp32 O0 trace is pinned against a stored
golden file (catches any numerical regression, 1-step resolution), and
the mixed-precision levels must track the O0 trace within per-level
tolerances (the reference compares O1/O2/O3 runs against a stored O0
baseline of ResNet-50; here the workload is the tiny in-repo GPT).

Regenerate the golden file after an *intentional* numerics change:
    python tests/test_l1_traces.py --regen
"""

import json
import os
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.amp.frontend import make_train_step
from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.transformer_lm import gpt_loss, init_gpt_params
from apex_tpu.optimizers import fused_adam
from apex_tpu.optimizers._common import GradientTransformation, global_norm

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "l1_trace_o0.json")
GOLDEN_GQA = os.path.join(os.path.dirname(__file__), "data",
                          "l1_trace_gqa_o0.json")
N_STEPS = 12


class _NormState(NamedTuple):
    inner: Any
    grad_norm: jax.Array


def _norm_tracking(tx: GradientTransformation) -> GradientTransformation:
    """Record the global grad norm in the optimizer state (the L1 trace's
    second channel, reference compare.py)."""

    def init(params):
        return _NormState(tx.init(params), jnp.zeros((), jnp.float32))

    def update(grads, state, params=None):
        updates, inner = tx.update(grads, state.inner, params)
        return updates, _NormState(inner, global_norm(grads))

    return GradientTransformation(init, update)


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 32)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


def _data(cfg, b=8, s=16):
    rng = np.random.RandomState(1234)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
    return tokens, labels


def run_trace(opt_level: str, n_steps: int = N_STEPS, cfg=None):
    """Deterministic training trace: (losses, grad_norms) per step."""
    cfg = cfg if cfg is not None else _cfg()
    params = init_gpt_params(jax.random.PRNGKey(42), cfg)
    tokens, labels = _data(cfg)

    def loss_fn(p, t, l):
        return gpt_loss(p, t, l, cfg)

    tx = _norm_tracking(fused_adam(lr=1e-3))
    init_fn, step_fn = make_train_step(loss_fn, tx, opt_level)
    step_fn = jax.jit(step_fn)
    state = init_fn(params)
    losses, norms = [], []
    for _ in range(n_steps):
        state, metrics = step_fn(state, tokens, labels)
        losses.append(float(metrics["loss"]))
        norms.append(float(state.opt_state.grad_norm))
    return np.array(losses), np.array(norms)


class TestL1Traces:
    def test_o0_matches_stored_golden(self):
        """1-step-resolution regression pin for fp32 numerics."""
        assert os.path.exists(GOLDEN), (
            "golden trace missing; run `python tests/test_l1_traces.py "
            "--regen` and commit tests/data/l1_trace_o0.json")
        with open(GOLDEN) as f:
            gold = json.load(f)
        losses, norms = run_trace("O0")
        np.testing.assert_allclose(
            losses, np.array(gold["loss"]), rtol=2e-5, atol=1e-6,
            err_msg="O0 loss trace drifted from the stored baseline")
        np.testing.assert_allclose(
            norms, np.array(gold["grad_norm"]), rtol=2e-4, atol=1e-5,
            err_msg="O0 grad-norm trace drifted from the stored baseline")

    @pytest.mark.parametrize("level,loss_tol,norm_tol", [
        ("O1", 2e-2, 0.15),
        ("O2", 2e-2, 0.15),
        ("O5", 2e-2, 0.15),
    ])
    def test_amp_levels_track_o0(self, level, loss_tol, norm_tol):
        """Mixed precision must converge along the fp32 trajectory
        (reference run_test.sh opt-level cross-product vs O0 baseline)."""
        ref_losses, ref_norms = run_trace("O0")
        losses, norms = run_trace(level)
        np.testing.assert_allclose(
            losses, ref_losses, rtol=loss_tol,
            err_msg=f"{level} loss trace diverged from O0")
        np.testing.assert_allclose(
            norms, ref_norms, rtol=norm_tol,
            err_msg=f"{level} grad-norm trace diverged from O0")
        # and training must actually make progress
        assert losses[-1] < losses[0]


def run_trace_mesh(dp: int, tp: int, sp: int = 1,
                   context_parallel=False, n_steps: int = N_STEPS):
    """The same O0 trace under GSPMD dp/tp (and optionally sp context
    parallelism) on the 8-device mesh — the reference
    tests/L1/cross_product_distributed analog (run.sh repeats the
    convergence comparison under a 2-GPU launch)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.models.transformer_lm import gpt_param_specs, gspmd_ctx
    from apex_tpu.parallel.mesh import create_mesh

    cfg = _cfg()
    mesh = create_mesh(dp=dp, tp=tp, pp=1, sp=sp)
    ctx = (gspmd_ctx(seq_axis="sp", context_parallel=context_parallel)
           if context_parallel else gspmd_ctx())
    params = init_gpt_params(jax.random.PRNGKey(42), cfg)
    params = jax.device_put(
        params,
        jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), gpt_param_specs(cfg),
            is_leaf=lambda x: isinstance(x, P)))
    tokens, labels = _data(cfg)
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp")))
    labels = jax.device_put(labels, NamedSharding(mesh, P("dp")))

    def loss_fn(p, t, l):
        return gpt_loss(p, t, l, cfg, ctx)

    tx = _norm_tracking(fused_adam(lr=1e-3))
    init_fn, step_fn = make_train_step(loss_fn, tx, "O0")
    step_fn = jax.jit(step_fn)
    losses, norms = [], []
    with jax.set_mesh(mesh):
        state = init_fn(params)
        for _ in range(n_steps):
            state, metrics = step_fn(state, tokens, labels)
            losses.append(float(metrics["loss"]))
            norms.append(float(state.opt_state.grad_norm))
    return np.array(losses), np.array(norms)


class TestL1TracesDistributed:
    """Multi-device L1: the dp and dp×tp shardings must track the stored
    single-device golden — same model, same batch, same trajectory."""

    # [4-2] stays default: it is the only default-tier MULTI-STEP
    # optimizer-trajectory parity check across shardings (the dryrun
    # gate deliberately stops at single-shot loss/grads). The pure-dp
    # re-factoring of the same golden rides the slow tier.
    @pytest.mark.parametrize(
        "dp,tp", [pytest.param(8, 1, marks=pytest.mark.slow), (4, 2)])
    def test_sharded_trace_matches_golden(self, dp, tp):
        if len(jax.devices()) < dp * tp:
            pytest.skip("needs the 8-device mesh")
        with open(GOLDEN) as f:
            gold = json.load(f)
        losses, norms = run_trace_mesh(dp, tp)
        np.testing.assert_allclose(
            losses, np.array(gold["loss"]), rtol=1e-4, atol=1e-5,
            err_msg=f"dp={dp},tp={tp} loss trace drifted from the "
                    "single-device golden")
        np.testing.assert_allclose(
            norms, np.array(gold["grad_norm"]), rtol=1e-3, atol=1e-4,
            err_msg=f"dp={dp},tp={tp} grad-norm trace drifted from the "
                    "single-device golden")

    # ring stays default-tier: the only multi-STEP trajectory pin of the
    # long-context path (the dryrun gate asserts single-shot parity);
    # ulysses re-pins the same golden through the other collective
    # pattern and rides the slow tier
    @pytest.mark.parametrize(
        "mode", ["ring", pytest.param("ulysses", marks=pytest.mark.slow)])
    def test_context_parallel_trace_matches_golden(self, mode):
        """Context parallelism is not allowed to bend the optimizer
        trajectory: 12 steps under dp=2 x sp=4 must track the stored
        single-device golden (VERDICT r4 #5e)."""
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device mesh")
        with open(GOLDEN) as f:
            gold = json.load(f)
        losses, norms = run_trace_mesh(2, 1, sp=4, context_parallel=mode)
        np.testing.assert_allclose(
            losses, np.array(gold["loss"]), rtol=1e-4, atol=1e-5,
            err_msg=f"cp={mode} loss trace drifted from the golden")
        np.testing.assert_allclose(
            norms, np.array(gold["grad_norm"]), rtol=1e-3, atol=1e-4,
            err_msg=f"cp={mode} grad-norm trace drifted from the golden")


class TestL1TracesGQA:
    """The GQA path gets its own golden (VERDICT r4 #5e): the group-major
    layout landed in round 5 and future refactors must not bend its
    numerics.  Same regen protocol: `python tests/test_l1_traces.py
    --regen` rewrites both goldens."""

    def test_gqa_o0_matches_stored_golden(self):
        assert os.path.exists(GOLDEN_GQA), (
            "GQA golden trace missing; run `python tests/test_l1_traces"
            ".py --regen` and commit tests/data/l1_trace_gqa_o0.json")
        with open(GOLDEN_GQA) as f:
            gold = json.load(f)
        losses, norms = run_trace("O0", cfg=_cfg(num_query_groups=2))
        np.testing.assert_allclose(
            losses, np.array(gold["loss"]), rtol=2e-5, atol=1e-6,
            err_msg="GQA O0 loss trace drifted from the stored baseline")
        np.testing.assert_allclose(
            norms, np.array(gold["grad_norm"]), rtol=2e-4, atol=1e-5,
            err_msg="GQA O0 grad-norm trace drifted from the baseline")

    @pytest.mark.slow   # O2 tracks its own-golden's trajectory; CI job
    def test_gqa_amp_tracks_o0(self):
        ref_losses, _ = run_trace("O0", cfg=_cfg(num_query_groups=2))
        losses, _ = run_trace("O2", cfg=_cfg(num_query_groups=2))
        np.testing.assert_allclose(
            losses, ref_losses, rtol=2e-2,
            err_msg="GQA O2 loss trace diverged from GQA O0")
        assert losses[-1] < losses[0]


if __name__ == "__main__":
    import sys
    if "--regen" in sys.argv:
        for path, cfg in ((GOLDEN, None),
                          (GOLDEN_GQA, _cfg(num_query_groups=2))):
            losses, norms = run_trace("O0", cfg=cfg)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                json.dump({"loss": losses.tolist(),
                           "grad_norm": norms.tolist()}, f, indent=1)
            print(f"wrote {path}")
