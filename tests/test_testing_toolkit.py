"""Megatron-style arguments + global_vars toolkit."""

import jax.numpy as jnp
import pytest

from apex_tpu.transformer.testing import (
    get_args,
    get_num_microbatches,
    get_timers,
    parse_args,
    set_global_variables,
)
from apex_tpu.transformer.testing.arguments import to_transformer_config
from apex_tpu.transformer.testing.global_vars import destroy_global_vars


@pytest.fixture(autouse=True)
def _clean():
    destroy_global_vars()
    yield
    destroy_global_vars()


class TestArguments:
    def test_megatron_flags_parse(self):
        a = parse_args(args=[
            "--num-layers", "4", "--hidden-size", "128",
            "--num-attention-heads", "8", "--micro-batch-size", "4",
            "--global-batch-size", "16", "--bf16",
            "--tensor-model-parallel-size", "2",
            "--pipeline-model-parallel-size", "2",
            "--vocab-size", "1000",
        ])
        assert a.num_layers == 4
        assert a.tensor_model_parallel_size == 2
        # vocab padded to make_vocab_size_divisible_by * tp = 256
        assert a.padded_vocab_size == 1024

    def test_to_transformer_config(self):
        a = parse_args(args=["--bf16", "--hidden-size", "64",
                             "--num-attention-heads", "4"])
        cfg = to_transformer_config(a)
        assert cfg.hidden_size == 64
        assert cfg.compute_dtype == jnp.bfloat16

    def test_foreign_backend_warns_not_raises(self):
        with pytest.warns(UserWarning, match="XLA collectives"):
            parse_args(args=["--distributed-backend", "nccl"])

    def test_extra_args_provider_and_defaults(self):
        def extra(p):
            p.add_argument("--my-flag", type=int, default=None)
            return p

        a = parse_args(extra_args_provider=extra,
                       defaults={"my_flag": 7}, args=[])
        assert a.my_flag == 7


class TestGlobalVars:
    def test_set_and_get(self):
        a = set_global_variables(args=[
            "--micro-batch-size", "2", "--global-batch-size", "8"])
        assert get_args() is a
        assert get_num_microbatches() == 4
        timers = get_timers()
        timers("fwd").start()
        timers("fwd").stop()

    def test_double_init_asserts(self):
        set_global_variables(args=[])
        with pytest.raises(AssertionError):
            set_global_variables(args=[])
