"""Fused optimizer parity tests.

Reference analog: tests/L0/run_optimizers/test_fused_optimizer.py — FusedAdam
vs torch.optim.Adam step-for-step. Here torch (CPU) is the oracle for
Adam/AdamW/SGD/Adagrad; LAMB/NovoGrad/LARS check against hand-rolled numpy
of the documented kernel formulas.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import optimizers as opt


def _tree_from(np_tree):
    return {k: jnp.asarray(v) for k, v in np_tree.items()}


def _rand_params_grads(seed=0, shapes=((4, 8), (8,), (3, 5, 2))):
    rng = np.random.RandomState(seed)
    params = {f"p{i}": rng.randn(*s).astype(np.float32)
              for i, s in enumerate(shapes)}
    grads = [
        {f"p{i}": rng.randn(*s).astype(np.float32)
         for i, s in enumerate(shapes)}
        for _ in range(5)
    ]
    return params, grads


def _run_jax(tx, params_np, grads_np):
    params = _tree_from(params_np)
    state = tx.init(params)
    step = jax.jit(lambda g, s, p: tx.update(g, s, p))
    for g_np in grads_np:
        updates, state = step(_tree_from(g_np), state, params)
        params = opt.apply_updates(params, updates)
    return {k: np.asarray(v) for k, v in params.items()}


def _run_torch(optim_cls, params_np, grads_np, **kwargs):
    tparams = {k: torch.nn.Parameter(torch.tensor(v))
               for k, v in params_np.items()}
    optim = optim_cls(list(tparams.values()), **kwargs)
    for g_np in grads_np:
        for k, p in tparams.items():
            p.grad = torch.tensor(g_np[k])
        optim.step()
    return {k: p.detach().numpy() for k, p in tparams.items()}


class TestFusedAdam:
    @pytest.mark.parametrize("wd", [0.0, 0.1])
    def test_matches_torch_adamw(self, wd):
        params, grads = _rand_params_grads()
        ours = _run_jax(
            opt.fused_adam(lr=1e-2, weight_decay=wd, adam_w_mode=True),
            params, grads,
        )
        ref = _run_torch(torch.optim.AdamW, params, grads,
                         lr=1e-2, weight_decay=wd)
        for k in params:
            np.testing.assert_allclose(ours[k], ref[k], atol=1e-6, rtol=1e-5)

    def test_matches_torch_adam_l2_mode(self):
        params, grads = _rand_params_grads(1)
        ours = _run_jax(
            opt.fused_adam(lr=1e-2, weight_decay=0.1, adam_w_mode=False),
            params, grads,
        )
        ref = _run_torch(torch.optim.Adam, params, grads,
                         lr=1e-2, weight_decay=0.1)
        for k in params:
            np.testing.assert_allclose(ours[k], ref[k], atol=1e-6, rtol=1e-5)

    def test_no_bias_correction(self):
        params, grads = _rand_params_grads(2, shapes=((4,),))
        ours = _run_jax(opt.fused_adam(lr=1e-2, bias_correction=False),
                        params, grads[:1])
        # hand formula, one step
        g = grads[0]["p0"]
        m = 0.1 * g
        v = 0.001 * g * g
        expect = params["p0"] - 1e-2 * m / (np.sqrt(v) + 1e-8)
        np.testing.assert_allclose(ours["p0"], expect, atol=1e-6)

    def test_amsgrad_rejected(self):
        with pytest.raises(RuntimeError):
            opt.fused_adam(amsgrad=True)

    def test_lr_schedule(self):
        params, grads = _rand_params_grads(3, shapes=((4,),))
        sched = lambda step: 1e-2 / step.astype(jnp.float32)  # noqa: E731
        ours = _run_jax(opt.fused_adam(lr=sched), params, grads)
        assert np.isfinite(ours["p0"]).all()

    def test_flat_buffer_path_matches_tree_path(self):
        params, grads = _rand_params_grads(4)
        base = _run_jax(
            opt.fused_adam(lr=1e-2, weight_decay=0.05), params, grads
        )
        flat = _run_jax(
            opt.fused_adam(lr=1e-2, weight_decay=0.05,
                           use_flat_buffer=True),
            params, grads,
        )
        for k in params:
            np.testing.assert_allclose(flat[k], base[k], atol=1e-6,
                                       rtol=1e-6)

    def test_use_pallas_alias_deprecated_but_working(self):
        params, grads = _rand_params_grads(4)
        with pytest.warns(DeprecationWarning, match="use_flat_buffer"):
            tx = opt.fused_adam(lr=1e-2, use_pallas=True)
        aliased = _run_jax(tx, params, grads)
        flat = _run_jax(
            opt.fused_adam(lr=1e-2, use_flat_buffer=True), params, grads)
        for k in params:
            np.testing.assert_allclose(aliased[k], flat[k], rtol=1e-7)


class TestFusedSGD:
    @pytest.mark.parametrize(
        "kwargs",
        [
            dict(momentum=0.0, weight_decay=0.0),
            dict(momentum=0.9, weight_decay=0.0),
            dict(momentum=0.9, weight_decay=0.01),
            dict(momentum=0.9, dampening=0.1, weight_decay=0.01),
            dict(momentum=0.9, nesterov=True),
        ],
    )
    def test_matches_torch_sgd(self, kwargs):
        params, grads = _rand_params_grads(5)
        ours = _run_jax(opt.fused_sgd(lr=0.05, **kwargs), params, grads)
        ref = _run_torch(torch.optim.SGD, params, grads, lr=0.05, **kwargs)
        for k in params:
            np.testing.assert_allclose(ours[k], ref[k], atol=1e-6, rtol=1e-5)

    def test_nesterov_validation(self):
        with pytest.raises(ValueError):
            opt.fused_sgd(momentum=0.0, nesterov=True)


class TestFusedAdagrad:
    @pytest.mark.parametrize("wd", [0.0, 0.05])
    def test_matches_torch_adagrad(self, wd):
        params, grads = _rand_params_grads(6)
        ours = _run_jax(opt.fused_adagrad(lr=0.05, weight_decay=wd),
                        params, grads)
        ref = _run_torch(torch.optim.Adagrad, params, grads, lr=0.05,
                         weight_decay=wd, eps=1e-10)
        for k in params:
            np.testing.assert_allclose(ours[k], ref[k], atol=1e-6, rtol=1e-5)


def _numpy_lamb(params, grads, lr, b1, b2, eps, wd, max_gn, nvlamb=False,
                steps=None):
    m = {k: np.zeros_like(v) for k, v in params.items()}
    v = {k: np.zeros_like(p) for k, p in params.items()}
    p = {k: x.copy() for k, x in params.items()}
    t = 0
    for g in grads:
        t += 1
        gnorm = np.sqrt(sum(np.sum(x ** 2) for x in g.values()))
        clip = max(gnorm / max_gn, 1.0) if max_gn else 1.0
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t
        for k in p:
            gg = g[k] / clip
            m[k] = b1 * m[k] + (1 - b1) * gg
            v[k] = b2 * v[k] + (1 - b2) * gg * gg
            u = (m[k] / bc1) / (np.sqrt(v[k] / bc2) + eps)
            if wd:
                u = u + wd * p[k]
            wn = np.sqrt(np.sum(p[k] ** 2))
            un = np.sqrt(np.sum(u ** 2))
            if wd == 0.0 and not nvlamb:
                ratio = 1.0
            else:
                ratio = wn / un if (wn > 0 and un > 0) else 1.0
            p[k] = p[k] - lr * ratio * u
    return p


class TestFusedLAMB:
    def test_l2_mode_weight_decay_reaches_moments(self):
        # MOMENT_MODE_0: with zero grads, decay*p drives a nonzero update.
        params = {"p0": np.array([2.0, -3.0], np.float32)}
        zeros = [{"p0": np.zeros(2, np.float32)}]
        out = _run_jax(
            opt.fused_lamb(lr=0.1, weight_decay=0.5, adam_w_mode=False,
                           max_grad_norm=0.0),
            params, zeros,
        )
        assert np.abs(out["p0"] - params["p0"]).max() > 1e-3

    @pytest.mark.parametrize("wd,nvlamb", [(0.01, False), (0.0, False),
                                           (0.0, True)])
    def test_matches_numpy_reference(self, wd, nvlamb):
        params, grads = _rand_params_grads(7)
        ours = _run_jax(
            opt.fused_lamb(lr=1e-2, weight_decay=wd, max_grad_norm=1.0,
                           use_nvlamb=nvlamb),
            params, grads,
        )
        ref = _numpy_lamb(params, grads, 1e-2, 0.9, 0.999, 1e-6, wd, 1.0,
                          nvlamb)
        for k in params:
            np.testing.assert_allclose(ours[k], ref[k], atol=1e-5, rtol=1e-4)


class TestFusedNovoGrad:
    def test_one_step_hand_formula(self):
        g0 = np.array([3.0, 4.0], np.float32)   # ||g|| = 5
        params = {"p0": np.array([1.0, 2.0], np.float32)}
        ours = _run_jax(
            opt.fused_novograd(lr=0.1, betas=(0.95, 0.98), eps=1e-8,
                               weight_decay=0.0),
            params, [{"p0": g0}],
        )
        # v init = ||g|| = 5 (init-with-first-norm), bc2 = sqrt(1-0.98)
        v = 5.0
        bc1, bc2 = 1 - 0.95, np.sqrt(1 - 0.98)
        m = 0.05 * g0
        u = (m / bc1) / (v / bc2 + 1e-8)
        expect = params["p0"] - 0.1 * u
        np.testing.assert_allclose(ours["p0"], expect, atol=1e-6)

    def test_l2_quadrature_blend_two_steps(self):
        # reference multi_tensor_norm_out_cuda: gn = sqrt(b2*gn^2+(1-b2)*n^2)
        g1 = np.array([3.0, 4.0], np.float32)            # ||g1|| = 5
        g2 = np.array([6.0, 8.0], np.float32)            # ||g2|| = 10
        params = {"p0": np.array([1.0, 2.0], np.float32)}
        b1, b2, lr, eps = 0.95, 0.98, 0.1, 1e-8
        ours = _run_jax(
            opt.fused_novograd(lr=lr, betas=(b1, b2), eps=eps),
            params, [{"p0": g1}, {"p0": g2}],
        )
        p = params["p0"].copy()
        v = 5.0
        m = np.zeros(2, np.float32)
        for t, g in enumerate([g1, g2], start=1):
            n = np.sqrt(np.sum(g ** 2))
            v = np.sqrt(b2 * v ** 2 + (1 - b2) * n ** 2)
            bc1, bc2 = 1 - b1 ** t, np.sqrt(1 - b2 ** t)
            m = b1 * m + (1 - b1) * g
            p = p - lr * ((m / bc1) / (v / bc2 + eps))
        np.testing.assert_allclose(ours["p0"], p, atol=1e-6)

    def test_inf_norm_and_init_zero(self):
        params, grads = _rand_params_grads(8, shapes=((6,),))
        ours = _run_jax(
            opt.fused_novograd(lr=0.01, norm_type=0, init_zero=True),
            params, grads,
        )
        assert np.isfinite(ours["p0"]).all()

    def test_bad_norm_type(self):
        with pytest.raises(RuntimeError):
            opt.fused_novograd(norm_type=1)


class TestFusedLARS:
    def test_one_step_hand_formula(self):
        p0 = np.array([3.0, 4.0], np.float32)        # ||p|| = 5
        g0 = np.array([0.6, 0.8], np.float32)        # ||g|| = 1
        params = {"p0": p0}
        tc, wd, lr, mom = 0.001, 0.01, 0.1, 0.9
        ours = _run_jax(
            opt.fused_lars(lr=lr, momentum=mom, weight_decay=wd,
                           trust_coefficient=tc),
            params, [{"p0": g0}],
        )
        trust = tc * 5.0 / (1.0 + 5.0 * wd + 0.0)
        slr = lr * trust
        d = g0 + wd * p0
        m = -slr * d
        expect = p0 + m
        np.testing.assert_allclose(ours["p0"], expect, atol=1e-7)

    def test_skip_predicate_uses_plain_lr(self):
        p0 = np.array([3.0, 4.0], np.float32)
        g0 = np.array([0.6, 0.8], np.float32)
        ours = _run_jax(
            opt.fused_lars(lr=0.1, momentum=0.0, trust_coefficient=0.001,
                           skip_predicate=lambda path: True),
            {"p0": p0}, [{"p0": g0}],
        )
        np.testing.assert_allclose(ours["p0"], p0 - 0.1 * g0, atol=1e-7)


class TestMultiTensor:
    def test_scale_and_flag(self):
        from apex_tpu.multi_tensor import multi_tensor_scale

        outs, flag = multi_tensor_scale(
            [jnp.asarray([2.0, 4.0]), jnp.asarray([6.0])], 0.5
        )
        np.testing.assert_allclose(outs[0], [1.0, 2.0])
        np.testing.assert_allclose(outs[1], [3.0])
        assert int(flag) == 0
        _, flag = multi_tensor_scale([jnp.asarray([jnp.inf])], 1.0)
        assert int(flag) == 1

    def test_axpby(self):
        from apex_tpu.multi_tensor import multi_tensor_axpby

        outs, flag = multi_tensor_axpby(
            [jnp.asarray([1.0, 2.0])], [jnp.asarray([10.0, 20.0])], 2.0, 0.5
        )
        np.testing.assert_allclose(outs[0], [7.0, 14.0])
        assert int(flag) == 0

    def test_l2norm(self):
        from apex_tpu.multi_tensor import multi_tensor_l2norm

        total, per = multi_tensor_l2norm(
            [jnp.asarray([3.0]), jnp.asarray([4.0])], per_tensor=True
        )
        np.testing.assert_allclose(float(total), 5.0)
        np.testing.assert_allclose(per, [3.0, 4.0])

    def test_applier_reference_pattern(self):
        # The exact calling pattern of apex/amp/scaler.py:114-126.
        from apex_tpu.multi_tensor import amp_C, multi_tensor_applier

        model_grads = [jnp.asarray([2.0, 4.0], jnp.float16)]
        master_grads = [jnp.asarray([0.0, 0.0], jnp.float32)]
        outs, flag = multi_tensor_applier(
            amp_C.multi_tensor_scale,
            jnp.zeros((), jnp.int32),
            [model_grads, master_grads],
            0.5,
        )
        assert outs[0].dtype == jnp.float32
        np.testing.assert_allclose(outs[0], [1.0, 2.0])
        assert int(flag) == 0

    def test_applier_axpby_pattern(self):
        from apex_tpu.multi_tensor import amp_C, multi_tensor_applier

        xs = [jnp.asarray([1.0, 2.0])]
        ys = [jnp.asarray([10.0, 20.0])]
        outs, flag = multi_tensor_applier(
            amp_C.multi_tensor_axpby,
            jnp.zeros((), jnp.int32),
            [xs, ys, xs],
            2.0, 0.5, -1,
        )
        np.testing.assert_allclose(outs[0], [7.0, 14.0])
