"""KV-cache decoding: teacher-forcing parity with the training forward,
prefill-vs-stepwise cache equivalence, and ragged-batch decode parity."""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import (
    decode_step, generate, init_kv_cache, prefill, sample_logits)
from apex_tpu.models.transformer_lm import gpt_forward, init_gpt_params


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 24)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


VARIANTS = [
    {},
    {"position_embedding_type": "rope"},
    {"activation": "swiglu"},
    {"activation": "gelu_tanh"},
    {"apply_residual_connection_post_layernorm": True},
    {"normalization": "rmsnorm"},
]


class TestDecodeParity:
    @pytest.mark.parametrize("variant", VARIANTS)
    def test_stepwise_logits_match_full_forward(self, variant):
        """Feeding the gold sequence token-by-token through the cached
        decode must reproduce the training forward's logits at every
        position — the strongest possible pin of the cache math."""
        cfg = _cfg(**variant)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        b, s = 2, 12
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)),
                             jnp.int32)

        want = np.asarray(gpt_forward(params, tokens, cfg))

        cache = init_kv_cache(cfg, b, s)
        step = jax.jit(lambda t, c: decode_step(params, t, c, cfg))
        for i in range(s):
            logits, cache = step(tokens[:, i], cache)
            np.testing.assert_allclose(
                np.asarray(logits), want[:, i], atol=2e-4, rtol=2e-4,
                err_msg=f"{variant} position {i}")


class TestGenerate:
    def test_greedy_matches_argmax_of_forward(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(1), cfg)
        rng = np.random.RandomState(1)
        prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 4)),
                             jnp.int32)
        out = generate(params, prompt, cfg, max_new_tokens=6)
        assert out.shape == (2, 10)
        np.testing.assert_array_equal(np.asarray(out[:, :4]),
                                      np.asarray(prompt))
        # reference: greedy re-decode with the full forward each step
        seq = np.asarray(prompt)
        for _ in range(6):
            logits = np.asarray(gpt_forward(
                params, jnp.asarray(seq, jnp.int32), cfg))
            nxt = logits[:, -1].argmax(-1).astype(np.int32)
            seq = np.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), seq)

    def test_sampling_is_seeded_and_topk_restricts(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(2), cfg)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        a = generate(params, prompt, cfg, max_new_tokens=8,
                     temperature=1.0, top_k=5, rng=jax.random.PRNGKey(7))
        b = generate(params, prompt, cfg, max_new_tokens=8,
                     temperature=1.0, top_k=5, rng=jax.random.PRNGKey(7))
        c = generate(params, prompt, cfg, max_new_tokens=8,
                     temperature=1.0, top_k=5, rng=jax.random.PRNGKey(8))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_imported_hf_weights_generate(self):
        transformers = pytest.importorskip("transformers")
        torch = pytest.importorskip("torch")
        import os
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tools.import_hf import config_from_hf, params_from_hf

        hfc = transformers.GPT2Config(
            n_layer=2, n_embd=64, n_head=4, vocab_size=100,
            n_positions=32, resid_pdrop=0.0, embd_pdrop=0.0,
            attn_pdrop=0.0)
        torch.manual_seed(3)
        hf = transformers.GPT2LMHeadModel(hfc).eval()
        cfg = config_from_hf(hfc, compute_dtype=jnp.float32)
        params = params_from_hf(hf.state_dict(), cfg)

        prompt = jnp.asarray([[5, 17, 31]], jnp.int32)
        ours = generate(params, prompt, cfg, max_new_tokens=5,
                        vocab_limit=hfc.vocab_size)
        with torch.no_grad():
            theirs = hf.generate(
                torch.asarray(np.asarray(prompt)), max_new_tokens=5,
                do_sample=False, pad_token_id=0)
        np.testing.assert_array_equal(np.asarray(ours),
                                      theirs.numpy())


    def test_vocab_limit_masks_padded_ids(self):
        cfg = _cfg(vocab_size=128)
        params = init_gpt_params(jax.random.PRNGKey(5), cfg)
        prompt = jnp.asarray([[1, 2]], jnp.int32)
        out = generate(params, prompt, cfg, max_new_tokens=10,
                       temperature=1.0, rng=jax.random.PRNGKey(0),
                       vocab_limit=7)
        assert np.asarray(out)[:, 2:].max() < 7

    def test_overflowing_learned_positions_raise(self):
        cfg = _cfg(max_position_embeddings=8)
        params = init_gpt_params(jax.random.PRNGKey(6), cfg)
        prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        with pytest.raises(ValueError, match="exceeds"):
            generate(params, prompt, cfg, max_new_tokens=8)

    def test_moe_and_padding_configs_rejected(self):
        cfg = _cfg(num_experts=2)
        params = init_gpt_params(jax.random.PRNGKey(7), cfg)
        with pytest.raises(ValueError, match="MoE"):
            decode_step(params, jnp.asarray([1], jnp.int32),
                        init_kv_cache(cfg, 1, 4), cfg)
        cfg2 = _cfg(attn_mask_type="padding")
        params2 = init_gpt_params(jax.random.PRNGKey(8), cfg2)
        with pytest.raises(ValueError, match="causal"):
            decode_step(params2, jnp.asarray([1], jnp.int32),
                        init_kv_cache(cfg2, 1, 4), cfg2)


def _ragged_batch(rng, vocab, lens):
    """Left-aligned right-padded [b, max(lens)] batch + per-row prompts."""
    prompts = [rng.randint(0, vocab, (n,)).astype(np.int32) for n in lens]
    batch = np.zeros((len(lens), max(lens)), np.int32)
    for i, p in enumerate(prompts):
        batch[i, : len(p)] = p
    return jnp.asarray(batch), prompts


class TestPrefill:
    """The batched flash prefill must fill EXACTLY the cache the
    sequential decode would have built — the cache-equivalence pin that
    keeps the prefill/decode split honest."""

    # the GQA x rope variant is the riskiest; the activation/norm
    # variants ride the slow tier (prefill reuses the same layer math)
    @pytest.mark.parametrize("variant", [
        {},
        {"position_embedding_type": "rope", "num_query_groups": 2},
        pytest.param({"activation": "swiglu", "normalization": "rmsnorm"},
                     marks=pytest.mark.slow),
    ])
    def test_prefill_cache_matches_stepwise_decode(self, variant):
        cfg = _cfg(**variant)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        b, s = 2, 10
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)),
                             jnp.int32)

        cache = init_kv_cache(cfg, b, s)
        for i in range(s):
            _, cache = decode_step(params, tokens[:, i], cache, cfg)

        logits, pcache = prefill(params, tokens, cfg)
        np.testing.assert_allclose(
            np.asarray(pcache["k"]), np.asarray(cache["k"]),
            atol=2e-4, rtol=2e-4, err_msg=f"{variant} k")
        np.testing.assert_allclose(
            np.asarray(pcache["v"]), np.asarray(cache["v"]),
            atol=2e-4, rtol=2e-4, err_msg=f"{variant} v")
        np.testing.assert_array_equal(np.asarray(pcache["pos"]),
                                      np.full((b,), s))
        # prefill's last-token logits == the training forward's
        want = np.asarray(gpt_forward(params, tokens, cfg))[:, -1]
        np.testing.assert_allclose(np.asarray(logits), want,
                                   atol=2e-4, rtol=2e-4)

    def test_prefill_into_longer_cache_then_decode(self):
        """Teacher-forcing split point: prefill the first half, decode
        the second half stepwise — logits must match the full forward
        at every decoded position (extends TestDecodeParity across the
        prefill/decode seam)."""
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(1), cfg)
        rng = np.random.RandomState(1)
        b, s, tail = 2, 12, 5
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)),
                             jnp.int32)
        want = np.asarray(gpt_forward(params, tokens, cfg))

        head = s - tail
        logits, cache = prefill(params, tokens[:, :head], cfg, max_len=s)
        np.testing.assert_allclose(np.asarray(logits), want[:, head - 1],
                                   atol=2e-4, rtol=2e-4)
        for i in range(head, s):
            logits, cache = decode_step(params, tokens[:, i], cache, cfg)
            np.testing.assert_allclose(
                np.asarray(logits), want[:, i], atol=2e-4, rtol=2e-4,
                err_msg=f"position {i}")

    def test_ragged_prefill_matches_per_sequence(self):
        cfg = _cfg(position_embedding_type="rope")
        params = init_gpt_params(jax.random.PRNGKey(2), cfg)
        rng = np.random.RandomState(2)
        lens = [3, 7]
        batch, prompts = _ragged_batch(rng, cfg.vocab_size, lens)
        logits, cache = prefill(params, batch, cfg,
                                prompt_lens=jnp.asarray(lens))
        np.testing.assert_array_equal(np.asarray(cache["pos"]), lens)
        for i, p in enumerate(prompts):
            solo_logits, solo = prefill(params, jnp.asarray(p[None]), cfg)
            n = len(p)
            np.testing.assert_allclose(
                np.asarray(cache["k"])[:, i, :n],
                np.asarray(solo["k"])[:, 0],
                atol=2e-4, rtol=2e-4, err_msg=f"row {i} k")
            np.testing.assert_allclose(
                np.asarray(logits)[i], np.asarray(solo_logits)[0],
                atol=2e-4, rtol=2e-4, err_msg=f"row {i} logits")


class TestRaggedGenerate:
    def test_ragged_greedy_matches_unbatched(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(3), cfg)
        rng = np.random.RandomState(3)
        lens = [3, 8]          # each solo length is its own compile
        batch, prompts = _ragged_batch(rng, cfg.vocab_size, lens)
        new = 6
        out = generate(params, batch, cfg, max_new_tokens=new,
                       prompt_lens=jnp.asarray(lens))
        assert out.shape == (len(lens), max(lens) + new)
        for i, p in enumerate(prompts):
            solo = generate(params, jnp.asarray(p[None]), cfg,
                            max_new_tokens=new)
            np.testing.assert_array_equal(
                np.asarray(out)[i, lens[i]: lens[i] + new],
                np.asarray(solo)[0, lens[i]:],
                err_msg=f"row {i}")

    def test_ragged_gqa_rope_matches_unbatched(self):
        """GQA + rope through the [b] position vector — the riskiest
        combination (grouped cache heads x per-sequence rotary
        offsets)."""
        cfg = _cfg(position_embedding_type="rope", num_query_groups=2)
        params = init_gpt_params(jax.random.PRNGKey(4), cfg)
        rng = np.random.RandomState(4)
        lens = [2, 6]
        batch, prompts = _ragged_batch(rng, cfg.vocab_size, lens)
        new = 5
        out = generate(params, batch, cfg, max_new_tokens=new,
                       prompt_lens=jnp.asarray(lens))
        for i, p in enumerate(prompts):
            solo = generate(params, jnp.asarray(p[None]), cfg,
                            max_new_tokens=new)
            np.testing.assert_array_equal(
                np.asarray(out)[i, lens[i]: lens[i] + new],
                np.asarray(solo)[0, lens[i]:],
                err_msg=f"row {i}")

    def test_eos_stops_early_and_freezes_rows(self):
        from apex_tpu.observability import metrics as telemetry

        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(5), cfg)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        ref = np.asarray(generate(params, prompt, cfg, max_new_tokens=8))
        eos = int(ref[0, 3])   # the FIRST generated token: stops at once
        reg = telemetry.configure()
        try:
            out = generate(params, prompt, cfg, max_new_tokens=8,
                           eos_token_id=eos)
            # identical up to and including the emitted EOS, padding after
            np.testing.assert_array_equal(np.asarray(out)[0, :4],
                                          ref[0, :4])
            np.testing.assert_array_equal(np.asarray(out)[0, 4:], 0)
            # the while_loop exited early: fewer decode steps than budget
            steps = reg.counter("generate.decode_steps").value
            assert steps < 8, steps
        finally:
            telemetry.shutdown()


class TestTraceCounts:
    """The acceptance pin of the prefill/decode split: the prompt does
    NOT pass through the per-token decode loop."""

    def _counts(self, b, s, new):
        from apex_tpu.observability import metrics as telemetry

        cfg = _cfg(max_position_embeddings=max(24, s + new))
        params = init_gpt_params(jax.random.PRNGKey(6), cfg)
        rng = np.random.RandomState(6)
        prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)),
                             jnp.int32)
        reg = telemetry.configure()
        try:
            generate(params, prompt, cfg, max_new_tokens=new)
            return (reg.counter("generate.prefill_calls").value,
                    reg.counter("generate.decode_steps").value)
        finally:
            telemetry.shutdown()

    # new - 1 decode forwards: the first token comes from the prefill
    # logits, the last needs no decode behind it — the count scales
    # with the NEW tokens, never with the prompt length

    def test_prefill_once_decode_counts_new_tokens_only(self):
        prefills, steps = self._counts(b=2, s=16, new=5)
        assert prefills == 1
        assert steps == 5 - 1      # not s + new

    @pytest.mark.slow   # the [b=4, s=512] acceptance geometry; CI slow job
    def test_prefill_512_one_forward(self):
        prefills, steps = self._counts(b=4, s=512, new=8)
        assert prefills == 1
        assert steps == 8 - 1      # not 512 + 8


class TestSamplingSatellites:
    def test_negative_temperature_raises(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(7), cfg)
        prompt = jnp.asarray([[1, 2]], jnp.int32)
        with pytest.raises(ValueError, match="temperature"):
            generate(params, prompt, cfg, max_new_tokens=2,
                     temperature=-0.5)
        with pytest.raises(ValueError, match="temperature"):
            sample_logits(jnp.zeros((1, 8)), jax.random.PRNGKey(0),
                          temperature=-1.0)

    def test_topk_without_topp_restricts_support(self):
        """The lax.top_k fast path (no full vocab sort) must still
        confine sampling to the k best logits."""
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(2, 64), jnp.float32)
        top3 = np.argsort(np.asarray(logits), axis=-1)[:, -3:]
        for seed in range(20):
            toks = np.asarray(sample_logits(
                logits, jax.random.PRNGKey(seed), temperature=1.0,
                top_k=3))
            for row in range(2):
                assert toks[row] in top3[row], (seed, row, toks)
        # top_k=1 at full temperature degenerates to greedy
        np.testing.assert_array_equal(
            np.asarray(sample_logits(logits, jax.random.PRNGKey(0),
                                     temperature=1.0, top_k=1)),
            np.asarray(sample_logits(logits, jax.random.PRNGKey(0))))

    def test_cache_dtype_override(self):
        cfg = _cfg()   # fp32 compute
        cache = init_kv_cache(cfg, 2, 8)
        assert cache["k"].dtype == cfg.compute_dtype
        assert cache["pos"].shape == (2,)
        bf16 = init_kv_cache(cfg, 2, 8, cache_dtype=jnp.bfloat16)
        assert bf16["k"].dtype == jnp.bfloat16
        # decode runs with the downcast cache (casts at the einsum)
        params = init_gpt_params(jax.random.PRNGKey(8), cfg)
        logits, bf16 = decode_step(
            params, jnp.asarray([1, 2], jnp.int32), bf16, cfg)
        assert bf16["k"].dtype == jnp.bfloat16
        assert logits.shape == (2, cfg.vocab_size)
        out = generate(params, jnp.asarray([[1, 2, 3]], jnp.int32), cfg,
                       max_new_tokens=4, cache_dtype=jnp.bfloat16)
        assert out.shape == (1, 7)


class TestTopP:
    def test_nucleus_restricts_support(self):
        """top_p at its degenerate limit must behave greedily — even at
        temperature 1.0, where a no-op filter would sample the whole
        distribution and diverge from argmax almost surely."""
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.generate import generate
        from apex_tpu.models.transformer_lm import init_gpt_params

        cfg = TransformerConfig(
            num_layers=1, hidden_size=32, num_attention_heads=2,
            vocab_size=32, max_position_embeddings=16,
            compute_dtype=jnp.float32)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray([[1, 2]], jnp.int32)

        greedy = generate(params, prompt, cfg, max_new_tokens=6)
        for tp in (0.0, 1e-6):
            # full temperature: only the nucleus filter itself can make
            # this match argmax — a no-op regression fails loudly
            nucleus = generate(params, prompt, cfg, max_new_tokens=6,
                               temperature=1.0, top_p=tp,
                               rng=jax.random.PRNGKey(3))
            np.testing.assert_array_equal(
                np.asarray(greedy), np.asarray(nucleus),
                err_msg=f"top_p={tp}")

    def test_top_p_with_top_k_composes(self):
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.generate import generate
        from apex_tpu.models.transformer_lm import init_gpt_params

        cfg = TransformerConfig(
            num_layers=1, hidden_size=32, num_attention_heads=2,
            vocab_size=32, max_position_embeddings=16,
            compute_dtype=jnp.float32)
        params = init_gpt_params(jax.random.PRNGKey(1), cfg)
        prompt = jnp.asarray([[3, 4, 5]], jnp.int32)
        out = generate(params, prompt, cfg, max_new_tokens=5,
                       temperature=0.8, top_k=8, top_p=0.9,
                       rng=jax.random.PRNGKey(7))
        assert out.shape == (1, 8)
        assert bool(jnp.all((out >= 0) & (out < cfg.vocab_size)))
