"""KV-cache decoding: teacher-forcing parity with the training forward."""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import decode_step, generate, init_kv_cache
from apex_tpu.models.transformer_lm import gpt_forward, init_gpt_params


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 24)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


VARIANTS = [
    {},
    {"position_embedding_type": "rope"},
    {"activation": "swiglu"},
    {"activation": "gelu_tanh"},
    {"apply_residual_connection_post_layernorm": True},
    {"normalization": "rmsnorm"},
]


class TestDecodeParity:
    @pytest.mark.parametrize("variant", VARIANTS)
    def test_stepwise_logits_match_full_forward(self, variant):
        """Feeding the gold sequence token-by-token through the cached
        decode must reproduce the training forward's logits at every
        position — the strongest possible pin of the cache math."""
        cfg = _cfg(**variant)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        b, s = 2, 12
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (b, s)),
                             jnp.int32)

        want = np.asarray(gpt_forward(params, tokens, cfg))

        cache = init_kv_cache(cfg, b, s)
        step = jax.jit(lambda t, c: decode_step(params, t, c, cfg))
        for i in range(s):
            logits, cache = step(tokens[:, i], cache)
            np.testing.assert_allclose(
                np.asarray(logits), want[:, i], atol=2e-4, rtol=2e-4,
                err_msg=f"{variant} position {i}")


class TestGenerate:
    def test_greedy_matches_argmax_of_forward(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(1), cfg)
        rng = np.random.RandomState(1)
        prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 4)),
                             jnp.int32)
        out = generate(params, prompt, cfg, max_new_tokens=6)
        assert out.shape == (2, 10)
        np.testing.assert_array_equal(np.asarray(out[:, :4]),
                                      np.asarray(prompt))
        # reference: greedy re-decode with the full forward each step
        seq = np.asarray(prompt)
        for _ in range(6):
            logits = np.asarray(gpt_forward(
                params, jnp.asarray(seq, jnp.int32), cfg))
            nxt = logits[:, -1].argmax(-1).astype(np.int32)
            seq = np.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), seq)

    def test_sampling_is_seeded_and_topk_restricts(self):
        cfg = _cfg()
        params = init_gpt_params(jax.random.PRNGKey(2), cfg)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        a = generate(params, prompt, cfg, max_new_tokens=8,
                     temperature=1.0, top_k=5, rng=jax.random.PRNGKey(7))
        b = generate(params, prompt, cfg, max_new_tokens=8,
                     temperature=1.0, top_k=5, rng=jax.random.PRNGKey(7))
        c = generate(params, prompt, cfg, max_new_tokens=8,
                     temperature=1.0, top_k=5, rng=jax.random.PRNGKey(8))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_imported_hf_weights_generate(self):
        transformers = pytest.importorskip("transformers")
        torch = pytest.importorskip("torch")
        import os
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tools.import_hf import config_from_hf, params_from_hf

        hfc = transformers.GPT2Config(
            n_layer=2, n_embd=64, n_head=4, vocab_size=100,
            n_positions=32, resid_pdrop=0.0, embd_pdrop=0.0,
            attn_pdrop=0.0)
        torch.manual_seed(3)
        hf = transformers.GPT2LMHeadModel(hfc).eval()
        cfg = config_from_hf(hfc, compute_dtype=jnp.float32)
        params = params_from_hf(hf.state_dict(), cfg)

        prompt = jnp.asarray([[5, 17, 31]], jnp.int32)
        ours = generate(params, prompt, cfg, max_new_tokens=5,
                        vocab_limit=hfc.vocab_size)
        with torch.no_grad():
            theirs = hf.generate(
                torch.asarray(np.asarray(prompt)), max_new_tokens=5,
                do_sample=False, pad_token_id=0)
        np.testing.assert_array_equal(np.asarray(ours),
                                      theirs.numpy())


    def test_vocab_limit_masks_padded_ids(self):
        cfg = _cfg(vocab_size=128)
        params = init_gpt_params(jax.random.PRNGKey(5), cfg)
        prompt = jnp.asarray([[1, 2]], jnp.int32)
        out = generate(params, prompt, cfg, max_new_tokens=10,
                       temperature=1.0, rng=jax.random.PRNGKey(0),
                       vocab_limit=7)
        assert np.asarray(out)[:, 2:].max() < 7

    def test_overflowing_learned_positions_raise(self):
        cfg = _cfg(max_position_embeddings=8)
        params = init_gpt_params(jax.random.PRNGKey(6), cfg)
        prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        with pytest.raises(ValueError, match="exceeds"):
            generate(params, prompt, cfg, max_new_tokens=8)

    def test_moe_and_padding_configs_rejected(self):
        cfg = _cfg(num_experts=2)
        params = init_gpt_params(jax.random.PRNGKey(7), cfg)
        with pytest.raises(ValueError, match="MoE"):
            decode_step(params, jnp.asarray([1], jnp.int32),
                        init_kv_cache(cfg, 1, 4), cfg)
        cfg2 = _cfg(attn_mask_type="padding")
        params2 = init_gpt_params(jax.random.PRNGKey(8), cfg2)
        with pytest.raises(ValueError, match="causal"):
            decode_step(params2, jnp.asarray([1], jnp.int32),
                        init_kv_cache(cfg2, 1, 4), cfg2)


class TestTopP:
    def test_nucleus_restricts_support(self):
        """top_p at its degenerate limit must behave greedily — even at
        temperature 1.0, where a no-op filter would sample the whole
        distribution and diverge from argmax almost surely."""
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.generate import generate
        from apex_tpu.models.transformer_lm import init_gpt_params

        cfg = TransformerConfig(
            num_layers=1, hidden_size=32, num_attention_heads=2,
            vocab_size=32, max_position_embeddings=16,
            compute_dtype=jnp.float32)
        params = init_gpt_params(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray([[1, 2]], jnp.int32)

        greedy = generate(params, prompt, cfg, max_new_tokens=6)
        for tp in (0.0, 1e-6):
            # full temperature: only the nucleus filter itself can make
            # this match argmax — a no-op regression fails loudly
            nucleus = generate(params, prompt, cfg, max_new_tokens=6,
                               temperature=1.0, top_p=tp,
                               rng=jax.random.PRNGKey(3))
            np.testing.assert_array_equal(
                np.asarray(greedy), np.asarray(nucleus),
                err_msg=f"top_p={tp}")

    def test_top_p_with_top_k_composes(self):
        from apex_tpu.models.config import TransformerConfig
        from apex_tpu.models.generate import generate
        from apex_tpu.models.transformer_lm import init_gpt_params

        cfg = TransformerConfig(
            num_layers=1, hidden_size=32, num_attention_heads=2,
            vocab_size=32, max_position_embeddings=16,
            compute_dtype=jnp.float32)
        params = init_gpt_params(jax.random.PRNGKey(1), cfg)
        prompt = jnp.asarray([[3, 4, 5]], jnp.int32)
        out = generate(params, prompt, cfg, max_new_tokens=5,
                       temperature=0.8, top_k=8, top_p=0.9,
                       rng=jax.random.PRNGKey(7))
        assert out.shape == (1, 8)
        assert bool(jnp.all((out >= 0) & (out < cfg.vocab_size)))
