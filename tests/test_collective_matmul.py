"""Ring collective-matmul (ops/collective_matmul) on the 8-device mesh.

Parity contract (the ISSUE-5 acceptance semantics, also enforced by the
driver's ``tp_overlap`` dryrun phase): every overlapped ring form must
match its monolithic counterpart — forward AND backward — to fp32-tight
tolerances, with bf16 inputs allowed bf16-rounding slack.  Plus the
telemetry invariant: each ring loop books exactly ``n−1`` hops, so
``collectives.ring.hops == (tp−1) × collectives.ring.calls`` on any
fixed-tp program.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import apex_tpu.observability as obs
from apex_tpu.ops import collective_matmul as cm

shard_map = jax.shard_map


@pytest.fixture(autouse=True)
def _clean_telemetry():
    yield
    obs.shutdown()


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("tp",))


def _mm_ref(x, w):
    # the monolithic math with the SAME accumulation contract as the ring
    # (_mm: fp32 accumulate, result_type output)
    y = jax.lax.dot_general(
        x, w, dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return y.astype(jnp.result_type(x, w))


def _tols(dtype):
    # fp32 tight; bf16 pays output rounding (and CPU bf16 matmul noise)
    return ((1e-5, 1e-5) if dtype == jnp.float32 else (5e-2, 5e-2))


def _rand(rng, shape, dtype):
    return jnp.asarray(rng.randn(*shape), dtype)


class TestRingPrimitives:
    """ring_all_gather / ring_reduce_scatter vs the monolithic lax ops."""

    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_ring_all_gather_fwd_bwd(self, n):
        rng = np.random.RandomState(0)
        x = _rand(rng, (n * 2, 3), jnp.float32)
        cot = _rand(rng, (n * 2, 3), jnp.float32)
        mesh = _mesh(n)

        def ring(x_):
            return shard_map(
                functools.partial(cm.ring_all_gather, axis_name="tp"),
                mesh=mesh, in_specs=P("tp"), out_specs=P())(x_)

        def mono(x_):
            return shard_map(
                lambda v: jax.lax.all_gather(v, "tp", axis=0, tiled=True),
                mesh=mesh, in_specs=P("tp"), out_specs=P())(x_)

        np.testing.assert_allclose(np.asarray(ring(x)), np.asarray(mono(x)),
                                   rtol=0, atol=0)
        # autodiff transposes the ppermute ring into the reversed ring
        g_ring = jax.grad(lambda v: jnp.vdot(ring(v), cot))(x)
        g_mono = jax.grad(lambda v: jnp.vdot(mono(v), cot))(x)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_mono),
                                   rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("n", [2, 8])
    def test_ring_reduce_scatter_fwd_bwd(self, n):
        rng = np.random.RandomState(1)
        x = _rand(rng, (n * 2, 3), jnp.float32)
        cot = _rand(rng, (n * 2, 3), jnp.float32)
        mesh = _mesh(n)

        def ring(x_):
            # replicate in, shard-summed out: each rank contributes the
            # full x (rank-scaled so shards genuinely differ)
            def f(v):
                from apex_tpu.utils.collectives import pvary

                v = pvary(v, "tp") * (jax.lax.axis_index("tp") + 1.0)
                return cm.ring_reduce_scatter(v, "tp", dim=0)

            return shard_map(f, mesh=mesh, in_specs=P(),
                             out_specs=P("tp"))(x_)

        def mono(x_):
            def f(v):
                from apex_tpu.utils.collectives import pvary

                v = pvary(v, "tp") * (jax.lax.axis_index("tp") + 1.0)
                return jax.lax.psum_scatter(v, "tp", scatter_dimension=0,
                                            tiled=True)

            return shard_map(f, mesh=mesh, in_specs=P(),
                             out_specs=P("tp"))(x_)

        np.testing.assert_allclose(np.asarray(ring(x)), np.asarray(mono(x)),
                                   rtol=1e-6, atol=1e-6)
        g_ring = jax.grad(lambda v: jnp.vdot(ring(v), cot))(x)
        g_mono = jax.grad(lambda v: jnp.vdot(mono(v), cot))(x)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_mono),
                                   rtol=1e-6, atol=1e-6)

    def test_indivisible_dim_raises(self):
        mesh = _mesh(8)
        x = jnp.ones((9, 2))
        with pytest.raises(ValueError, match="not divisible"):
            shard_map(
                functools.partial(cm.ring_reduce_scatter, axis_name="tp"),
                mesh=mesh, in_specs=P(), out_specs=P("tp"))(x)


class TestAllGatherMatmul:
    """all_gather(x) @ w as the overlapped ring, fwd + custom-vjp bwd."""

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("n", [2, 8])
    def test_fwd_bwd_parity(self, dtype, n):
        rng = np.random.RandomState(2)
        s, b, k, p = n * 2, 3, 16, n * 4
        x = _rand(rng, (s, b, k), dtype)      # sequence-sharded input
        w = _rand(rng, (k, p), dtype)         # column-sharded weight
        cot = _rand(rng, (s, b, p), jnp.float32)
        mesh = _mesh(n)
        rtol, atol = _tols(dtype)

        ring = shard_map(
            functools.partial(cm.all_gather_matmul, axis_name="tp"),
            mesh=mesh, in_specs=(P("tp"), P(None, "tp")),
            out_specs=P(None, None, "tp"))

        np.testing.assert_allclose(
            np.asarray(ring(x, w), np.float32),
            np.asarray(_mm_ref(x, w), np.float32), rtol=rtol, atol=atol)

        def loss_ring(x_, w_):
            return jnp.vdot(ring(x_, w_).astype(jnp.float32), cot)

        def loss_mono(x_, w_):
            return jnp.vdot(_mm_ref(x_, w_).astype(jnp.float32), cot)

        gx_r, gw_r = jax.grad(loss_ring, argnums=(0, 1))(x, w)
        gx_m, gw_m = jax.grad(loss_mono, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx_r, np.float32),
                                   np.asarray(gx_m, np.float32),
                                   rtol=rtol, atol=atol)
        np.testing.assert_allclose(np.asarray(gw_r, np.float32),
                                   np.asarray(gw_m, np.float32),
                                   rtol=rtol, atol=max(atol, 1e-4))

    def test_contraction_mismatch_raises(self):
        with pytest.raises(ValueError, match="contraction mismatch"):
            cm.all_gather_matmul(jnp.ones((4, 8)), jnp.ones((16, 4)), "tp")


class TestMatmulReduceScatter:
    """reduce_scatter(x @ w) as the rotating-accumulator ring."""

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("n", [2, 8])
    def test_fwd_bwd_parity(self, dtype, n):
        rng = np.random.RandomState(3)
        s, b, k, p = n * 2, 3, n * 4, 12
        x = _rand(rng, (s, b, k), dtype)      # contraction tp-sharded
        w = _rand(rng, (k, p), dtype)         # row-sharded weight
        cot = _rand(rng, (s, b, p), jnp.float32)
        mesh = _mesh(n)
        rtol, atol = _tols(dtype)

        ring = shard_map(
            functools.partial(cm.matmul_reduce_scatter, axis_name="tp"),
            mesh=mesh, in_specs=(P(None, None, "tp"), P("tp")),
            out_specs=P("tp"))

        np.testing.assert_allclose(
            np.asarray(ring(x, w), np.float32),
            np.asarray(_mm_ref(x, w), np.float32), rtol=rtol, atol=atol)

        def loss_ring(x_, w_):
            return jnp.vdot(ring(x_, w_).astype(jnp.float32), cot)

        def loss_mono(x_, w_):
            return jnp.vdot(_mm_ref(x_, w_).astype(jnp.float32), cot)

        gx_r, gw_r = jax.grad(loss_ring, argnums=(0, 1))(x, w)
        gx_m, gw_m = jax.grad(loss_mono, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx_r, np.float32),
                                   np.asarray(gx_m, np.float32),
                                   rtol=rtol, atol=atol)
        np.testing.assert_allclose(np.asarray(gw_r, np.float32),
                                   np.asarray(gw_m, np.float32),
                                   rtol=rtol, atol=max(atol, 1e-4))

    def test_matmul_all_reduce_fwd_bwd(self):
        n = 8
        rng = np.random.RandomState(4)
        s, b, k, p = 8, 2, n * 4, 12
        x = _rand(rng, (s, b, k), jnp.float32)
        w = _rand(rng, (k, p), jnp.float32)
        cot = _rand(rng, (s, b, p), jnp.float32)
        mesh = _mesh(n)

        ring = shard_map(
            functools.partial(cm.matmul_all_reduce, axis_name="tp"),
            mesh=mesh, in_specs=(P(None, None, "tp"), P("tp")),
            out_specs=P())

        np.testing.assert_allclose(
            np.asarray(ring(x, w)), np.asarray(_mm_ref(x, w)),
            rtol=1e-5, atol=1e-5)
        gx_r, gw_r = jax.grad(
            lambda a, b_: jnp.vdot(ring(a, b_), cot), argnums=(0, 1))(x, w)
        gx_m, gw_m = jax.grad(
            lambda a, b_: jnp.vdot(_mm_ref(a, b_), cot),
            argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx_r), np.asarray(gx_m),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw_r), np.asarray(gw_m),
                                   rtol=1e-5, atol=1e-4)


class TestRingTelemetry:
    """collectives.ring.* trace-time invariant: hops == (tp−1) × calls."""

    def test_hops_equal_tp_minus_one_per_call(self):
        n = 8
        reg = obs.configure(stderr_summary=False)
        rng = np.random.RandomState(5)
        x = _rand(rng, (n * 2, 2, 16), jnp.float32)
        w = _rand(rng, (16, n * 4), jnp.float32)
        mesh = _mesh(n)

        c0 = reg.counter("collectives.ring.calls").value
        h0 = reg.counter("collectives.ring.hops").value
        b0 = reg.counter("collectives.ring.bytes").value
        ring = shard_map(
            functools.partial(cm.all_gather_matmul, axis_name="tp"),
            mesh=mesh, in_specs=(P("tp"), P(None, "tp")),
            out_specs=P(None, None, "tp"))
        # fwd trace + bwd trace: every ring loop, in either direction,
        # must book exactly n−1 hops
        jax.grad(lambda a, b_: jnp.sum(ring(a, b_)), argnums=(0, 1))(x, w)
        calls = reg.counter("collectives.ring.calls").value - c0
        hops = reg.counter("collectives.ring.hops").value - h0
        bys = reg.counter("collectives.ring.bytes").value - b0
        assert calls > 0
        assert hops == (n - 1) * calls
        assert bys > 0

    def test_ppermute_counters_ride_along(self):
        n = 8
        reg = obs.configure(stderr_summary=False)
        x = jnp.ones((n * 2, 4))
        mesh = _mesh(n)
        p0 = reg.counter("collectives.ppermute.calls").value
        shard_map(
            functools.partial(cm.ring_all_gather, axis_name="tp"),
            mesh=mesh, in_specs=P("tp"), out_specs=P())(x)
        # n−1 hops, each through the counted ppermute wrapper
        assert (reg.counter("collectives.ppermute.calls").value - p0
                == n - 1)


class TestOverlapScope:
    def test_tri_state_resolution(self):
        assert cm.overlap_enabled(True) is True
        assert cm.overlap_enabled(False) is False
        assert cm.overlap_enabled(None) is False        # default off
        with cm.overlap_scope(True):
            assert cm.overlap_enabled(None) is True
            assert cm.overlap_enabled(False) is False   # explicit wins
            with cm.overlap_scope(False):
                assert cm.overlap_enabled(None) is False
            assert cm.overlap_enabled(None) is True
        assert cm.overlap_enabled(None) is False

    def test_scope_restores_on_exception(self):
        with pytest.raises(RuntimeError):
            with cm.overlap_scope(True):
                raise RuntimeError("boom")
        assert cm.overlap_enabled(None) is False


class TestMappingsOverlap:
    """The sequence-parallel mappings under overlap_comm ride the ring in
    BOTH directions of the fwd/bwd table and stay numerically identical
    to the monolithic collectives."""

    def test_gather_from_sp_region_overlap_parity(self):
        from apex_tpu.transformer import tensor_parallel as tp
        from apex_tpu.transformer import parallel_state

        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=8)
        try:
            x = jnp.arange(16.0).reshape(8, 2)

            def run(overlap):
                @functools.partial(shard_map, mesh=mesh, in_specs=P("tp"),
                                   out_specs=P("tp"))
                def grads(x_):
                    def f(x__):
                        full = tp.gather_from_sequence_parallel_region(
                            x__, True, "tp", overlap)
                        w = jax.lax.axis_index("tp") + 1.0
                        return jnp.sum(full) * w

                    return jax.grad(f)(x_)

                @functools.partial(shard_map, mesh=mesh, in_specs=P("tp"),
                                   out_specs=P())
                def fwd(x_):
                    return tp.gather_from_sequence_parallel_region(
                        x_, True, "tp", overlap)

                return fwd(x), grads(x)

            f_on, g_on = run(True)
            f_off, g_off = run(False)
            np.testing.assert_allclose(np.asarray(f_on), np.asarray(f_off))
            np.testing.assert_allclose(np.asarray(g_on), np.asarray(g_off))
            # the bwd reduce-scatter sums rank+1 over 8 ranks = 36
            np.testing.assert_allclose(np.asarray(g_on),
                                       np.full((8, 2), 36.0))
        finally:
            parallel_state.destroy_model_parallel()

    def test_reduce_scatter_to_sp_region_overlap_parity(self):
        from apex_tpu.transformer import tensor_parallel as tp
        from apex_tpu.transformer import parallel_state

        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=8)
        try:
            x = jnp.arange(16.0).reshape(8, 2)

            def run(overlap):
                @functools.partial(shard_map, mesh=mesh, in_specs=P(),
                                   out_specs=P("tp"))
                def fwd(x_):
                    y = tp.copy_to_tensor_model_parallel_region(x_)
                    return tp.reduce_scatter_to_sequence_parallel_region(
                        y, "tp", overlap)

                @functools.partial(shard_map, mesh=mesh, in_specs=P(),
                                   out_specs=P("tp"))
                def grads(x_):
                    def f(x__):
                        y = tp.reduce_scatter_to_sequence_parallel_region(
                            x__, "tp", overlap)
                        return jnp.sum(y * (jax.lax.axis_index("tp") + 1.0))

                    return jax.grad(f)(x_)[None][0]

                return fwd(x), grads(x)

            f_on, g_on = run(True)
            f_off, g_off = run(False)
            np.testing.assert_allclose(np.asarray(f_on), np.asarray(f_off))
            np.testing.assert_allclose(np.asarray(g_on), np.asarray(g_off))
            np.testing.assert_allclose(np.asarray(f_on), np.asarray(x) * 8)
        finally:
            parallel_state.destroy_model_parallel()


class TestGspmdIslandFallback:
    """The GSPMD wrappers return None whenever the ring path does not
    apply, so layer call sites always have the monolithic fallback."""

    def test_disabled_returns_none(self):
        x, w = jnp.ones((8, 2, 4)), jnp.ones((4, 8))
        assert cm.sequence_parallel_matmul(x, w, mode="gather",
                                           enable=False) is None
        assert cm.gspmd_row_parallel_matmul(x, w, enable=False) is None

    def test_no_mesh_returns_none(self):
        x, w = jnp.ones((8, 2, 4)), jnp.ones((4, 8))
        assert cm.sequence_parallel_matmul(x, w, mode="gather",
                                           enable=True) is None
        assert cm.gspmd_row_parallel_matmul(x, w, enable=True) is None

    def test_bad_mode_raises(self):
        x, w = jnp.ones((8, 4)), jnp.ones((4, 8))
        with pytest.raises(ValueError, match="mode"):
            cm.sequence_parallel_matmul(x, w, mode="nope", enable=True)
