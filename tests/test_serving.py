"""Continuous-batching serving engine: lifecycle, parity, metrics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import generate
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.serving import (
    Request, ServingEngine, SlotPool, default_buckets, pad_prompt,
    pick_bucket)


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestBatchingHelpers:
    def test_default_buckets_ladder(self):
        assert default_buckets(256) == (32, 64, 128, 256)
        assert default_buckets(100) == (32, 64, 100)
        assert default_buckets(16) == (16,)

    def test_pick_bucket(self):
        assert pick_bucket(1, (8, 16)) == 8
        assert pick_bucket(8, (8, 16)) == 8
        assert pick_bucket(9, (8, 16)) == 16
        with pytest.raises(ValueError, match="exceeds"):
            pick_bucket(17, (8, 16))

    def test_pad_prompt(self):
        out = pad_prompt(np.asarray([1, 2, 3]), 8)
        np.testing.assert_array_equal(out, [1, 2, 3, 0, 0, 0, 0, 0])
        with pytest.raises(ValueError, match="exceeds"):
            pad_prompt(np.arange(9), 8)

    def test_slot_pool(self):
        pool = SlotPool(2)
        a, b = pool.claim(), pool.claim()
        assert {a, b} == {0, 1}
        assert pool.claim() is None
        pool.release(a)
        assert pool.n_free == 1 and pool.n_active == 1
        assert pool.claim() == a
        with pytest.raises(ValueError, match="not active"):
            pool.release(7)

    def test_request_validation(self):
        with pytest.raises(ValueError, match="empty"):
            Request(prompt=np.asarray([], np.int32))
        with pytest.raises(ValueError, match="max_new_tokens"):
            Request(prompt=np.asarray([1]), max_new_tokens=0)
        with pytest.raises(ValueError, match="temperature"):
            Request(prompt=np.asarray([1]), temperature=-1.0)


class TestEngineLifecycle:
    def test_mixed_lengths_match_generate(self, model):
        """More requests than slots, ragged lengths, greedy: every
        response must be token-identical to generate() — continuous
        batching must not change the math.  The oracle is ONE ragged
        generate call; its own parity against per-sequence decoding is
        pinned in tests/test_generate.py."""
        cfg, params = model
        rng = np.random.RandomState(0)
        lens = [3, 7, 5]
        new = 6
        prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
                   for n in lens]
        batch = np.zeros((len(lens), max(lens)), np.int32)
        for i, p in enumerate(prompts):
            batch[i, : len(p)] = p
        want = np.asarray(generate(
            params, jnp.asarray(batch), cfg, max_new_tokens=new,
            prompt_lens=jnp.asarray(lens)))

        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,))
        resps = engine.run([dict(prompt=p, max_new_tokens=new)
                            for p in prompts])
        assert [r.request_id for r in resps] == [0, 1, 2]
        for r, n in zip(resps, lens):
            np.testing.assert_array_equal(
                r.tokens, want[r.request_id, n: n + new],
                err_msg=f"request {r.request_id}")
            assert r.finish_reason == "length"
            assert r.decode_steps == new - 1
        assert engine.idle

    def test_continuous_admission_overlaps_decodes(self, model):
        """A freed slot admits the next request while others are still
        decoding: the total decode-step count must be far below the
        batch-serial sum."""
        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        rng = np.random.RandomState(1)
        budgets = [2, 10, 4]
        prompts = [rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)
                   for _ in budgets]
        reg = telemetry.configure()
        try:
            engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                                   prompt_buckets=(8,))
            resps = engine.run([
                dict(prompt=p, max_new_tokens=m)
                for p, m in zip(prompts, budgets)])
            assert len(resps) == 3
            steps = reg.counter("serving.decode_steps").value
            # serial lower bound would be sum(m - 1) = 13; overlapped
            # lanes need at most max-budget + the admission step
            assert steps <= 10, steps
            assert reg.counter("serving.prefill_calls").value == 3
        finally:
            telemetry.shutdown()

    def test_eos_completion_frees_slot(self, model):
        cfg, params = model
        p = np.asarray([5, 9, 13], np.int32)
        ref = np.asarray(generate(params, jnp.asarray(p[None]), cfg,
                                  max_new_tokens=6))[0, 3:]
        eos = int(ref[1])   # stop after the 2nd generated token
        engine = ServingEngine(params, cfg, max_slots=1, max_len=32,
                               prompt_buckets=(8,))
        resps = engine.run([dict(prompt=p, max_new_tokens=6,
                                 eos_token_id=eos)])
        (r,) = resps
        assert r.finish_reason == "eos"
        assert r.tokens[-1] == eos
        assert r.tokens.size <= 6
        np.testing.assert_array_equal(r.tokens, ref[: r.tokens.size])
        assert engine.idle and engine.stats()["free_slots"] == 1

    def test_submit_validation(self, model):
        cfg, params = model
        engine = ServingEngine(params, cfg, max_slots=1, max_len=16,
                               prompt_buckets=(8,))
        with pytest.raises(ValueError, match="max_len"):
            engine.submit(np.arange(8), max_new_tokens=9)
        with pytest.raises(ValueError, match="exceeds the largest"):
            engine.submit(np.arange(9), max_new_tokens=1)

    def test_metrics_stream(self, model):
        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        rng = np.random.RandomState(2)
        reg = telemetry.configure()
        try:
            engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                                   prompt_buckets=(8,))
            engine.run([
                dict(prompt=rng.randint(0, cfg.vocab_size, (4,)),
                     max_new_tokens=3) for _ in range(3)])
            summ = reg.summary()
            assert summ["counters"]["serving.requests"] == 3
            assert summ["counters"]["serving.prefill_calls"] == 3
            assert summ["counters"]["serving.tokens_generated"] == 9
            assert summ["histograms"]["serving.prefill_ms"]["count"] == 3
            # drained engine: occupancy and queue gauges end at zero
            assert summ["gauges"]["serving.slot_occupancy"] == 0.0
            assert summ["gauges"]["serving.queue_depth"] == 0.0
        finally:
            telemetry.shutdown()

    def test_bf16_cache_and_temperature_mix(self, model):
        """bf16 slot caches under the fp32 compute config (the serving
        memory win) + a per-request temperature mix in one batch."""
        cfg, params = model
        rng = np.random.RandomState(3)
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,),
                               cache_dtype=jnp.bfloat16)
        assert engine.cache["k"].dtype == jnp.bfloat16
        resps = engine.run([
            dict(prompt=rng.randint(0, cfg.vocab_size, (5,)),
                 max_new_tokens=4, temperature=0.0),
            dict(prompt=rng.randint(0, cfg.vocab_size, (5,)),
                 max_new_tokens=4, temperature=0.9),
        ])
        assert len(resps) == 2
        for r in resps:
            assert r.tokens.size == 4
            assert ((r.tokens >= 0) & (r.tokens < cfg.vocab_size)).all()


class TestServingTelemetry:
    """ISSUE 4 satellite: serving.* emission with the registry
    unconfigured (no-op, no crash) and configured mid-flight."""

    def test_unconfigured_engine_is_noop_and_does_not_crash(self, model):
        from apex_tpu.observability import metrics as telemetry
        from apex_tpu.observability.metrics import NOOP_METRIC

        cfg, params = model
        assert not telemetry.enabled()
        engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                               prompt_buckets=(8,))
        resps = engine.run([
            dict(prompt=np.asarray([1, 2, 3]), max_new_tokens=3),
            dict(prompt=np.asarray([4, 5]), max_new_tokens=2),
        ])
        assert len(resps) == 2
        # the whole run left telemetry on the no-op fast path
        assert not telemetry.enabled()
        assert telemetry.counter("serving.requests") is NOOP_METRIC

    def test_healthy_backlog_fires_no_admission_stall(self, model):
        """Neither a submit burst before the first step nor sustained
        short-request traffic (completions free slots every step while
        the backlog waits for the NEXT admission) is a stall: the
        detector samples post-admission, the one instant where free
        slots + queued work is abnormal.  24 two-token requests on 2
        slots drive well past the detector's patience window."""
        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        rng = np.random.RandomState(11)
        reg = telemetry.configure()
        try:
            engine = ServingEngine(params, cfg, max_slots=2, max_len=32,
                                   prompt_buckets=(8,))
            for _ in range(24):     # >> detector patience, all queued
                engine.submit(rng.randint(0, cfg.vocab_size, (4,)),
                              max_new_tokens=2)
            assert not reg.detectors.anomalies
            steps = 0
            while not engine.idle:
                engine.step()
                steps += 1
            assert steps > 8        # really exceeded patience
            # queue-detector specifically: wall-clock-noise kinds
            # (throughput) are out of scope for this test
            stalls = [a.kind for a in reg.detectors.anomalies
                      if a.kind.startswith("serving_")]
            assert stalls == []
        finally:
            telemetry.shutdown()

    def test_prefill_failure_leaks_no_slot_and_keeps_request(
            self, model, monkeypatch):
        """A transient prefill failure (device OOM, XLA error) must
        not leak the claimed slot or drop the popped request: the
        engine stays drainable and a retry succeeds."""
        import apex_tpu.serving.engine as engine_mod

        cfg, params = model
        engine = ServingEngine(params, cfg, max_slots=1, max_len=32,
                               prompt_buckets=(8,))
        real_prefill = engine_mod.prefill
        boom = {"armed": True}

        def flaky_prefill(*a, **kw):
            if boom["armed"]:
                boom["armed"] = False
                raise RuntimeError("transient prefill failure")
            return real_prefill(*a, **kw)

        monkeypatch.setattr(engine_mod, "prefill", flaky_prefill)
        rid = engine.submit(np.asarray([3, 1, 4]), max_new_tokens=3)
        with pytest.raises(RuntimeError, match="transient"):
            engine.step()
        assert engine.stats()["free_slots"] == 1     # slot released
        assert engine.stats()["queued"] == 1         # request kept
        assert not engine.idle
        resps = engine.run([])                       # retry drains it
        assert [r.request_id for r in resps] == [rid]
        assert resps[0].tokens.size == 3
        assert engine.idle and engine.stats()["free_slots"] == 1

    def test_configured_mid_flight_picks_up_serving_metrics(
            self, model, tmp_path):
        import json

        from apex_tpu.observability import metrics as telemetry

        cfg, params = model
        rng = np.random.RandomState(7)
        engine = ServingEngine(params, cfg, max_slots=1, max_len=32,
                               prompt_buckets=(8,))
        # phase 1: dark — a request runs with telemetry off
        engine.run([dict(prompt=rng.randint(0, cfg.vocab_size, (4,)),
                         max_new_tokens=2)])
        # phase 2: configure mid-flight; later requests are counted
        path = tmp_path / "serving.jsonl"
        reg = telemetry.configure(jsonl_path=str(path))
        try:
            engine.run([
                dict(prompt=rng.randint(0, cfg.vocab_size, (4,)),
                     max_new_tokens=3) for _ in range(2)])
            summ = reg.summary()
            assert summ["counters"]["serving.requests"] == 2
            assert summ["counters"]["serving.prefill_calls"] == 2
            assert summ["counters"]["serving.tokens_generated"] == 6
            assert summ["histograms"]["serving.request_ms"]["count"] == 2
        finally:
            telemetry.shutdown()
        recs = [json.loads(line) for line in open(path)]
        begins = [r for r in recs if r.get("type") == "event"
                  and r.get("name") == "serving.request.begin"]
        ends = [r for r in recs if r.get("type") == "event"
                and r.get("name") == "serving.request.end"]
        # request ids continue from the dark phase (id 0 ran dark)
        assert [b["data"]["id"] for b in begins] == [1, 2]
        assert sorted(e["data"]["id"] for e in ends) == [1, 2]
        assert all(e["data"]["finish_reason"] == "length" for e in ends)
        assert all(e["data"]["latency_ms"] > 0 for e in ends)


@pytest.mark.slow   # serving soak: many mixed requests; CI slow job
class TestServingSoak:
    def test_soak_mixed_traffic(self, model):
        cfg, params = model
        rng = np.random.RandomState(4)
        engine = ServingEngine(params, cfg, max_slots=3, max_len=64)
        reqs = []
        for i in range(16):
            n = int(rng.randint(2, 24))
            reqs.append(dict(
                prompt=rng.randint(0, cfg.vocab_size, (n,)),
                max_new_tokens=int(rng.randint(1, 12)),
                temperature=float(rng.choice([0.0, 0.8])),
                eos_token_id=int(rng.randint(0, cfg.vocab_size))
                if i % 3 == 0 else None,
            ))
        resps = engine.run(reqs)
        assert len(resps) == 16
        assert engine.idle
        for r, kw in zip(resps, reqs):
            assert 1 <= r.tokens.size <= kw["max_new_tokens"]
            if r.finish_reason == "eos":
                assert r.tokens[-1] == kw["eos_token_id"]
            elif kw["eos_token_id"] is None:
                assert r.finish_reason == "length"
                assert r.tokens.size == kw["max_new_tokens"]
