"""Multi-tenant LoRA serving (ISSUE 20): heterogeneous-adapter batched
decode through the refcounted slab pool.

The headline pin: a 64-distinct-adapter batch decoded through ONE
engine (ragged grouped matmuls over the stacked slabs, adapter slots
churning through a 6-slot pool) emits greedy tokens identical to each
tenant's merged-weights (``merge_lora``) solo oracle — on both cache
layouts and under speculative decoding — while the pool ledger drains
clean (zero pinned refs, census partition).  Plus the two control-plane
satellites: the dashboard's adapter row (present with a pool, hidden
without) and the router's adapter-affinity scoring (resident tenant
outranks raw headroom; legacy workers fall through)."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import generate
from apex_tpu.models.lora import merge_lora
from apex_tpu.models.transformer_lm import init_gpt_params
from apex_tpu.serving import ServingEngine
from apex_tpu.serving.adapter_pool import AdapterPool
from apex_tpu.serving.cluster.router import Router, _Pending
from apex_tpu.serving.cluster.worker import build_adapter_suite

ADAPTER_N = 64
POOL_SLOTS = 6                       # far below 64 tenants: LRU churns


def _cfg(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    params = init_gpt_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def suite(model):
    cfg, _ = model
    return build_adapter_suite(cfg, ADAPTER_N, rank=4)


@pytest.fixture(scope="module")
def merged(model, suite):
    """Per-tenant merged-weights params, built lazily — the oracle."""
    cfg, params = model
    cache = {}

    def get(aid):
        if aid == 0:
            return params
        if aid not in cache:
            cache[aid] = merge_lora(params, cfg, suite[aid])
        return cache[aid]

    return get


def _pooled_engine(params, cfg, suite, layout, n=ADAPTER_N, **kw):
    pool = AdapterPool(cfg, slots=POOL_SLOTS)
    for aid in range(1, n + 1):
        pool.register(aid, suite[aid])
    geom = dict(max_slots=4, max_len=24, prompt_buckets=(8,),
                cache_layout=layout)
    if layout == "paged":
        geom.update(block_size=4, num_blocks=32, reserve_blocks=0)
    geom.update(kw)
    return ServingEngine(params, cfg, adapter_pool=pool, **geom), pool


def _mixed_trace(cfg, n=ADAPTER_N, seed=3):
    """One request per tenant 1..n, with every 8th row a base-model
    request riding the same batch (adapter 0 = the free no-delta
    path)."""
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        aid = 0 if i % 8 == 7 else i + 1
        reqs.append(dict(
            prompt=rng.randint(0, cfg.vocab_size, (6,)).astype(
                np.int32),
            max_new_tokens=4, adapter_id=aid))
    return reqs


def _assert_matches_oracle(cfg, reqs, resps, merged):
    by_id = {r.request_id: r for r in resps}
    for i, req in enumerate(reqs):
        want = np.asarray(generate(
            merged(req["adapter_id"]),
            jnp.asarray(req["prompt"][None]), cfg,
            max_new_tokens=req["max_new_tokens"]))[0, 6:]
        got = by_id[i].tokens
        assert np.array_equal(got, want), (
            f"request {i} (adapter {req['adapter_id']}): "
            f"{got.tolist()} != oracle {want.tolist()}")


class TestHeterogeneousBatch64:
    @pytest.mark.parametrize("layout", ["contiguous", "paged"])
    def test_64_tenants_token_identical_to_merged_oracle(
            self, model, suite, merged, layout):
        cfg, params = model
        eng, pool = _pooled_engine(params, cfg, suite, layout)
        reqs = _mixed_trace(cfg)
        resps = eng.run([dict(r, prompt=r["prompt"].copy())
                         for r in reqs])
        assert len(resps) == len(reqs)
        _assert_matches_oracle(cfg, reqs, resps, merged)
        # the ledger drained clean through heavy churn: 56 distinct
        # tenants cycled a 6-slot pool
        st = pool.stats()
        assert st["evictions"] >= 1, "64 tenants never churned 6 slots"
        assert st["pinned_refs"] == 0, "adapter refs leaked past drain"
        census = pool.census()
        assert census["pinned"] == 0
        assert eng.stats()["blocks_in_use" if layout == "paged"
                           else "active"] == 0

    def test_64_tenants_under_spec_decode(self, model, suite, merged):
        """Speculative decoding composes: the ngram drafter runs per
        lane, ONE batched verify scores every lane's draft through the
        same ragged LoRA path, and greedy emission still matches each
        tenant's merged oracle exactly."""
        cfg, params = model
        eng, pool = _pooled_engine(params, cfg, suite, "paged",
                                   spec="ngram")
        reqs = _mixed_trace(cfg)
        resps = eng.run([dict(r, prompt=r["prompt"].copy())
                         for r in reqs])
        _assert_matches_oracle(cfg, reqs, resps, merged)
        assert pool.stats()["pinned_refs"] == 0
        pool.census()

    def test_admission_blocks_on_pinned_full_pool_then_progresses(
            self, model, suite):
        """A pool with fewer slots than decode lanes: the overflow
        tenant's admission must WAIT (not crash, not steal a pinned
        slab) and complete once a lane frees its pin."""
        cfg, params = model
        pool = AdapterPool(cfg, slots=2)
        for aid in range(1, 4):
            pool.register(aid, suite[aid])
        eng = ServingEngine(params, cfg, adapter_pool=pool,
                            max_slots=3, max_len=24,
                            prompt_buckets=(8,), cache_layout="paged",
                            block_size=4, num_blocks=32,
                            reserve_blocks=0)
        rng = np.random.RandomState(5)
        reqs = [dict(prompt=rng.randint(0, cfg.vocab_size, (6,))
                     .astype(np.int32),
                     max_new_tokens=4, adapter_id=aid)
                for aid in (1, 2, 3)]
        resps = eng.run(reqs)
        assert sorted(r.request_id for r in resps) == [0, 1, 2]
        assert pool.stats()["pinned_refs"] == 0
        assert pool.census()["pinned"] == 0


class TestServeDashAdapterRow:
    def test_dash_renders_adapter_row_from_live_exporter(self, model,
                                                         suite):
        """ISSUE 20 satellite: the dashboard surfaces the adapter-pool
        row (residency, hit rate, evictions) when the
        serving.adapter.* families are present — and hides it when the
        engine has no pool."""
        import importlib.util
        import os

        import apex_tpu.observability as obs

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "serve_dash", os.path.join(repo, "tools", "serve_dash.py"))
        dash = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(dash)
        om = dash.load_openmetrics_module()

        cfg, params = model
        rng = np.random.RandomState(41)
        reg = obs.configure(export_port=0)
        try:
            eng, _pool = _pooled_engine(params, cfg, suite, "paged",
                                        n=3)
            eng.run([dict(prompt=rng.randint(0, cfg.vocab_size, (6,))
                          .astype(np.int32),
                          max_new_tokens=4, adapter_id=aid)
                     for aid in (1, 2)])
            assert reg.counter("serving.adapter.misses").value >= 2
            out = io.StringIO()
            snap = dash.one_frame(om, reg.exporter.url, out=out)
            assert snap["adapter_resident"] is not None
            assert snap["adapter_misses"] >= 2
            text = out.getvalue()
            assert "adapters" in text and "resident" in text
        finally:
            obs.shutdown()
        # no pool: families absent, row hidden
        reg = obs.configure(export_port=0)
        try:
            eng = ServingEngine(params, cfg, max_slots=2, max_len=24,
                                prompt_buckets=(8,),
                                cache_layout="paged", block_size=4,
                                num_blocks=16)
            eng.run([dict(prompt=rng.randint(0, cfg.vocab_size, (6,))
                          .astype(np.int32), max_new_tokens=4)])
            out = io.StringIO()
            snap = dash.one_frame(om, reg.exporter.url, out=out)
            assert snap["adapter_resident"] is None
            assert "adapters" not in out.getvalue()
        finally:
            obs.shutdown()


class _StubWorker:
    """The _pick_decode-visible slice of a _Worker, minus the socket."""

    def __init__(self, addr, stats):
        self.addr = addr
        self.pool = "decode"
        self.alive = True
        self.draining = False
        self.stats = stats
        self.in_flight = {}
        self.dispatched_since_poll = 0


def _router_over(workers):
    r = Router.__new__(Router)
    r._decode = workers
    r._max_worker_queue = 4
    return r


def _pend(adapter_id, prompt_len=8):
    return _Pending(rid=0,
                    prompt=np.arange(prompt_len, dtype=np.int64),
                    kwargs={"adapter_id": adapter_id},
                    slo_class="default", submitted_t=0.0)


class TestRouterAdapterAffinity:
    def test_resident_tenant_outranks_headroom(self):
        """The worker already holding the slab wins the dispatch even
        when another worker has far more free headroom — a slab miss
        stalls admission, a few blocks of headroom do not."""
        roomy = _StubWorker("a:1", {"headroom_tokens": 1000,
                                    "block_size": 4, "queued": 0})
        resident = _StubWorker("b:2", {
            "headroom_tokens": 40, "block_size": 4, "queued": 0,
            "adapter_pool": {"resident_ids": [5]}})
        router = _router_over([roomy, resident])
        assert router._pick_decode(_pend(5)) is resident
        # a tenant neither holds — and the base model — go to headroom
        assert router._pick_decode(_pend(7)) is roomy
        assert router._pick_decode(_pend(0)) is roomy
        assert router._pick_decode() is roomy        # migration path

    def test_hot_adapter_trace_raises_resident_hit_rate(self):
        """The acceptance trace: a hot tenant's burst all lands on the
        resident worker (hit rate 1.0, counter advances), while a
        legacy pool with no inventory degrades gracefully to headroom
        ordering."""
        from apex_tpu.observability import metrics as telemetry

        resident = _StubWorker("b:2", {
            "headroom_tokens": 40, "block_size": 4, "queued": 0,
            "adapter_pool": {"resident_ids": [9]}})
        legacy = _StubWorker("a:1", {"headroom_tokens": 1000,
                                     "block_size": 4, "queued": 0})
        router = _router_over([legacy, resident])
        reg = telemetry.configure()
        try:
            picks = [router._pick_decode(_pend(9)) for _ in range(20)]
            assert all(p is resident for p in picks)
            hits = reg.counter("cluster.adapter_affinity_hits").value
            assert hits == 20
            # legacy fallback: strip the inventory — the same trace
            # scores 0 affinity everywhere and headroom decides
            resident.stats = {"headroom_tokens": 40, "block_size": 4,
                              "queued": 0}
            assert router._pick_decode(_pend(9)) is legacy
            assert reg.counter(
                "cluster.adapter_affinity_hits").value == hits
        finally:
            telemetry.shutdown()
