"""ResNet + SyncBN + AMP train step.

Mirrors the reference's L1 imagenet config (tests/L1/common/main_amp.py:
resnet50 + amp O2 + DDP + SyncBN, loss-trace based) at toy scale, plus the
syncbn unit test pattern (tests/distributed/synced_batchnorm/
two_gpu_unit_test.py: multi-rank BN == single-rank BN on the full batch).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.resnet import (
    make_resnet_train_step,
    resnet18,
    resnet50,
)
from apex_tpu.optimizers import fused_sgd
from apex_tpu.parallel.mesh import create_mesh


def data(b=8, hw=32, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(b, hw, hw, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, classes, (b,)), jnp.int32)
    return x, y


class TestForward:
    @pytest.mark.slow   # rn18 forward + rn50 train-step tests cover the block stack
    def test_resnet50_shapes(self):
        model = resnet50(num_classes=10)
        x, _ = data(b=2)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        logits = model.apply(variables, x, train=False)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32
        # BN stats exist for every bn layer
        assert "bn1" in variables["batch_stats"]

    def test_eval_uses_running_stats(self):
        model = resnet18(num_classes=10)
        x, _ = data(b=4, seed=1)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        # two eval passes are deterministic & identical
        l1 = model.apply(variables, x, train=False)
        l2 = model.apply(variables, x, train=False)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
        # train pass mutates stats
        _, mutated = model.apply(variables, x, train=True,
                                 mutable=["batch_stats"])
        before = variables["batch_stats"]["bn1"]["mean"]
        after = mutated["batch_stats"]["bn1"]["mean"]
        assert float(jnp.max(jnp.abs(before - after))) > 0


class TestTrainStep:
    def test_amp_o2_loss_decreases(self):
        model = resnet18(num_classes=10)
        init, step = make_resnet_train_step(
            model, fused_sgd(lr=0.05, momentum=0.9), "O2",
            image_shape=(32, 32, 3))
        state, stats = init(jax.random.PRNGKey(0))
        # O2: half-precision conv params (fp16 on CPU, bf16 on TPU),
        # fp32 masters, fp32 BN params
        assert state.params["conv1"]["kernel"].dtype in (
            jnp.bfloat16, jnp.float16)
        assert state.master_params["conv1"]["kernel"].dtype == jnp.float32
        assert state.params["bn1"]["scale"].dtype == jnp.float32
        x, y = data(b=8)
        losses = []
        for _ in range(12):
            state, stats, metrics = step(state, stats, x, y)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses

    def test_gspmd_dp_matches_single_device(self):
        # SyncBN under GSPMD: dp=4-sharded batch must produce the same
        # loss/stats as the unsharded run (global statistics)
        model = resnet18(num_classes=10, dtype=jnp.float32)
        x, y = data(b=8, seed=2)

        init, step = make_resnet_train_step(
            model, fused_sgd(lr=0.1), "O0", image_shape=(32, 32, 3))
        state, stats = init(jax.random.PRNGKey(1))
        _, stats_ref, m_ref = step(state, stats, x, y)

        mesh = create_mesh(tp=1)  # ('pp','dp','sp','tp') with dp=8
        init2, step2 = make_resnet_train_step(
            model, fused_sgd(lr=0.1), "O0", mesh,
            image_shape=(32, 32, 3))
        state2, stats2 = init2(jax.random.PRNGKey(1))
        _, stats_sh, m_sh = step2(state2, stats2, x, y)

        np.testing.assert_allclose(
            float(m_sh["loss"]), float(m_ref["loss"]), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(stats_sh["bn1"]["mean"]),
            np.asarray(stats_ref["bn1"]["mean"]), atol=1e-5)

    def test_overflow_skips_update(self):
        model = resnet18(num_classes=10)
        init, step = make_resnet_train_step(
            model, fused_sgd(lr=0.1), "O2", image_shape=(32, 32, 3))
        state, stats = init(jax.random.PRNGKey(0))
        x, y = data(b=4, seed=3)
        state, stats, _ = step(state, stats, x, y)
        w_before = np.asarray(state.master_params["conv1"]["kernel"])
        scale_before = float(state.loss_scale_state.loss_scale)
        bad = x.at[0, 0, 0, 0].set(jnp.inf)
        state, stats, metrics = step(state, stats, bad, y)
        assert bool(metrics["overflow"])
        np.testing.assert_array_equal(
            np.asarray(state.master_params["conv1"]["kernel"]), w_before)
        assert float(state.loss_scale_state.loss_scale) == scale_before / 2


class TestSpaceToDepthStem:
    """MLPerf-style TPU stem: exact equivalence with the 7x7 stem."""

    def test_kernel_transform_exact(self):
        from apex_tpu.models.resnet import (
            space_to_depth, stem_kernel_to_space_to_depth)

        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(2, 64, 64, 3), jnp.float32)
        w7 = jnp.asarray(rs.randn(7, 7, 3, 8) * 0.1, jnp.float32)
        ref = jax.lax.conv_general_dilated(
            x, w7, (2, 2), [(3, 3), (3, 3)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        out = jax.lax.conv_general_dilated(
            space_to_depth(x), stem_kernel_to_space_to_depth(w7),
            (1, 1), [(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)

    def test_model_forward_matches_plain_stem(self):
        from apex_tpu.models.resnet import (
            resnet18, stem_kernel_to_space_to_depth)

        plain = resnet18(num_classes=8, dtype=jnp.float32)
        s2d = resnet18(num_classes=8, dtype=jnp.float32,
                       space_to_depth_stem=True)
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(2, 64, 64, 3), jnp.float32)
        vars_p = plain.init(jax.random.PRNGKey(0), x, train=False)
        # graft the converted stem kernel into (a structural copy of)
        # the variables — tree_map rebuilds the containers, so mutating
        # the copy leaves vars_p untouched
        vars_s = jax.tree_util.tree_map(lambda v: v, vars_p)
        vars_s["params"]["conv1"]["kernel"] = stem_kernel_to_space_to_depth(
            vars_p["params"]["conv1"]["kernel"])
        out_p = plain.apply(vars_p, x, train=False)
        out_s = s2d.apply(vars_s, x, train=False)
        np.testing.assert_allclose(
            np.asarray(out_s), np.asarray(out_p), atol=1e-4, rtol=1e-4)
