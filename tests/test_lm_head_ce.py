"""Chunked fused LM-head + cross-entropy vs the two-stage composition."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.lm_head_ce import lm_head_cross_entropy
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss


def _case(n=70, h=32, v=97, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    hidden = jnp.asarray(rng.randn(n, h) * 0.5, dtype)
    head = jnp.asarray(rng.randn(v, h) * 0.1, dtype)
    labels = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)
    return hidden, head, labels


def _two_stage(hidden, head, labels, smoothing=0.0):
    logits = jnp.einsum("nh,vh->nv", hidden, head.astype(hidden.dtype),
                        preferred_element_type=jnp.float32)
    return softmax_cross_entropy_loss(logits, labels, smoothing, None)


class TestForward:
    @pytest.mark.parametrize("chunk", [16, 64, 1024])
    def test_matches_two_stage(self, chunk):
        hidden, head, labels = _case()
        got = lm_head_cross_entropy(hidden, head, labels, chunk=chunk)
        want = _two_stage(hidden, head, labels)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_smoothing(self):
        hidden, head, labels = _case(seed=1)
        got = lm_head_cross_entropy(hidden, head, labels,
                                    smoothing=0.1, chunk=32)
        want = _two_stage(hidden, head, labels, smoothing=0.1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_ignore_index(self):
        hidden, head, labels = _case(seed=2)
        labels = labels.at[::7].set(-1)
        got = lm_head_cross_entropy(hidden, head, labels, chunk=32,
                                    ignore_index=-1)
        assert float(jnp.max(jnp.abs(got[::7]))) == 0.0
        want = _two_stage(hidden, head, jnp.maximum(labels, 0))
        np.testing.assert_allclose(
            np.asarray(got[1::7]), np.asarray(want[1::7]),
            rtol=1e-5, atol=1e-6)

    def test_leading_dims(self):
        hidden, head, labels = _case(n=64, seed=3)
        got = lm_head_cross_entropy(
            hidden.reshape(4, 16, -1), head, labels.reshape(4, 16),
            chunk=16)
        assert got.shape == (4, 16)


class TestBackward:
    @pytest.mark.parametrize("chunk", [16, 1024])
    def test_grads_match_two_stage(self, chunk):
        hidden, head, labels = _case(seed=4)

        def fused(hd, he):
            return lm_head_cross_entropy(hd, he, labels,
                                         chunk=chunk).mean()

        def ref(hd, he):
            return _two_stage(hd, he, labels).mean()

        gf = jax.grad(fused, argnums=(0, 1))(hidden, head)
        gr = jax.grad(ref, argnums=(0, 1))(hidden, head)
        np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gr[0]),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gr[1]),
                                   rtol=1e-4, atol=1e-6)

    def test_grads_with_ignore_and_smoothing(self):
        hidden, head, labels = _case(seed=5)
        labels = labels.at[::5].set(-1)

        def fused(hd, he):
            return lm_head_cross_entropy(
                hd, he, labels, chunk=32, smoothing=0.05,
                ignore_index=-1).sum()

        def ref(hd, he):
            losses = _two_stage(hd, he, jnp.maximum(labels, 0), 0.05)
            return jnp.where(labels == -1, 0.0, losses).sum()

        gf = jax.grad(fused, argnums=(0, 1))(hidden, head)
        gr = jax.grad(ref, argnums=(0, 1))(hidden, head)
        np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gr[0]),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gr[1]),
                                   rtol=1e-4, atol=1e-6)

    def test_bf16_inputs(self):
        hidden, head, labels = _case(seed=6, dtype=jnp.bfloat16)
        g = jax.grad(lambda hd: lm_head_cross_entropy(
            hd, head, labels, chunk=32).mean())(hidden)
        assert g.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(g, np.float32)).all()
