"""Op registry tests (reference analog: tests/test_extension_import.py —
every compatibility shim imports; here: every registered op resolves)."""


import pytest

from apex_tpu.utils.registry import OpRegistry


def test_register_and_get():
    reg = OpRegistry()
    reg.register("myop", "xla", lambda x: x + 1)
    assert reg.get("myop")(1) == 2


def test_backend_priority_and_availability():
    reg = OpRegistry()
    reg.register("op", "xla", lambda: "xla")
    reg.register("op", "pallas", lambda: "pallas", is_available=lambda: False)
    assert reg.get("op")() == "xla"
    reg.register("op", "pallas", lambda: "pallas", is_available=lambda: True)
    assert reg.get("op")() == "pallas"


def test_forced_backend():
    reg = OpRegistry()
    reg.register("op", "xla", lambda: "xla")
    reg.register("op", "ref", lambda: "ref")
    assert reg.get("op", backend="ref")() == "ref"
    with pytest.raises(RuntimeError):
        reg.get("op", backend="pallas")


def test_unknown_op():
    reg = OpRegistry()
    with pytest.raises(KeyError):
        reg.get("nope")


def test_env_disable(monkeypatch):
    reg = OpRegistry()
    reg.register("op", "xla", lambda: "xla")
    reg.register("op", "ref", lambda: "ref")
    monkeypatch.setenv("APEX_TPU_DISABLE_OP", "1")
    with pytest.raises(RuntimeError):
        reg.get("op")


def test_bad_backend_rejected():
    reg = OpRegistry()
    with pytest.raises(ValueError):
        reg.register("op", "cuda", lambda: None)
