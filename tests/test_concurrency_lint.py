"""apexlint Tier C unit tests (ISSUE 13): every concurrency/lifecycle
rule must catch its fixture and pass its clean twin; the guarded-by
annotation grammar is pinned; the thread-escape graph resolves the
repo's real spawn idioms (self.method targets, nested defs, handler
classes through a `x = self` alias); and the historical PR-6 `_admit`
leak shape is the APX505 regression fixture.

Fixture style matches tests/test_lint.py: in-memory modules via
``rules.module_from_source`` — the same ModuleInfo path the real
linter walks.  The repo-clean-at-head pin and the tier/id selection
machinery are covered here too; the dynamic stress smoke is gated by
the ``concurrency_audit`` dryrun phase and smoke-tested (tiny sizes)
in the slow marker.
"""

import os

import pytest

from apex_tpu.analysis import linter
from apex_tpu.analysis.concurrency import parse_guard_spec, thread_model
from apex_tpu.analysis.rules import module_from_source, rules_by_id

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RULES = rules_by_id()

_HDR = "import threading\nimport queue\n"


def run_rule(rule_id, source, relpath="apex_tpu/_fixture.py"):
    return list(RULES[rule_id].check(
        module_from_source(source, relpath)))


def run_repo_rule(rule_id, *sources):
    mods = [module_from_source(src, f"apex_tpu/_fix{i}.py")
            for i, src in enumerate(sources)]
    return list(RULES[rule_id].check_repo(mods, REPO))


# ---------------------------------------------------------------------------
# the thread-escape graph
# ---------------------------------------------------------------------------


class TestThreadModel:
    def test_self_method_target_resolves(self):
        mod = module_from_source(_HDR + (
            "class W:\n"
            "    def start(self):\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "        self._t.start()\n"
            "    def _run(self):\n"
            "        self._helper()\n"
            "    def _helper(self):\n"
            "        pass\n"))
        m = thread_model(mod)
        assert len(m.spawns) == 1
        assert m.spawns[0].target_quals == ("W._run",)
        assert m.spawns[0].binding == "self._t"
        # transitive closure: the helper runs on the thread too
        assert m.is_thread_side("W._run")
        assert m.is_thread_side("W._helper")
        assert not m.is_thread_side("W.start")

    def test_nested_def_target_resolves(self):
        mod = module_from_source(_HDR + (
            "def go():\n"
            "    def worker():\n"
            "        pass\n"
            "    t = threading.Thread(target=worker)\n"
            "    t.start()\n"))
        m = thread_model(mod)
        assert m.spawns[0].target_quals == ("go.worker",)
        assert m.is_thread_side("go.worker")

    def test_handler_class_via_self_alias(self):
        # the exporter idiom: a nested handler class calling back
        # through `exporter = self`
        mod = module_from_source(_HDR + (
            "from http.server import BaseHTTPRequestHandler, "
            "ThreadingHTTPServer\n"
            "class Exp:\n"
            "    def __init__(self):\n"
            "        exporter = self\n"
            "        class H(BaseHTTPRequestHandler):\n"
            "            def do_GET(self):\n"
            "                exporter._handle(self)\n"
            "        self._server = ThreadingHTTPServer(('', 0), H)\n"
            "    def _handle(self, h):\n"
            "        pass\n"))
        m = thread_model(mod)
        assert any(s.kind == "server" for s in m.spawns)
        assert m.is_thread_side("Exp._handle")


class TestGuardSpecGrammar:
    def test_forms(self):
        assert parse_guard_spec("self._lock").form == "lock"
        assert parse_guard_spec("_global_lock trailing prose").value \
            == "_global_lock"
        j = parse_guard_spec("join(self._thread)")
        assert (j.form, j.value) == ("join", "self._thread")
        c = parse_guard_spec("confined(engine-loop)")
        assert (c.form, c.value) == ("confined", "engine-loop")
        assert parse_guard_spec("queue").form == "safe-type"
        assert parse_guard_spec("??garbage??").form == "bad"

    def test_annotation_inside_string_is_not_parsed(self):
        # the rule's own description quotes the convention — a string
        # literal mentioning guarded-by: must not register
        src = ('class C:\n'
               '    def __init__(self):\n'
               '        self.doc = "use # guarded-by: self._lock"\n'
               '    def touch(self):\n'
               '        self.doc = 1\n')
        assert not run_rule("APX502", src)


# ---------------------------------------------------------------------------
# APX501 — unguarded cross-thread mutation
# ---------------------------------------------------------------------------


class TestCrossThreadMutation:
    _RACE = _HDR + (
        "class W:\n"
        "    def __init__(self):\n"
        "        self.state = 0\n"
        "        self._lock = threading.Lock()\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._run).start()\n"
        "    def _run(self):\n"
        "        self.state = 1\n"
        "    def stop(self):\n"
        "        {}\n")

    def test_both_side_write_fires(self):
        fs = run_rule("APX501", self._RACE.format("self.state = 2"))
        assert len(fs) == 1 and "state" in fs[0].message

    def test_common_lock_is_clean(self):
        src = _HDR + (
            "class W:\n"
            "    def __init__(self):\n"
            "        self.state = 0\n"
            "        self._lock = threading.Lock()\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self.state = 1\n"
            "    def stop(self):\n"
            "        with self._lock:\n"
            "            self.state = 2\n")
        assert not run_rule("APX501", src)

    def test_annotated_attr_deferred_to_apx502(self):
        src = self._RACE.format("self.state = 2").replace(
            "self.state = 0",
            "self.state = 0   # guarded-by: join(self._t)")
        assert not run_rule("APX501", src)

    def test_init_writes_are_happens_before(self):
        # only __init__ writes on the spawning side: no race
        src = _HDR + (
            "class W:\n"
            "    def __init__(self):\n"
            "        self.state = 0\n"
            "        threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        self.state = 1\n")
        assert not run_rule("APX501", src)

    def test_safe_type_attr_is_clean(self):
        src = _HDR + (
            "class W:\n"
            "    def __init__(self):\n"
            "        self.q = queue.Queue()\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        self.q.put(1)\n"
            "    def stop(self):\n"
            "        self.q.put(None)\n")
        assert not run_rule("APX501", src)

    def test_nonlocal_closure_write_fires(self):
        src = _HDR + (
            "def go():\n"
            "    n = 0\n"
            "    def worker():\n"
            "        nonlocal n\n"
            "        n += 1\n"
            "    threading.Thread(target=worker).start()\n"
            "    n += 1\n"
            "    return n\n")
        fs = run_rule("APX501", src)
        assert fs and "'n'" in fs[0].message

    def test_shadowing_local_in_thread_fn_is_clean(self):
        # the spawn_worker drain idiom: `for line in ...` in the
        # nested def is its own local, not a shared cell
        src = _HDR + (
            "def go(stream):\n"
            "    def drain():\n"
            "        for line in stream:\n"
            "            pass\n"
            "    threading.Thread(target=drain).start()\n"
            "    line = stream.readline()\n"
            "    return line\n")
        assert not run_rule("APX501", src)


# ---------------------------------------------------------------------------
# APX502 — guarded-by discipline
# ---------------------------------------------------------------------------


class TestGuardedBy:
    _LOCKED = _HDR + (
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = {{}}   # guarded-by: self._lock\n"
        "    def put(self, k, v):\n"
        "        {}\n")

    def test_lock_form_unguarded_access_fires(self):
        fs = run_rule("APX502",
                      self._LOCKED.format("self.items[k] = v"))
        assert len(fs) == 1 and "with self._lock" in fs[0].message

    def test_lock_form_guarded_access_clean(self):
        src = self._LOCKED.format(
            "with self._lock:\n            self.items[k] = v")
        assert not run_rule("APX502", src)

    def test_join_form(self):
        tmpl = _HDR + (
            "class S:\n"
            "    def __init__(self):\n"
            "        self._err = None   # guarded-by: join(self._t)\n"
            "        self._t = None\n"
            "    def save(self):\n"
            "        self._t = threading.Thread(target=self._w)\n"
            "        self._t.start()\n"
            "    def _w(self):\n"
            "        self._err = ValueError()\n"      # thread side: ok
            "    def wait(self):\n"
            "        {}\n"
            "        return self._err\n")
        # reader joins first: clean
        assert not run_rule("APX502", tmpl.format("self._t.join()"))
        # reader never joins: fires
        fs = run_rule("APX502", tmpl.format("pass"))
        assert fs and "without joining" in fs[0].message

    def test_confined_form(self):
        tmpl = _HDR + (
            "class W:\n"
            "    def __init__(self):\n"
            "        self.box = []   # guarded-by: confined(loop)\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        {}\n"
            "    def pump(self):\n"
            "        self.box.append(1)\n")
        assert not run_rule("APX502", tmpl.format("pass"))
        fs = run_rule("APX502", tmpl.format("self.box.append(2)"))
        assert fs and "runs on a spawned thread" in fs[0].message

    def test_safe_type_form(self):
        ok = _HDR + ("class C:\n"
                     "    def __init__(self):\n"
                     "        self.q = queue.Queue()   "
                     "# guarded-by: queue\n")
        assert not run_rule("APX502", ok)
        bad = ok.replace("queue.Queue()", "list()")
        fs = run_rule("APX502", bad)
        assert fs and "does not construct" in fs[0].message

    def test_module_global_lock_form(self):
        tmpl = (_HDR +
                "_lk = threading.Lock()\n"
                "_count = 0   # guarded-by: _lk\n"
                "def bump():\n"
                "    global _count\n"
                "    {}\n")
        assert not run_rule(
            "APX502",
            tmpl.format("with _lk:\n        _count += 1"))
        fs = run_rule("APX502", tmpl.format("_count += 1"))
        assert fs and "_count" in fs[0].message

    def test_str_join_is_not_a_join_witness(self):
        # review regression: `", ".join(parts)` must NOT satisfy the
        # join-ordered form — only a Thread-shaped join (no positional
        # args, or a numeric timeout) counts
        src = _HDR + (
            "class S:\n"
            "    def __init__(self):\n"
            "        self._err = None   # guarded-by: join(self._t)\n"
            "        self._t = None\n"
            "    def save(self):\n"
            "        self._t = threading.Thread(target=self._w)\n"
            "        self._t.start()\n"
            "        self._t.join(5.0)\n"
            "    def _w(self):\n"
            "        self._err = ValueError()\n"
            "    def report(self):\n"
            "        msg = ', '.join(['a', 'b'])\n"
            "        return msg, self._err\n")
        fs = run_rule("APX502", src)
        assert fs and "report" in fs[0].message

    def test_bad_spec_fires(self):
        src = ("class C:\n"
               "    def __init__(self):\n"
               "        self.x = 0   # guarded-by: ???\n")
        fs = run_rule("APX502", src)
        assert fs and "unparseable" in fs[0].message

    def test_suppression_applies_at_the_access(self, tmp_path):
        pkg = tmp_path / "apex_tpu"
        pkg.mkdir()
        (pkg / "m.py").write_text(_HDR + (
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.items = {}   # guarded-by: self._lock\n"
            "    def fast(self):\n"
            "        return self.items   # apexlint: disable=APX502\n"))
        assert not linter.lint(str(tmp_path), targets=("apex_tpu",),
                               rules=[RULES["APX502"]])


# ---------------------------------------------------------------------------
# APX503 — lock-order cycles
# ---------------------------------------------------------------------------


class TestLockOrder:
    def test_opposite_nesting_fires(self):
        src = _HDR + (
            "_a = threading.Lock()\n"
            "_b = threading.Lock()\n"
            "def f():\n"
            "    with _a:\n"
            "        with _b:\n"
            "            pass\n"
            "def g():\n"
            "    with _b:\n"
            "        with _a:\n"
            "            pass\n")
        fs = run_repo_rule("APX503", src)
        assert fs and "lock-order cycle" in fs[0].message

    def test_consistent_order_clean(self):
        src = _HDR + (
            "_a = threading.Lock()\n"
            "_b = threading.Lock()\n"
            "def f():\n"
            "    with _a:\n"
            "        with _b:\n"
            "            pass\n"
            "def g():\n"
            "    with _a:\n"
            "        with _b:\n"
            "            pass\n")
        assert not run_repo_rule("APX503", src)

    def test_long_chain_terminates(self):
        # review regression: the cycle DFS must be linear-time and
        # iterative — a deep lock chain (plus a cycle at the end) ran
        # the old recursive all-simple-paths form out of stack
        n = 300
        locks = "\n".join(f"_l{i} = threading.Lock()"
                          for i in range(n))
        chain = "\n".join(
            f"def f{i}():\n    with _l{i}:\n        with _l{i + 1}:\n"
            "            pass"
            for i in range(n - 1))
        cycle = (f"def back():\n    with _l{n - 1}:\n"
                 "        with _l0:\n            pass\n")
        fs = run_repo_rule("APX503",
                           _HDR + locks + "\n" + chain + "\n" + cycle)
        assert fs and "cycle" in fs[0].message

    def test_call_mediated_edge(self):
        src = _HDR + (
            "_a = threading.Lock()\n"
            "_b = threading.Lock()\n"
            "def inner_b():\n"
            "    with _b:\n"
            "        pass\n"
            "def f():\n"
            "    with _a:\n"
            "        inner_b()\n"
            "def g():\n"
            "    with _b:\n"
            "        with _a:\n"
            "            pass\n")
        fs = run_repo_rule("APX503", src)
        assert fs and "cycle" in fs[0].message


# ---------------------------------------------------------------------------
# APX504 — thread/server lifecycle
# ---------------------------------------------------------------------------


class TestLifecycle:
    def test_fire_and_forget_fires(self):
        src = _HDR + (
            "def go(fn):\n"
            "    threading.Thread(target=fn, daemon=True).start()\n")
        fs = run_rule("APX504", src)
        assert fs and "fire-and-forget" in fs[0].message

    def test_bound_without_join_fires(self):
        src = _HDR + (
            "def go(fn):\n"
            "    t = threading.Thread(target=fn)\n"
            "    t.start()\n")
        fs = run_rule("APX504", src)
        assert fs and "no reachable" in fs[0].message

    def test_bound_with_join_clean(self):
        src = _HDR + (
            "def go(fn):\n"
            "    t = threading.Thread(target=fn)\n"
            "    t.start()\n"
            "    t.join()\n")
        assert not run_rule("APX504", src)

    def test_join_through_alias_clean(self):
        # t = self._thread; t.join() — the async_saver idiom
        src = _HDR + (
            "class S:\n"
            "    def save(self, fn):\n"
            "        self._thread = threading.Thread(target=fn)\n"
            "        self._thread.start()\n"
            "    def wait(self):\n"
            "        t = self._thread\n"
            "        if t is not None:\n"
            "            t.join()\n")
        assert not run_rule("APX504", src)

    def test_comprehension_binding_and_join_loop_clean(self):
        # the stress-module idiom: spawn via list comp, join in a for
        src = _HDR + (
            "def go(fns):\n"
            "    threads = [threading.Thread(target=f) for f in fns]\n"
            "    for t in threads:\n"
            "        t.start()\n"
            "    for t in threads:\n"
            "        t.join()\n")
        assert not run_rule("APX504", src)

    def test_str_join_does_not_discharge_lifecycle(self):
        # review regression: a str.join on a name aliasing the thread
        # binding must not count as the thread's teardown path
        src = _HDR + (
            "def go(fn):\n"
            "    t = threading.Thread(target=fn)\n"
            "    t.start()\n"
            "    label = t\n"
            "    return ', '.join(['x'])\n")
        fs = run_rule("APX504", src)
        assert fs and "no reachable" in fs[0].message

    def test_server_without_close_fires(self):
        src = (
            "from http.server import ThreadingHTTPServer\n"
            "class E:\n"
            "    def __init__(self, h):\n"
            "        self._server = ThreadingHTTPServer(('', 0), h)\n")
        fs = run_rule("APX504", src)
        assert fs and "server" in fs[0].message

    def test_close_ordering(self):
        tmpl = (
            "import threading\n"
            "from http.server import ThreadingHTTPServer\n"
            "class E:\n"
            "    def __init__(self, h):\n"
            "        self._server = ThreadingHTTPServer(('', 0), h)\n"
            "        self._thread = threading.Thread(\n"
            "            target=self._server.serve_forever)\n"
            "        self._thread.start()\n"
            "    def close(self):\n"
            "        server, self._server = self._server, None\n"
            "        server.shutdown()\n"
            "        {}\n")
        good = tmpl.format(
            "self._thread.join()\n        server.server_close()")
        assert not run_rule("APX504", good)
        bad = tmpl.format(
            "server.server_close()\n        self._thread.join()")
        fs = run_rule("APX504", bad)
        assert fs and "before the serve thread is joined" \
            in fs[0].message


# ---------------------------------------------------------------------------
# APX505 — paired acquire/release (the _admit regression shape)
# ---------------------------------------------------------------------------


class TestAcquireRelease:
    # The historical PR-6 bug, reduced: blocks claimed into a local
    # list, a prefill call that can raise, THEN the table store — an
    # exception between leaks every claimed block.
    _ADMIT_LEAK = (
        "class Engine:\n"
        "    def _admit(self, prompt):\n"
        "        claimed = []\n"
        "        for _ in range(4):\n"
        "            blk = self._mgr.alloc()\n"
        "            claimed.append(blk)\n"
        "        self._prefill(prompt)\n"
        "        self.table.extend(claimed)\n")

    def test_admit_leak_shape_fires(self):
        fs = run_rule("APX505", self._ADMIT_LEAK)
        assert len(fs) == 1
        assert "alloc()" in fs[0].message
        assert "unwind" in fs[0].message

    def test_admit_with_unwind_edge_clean(self):
        src = (
            "class Engine:\n"
            "    def _admit(self, prompt):\n"
            "        claimed = []\n"
            "        try:\n"
            "            for _ in range(4):\n"
            "                blk = self._mgr.alloc()\n"
            "                claimed.append(blk)\n"
            "            self._prefill(prompt)\n"
            "        except Exception:\n"
            "            self._mgr.free_all(claimed)\n"
            "            raise\n"
            "        self.table.extend(claimed)\n")
        assert not run_rule("APX505", src)

    def test_finally_release_clean(self):
        src = (
            "def probe(addr):\n"
            "    import socket\n"
            "    s = socket.create_connection(addr)\n"
            "    try:\n"
            "        return handshake(s)\n"
            "    finally:\n"
            "        s.close()\n")
        assert not run_rule("APX505", src)

    def test_socket_without_unwind_fires(self):
        src = (
            "def probe(addr):\n"
            "    import socket\n"
            "    s = socket.create_connection(addr)\n"
            "    hello = handshake(s)\n"
            "    s.close()\n"
            "    return hello\n")
        fs = run_rule("APX505", src)
        assert fs and "create_connection" in fs[0].message

    def test_immediate_ownership_transfer_clean(self):
        # self._sock = create_connection(...): the object owns it now
        src = (
            "import socket\n"
            "class W:\n"
            "    def __init__(self, addr):\n"
            "        self._sock = socket.create_connection(addr)\n"
            "        self._sock.settimeout(5.0)\n"
            "        self.hello = self.rpc({'op': 'hello'})\n")
        assert not run_rule("APX505", src)

    def test_with_block_clean(self):
        src = (
            "def read(p):\n"
            "    with open(p) as f:\n"
            "        return f.read()\n")
        assert not run_rule("APX505", src)

    def test_no_risk_calls_clean(self):
        # acquire immediately escaped with only no-raise builtins in
        # between (the engine's _ensure_tail_blocks shape)
        src = (
            "class E:\n"
            "    def grow(self, st, slot):\n"
            "        blk = self._mgr.alloc()\n"
            "        self._tables[slot, len(st.blocks)] = blk\n"
            "        st.blocks.append(blk)\n")
        assert not run_rule("APX505", src)


# ---------------------------------------------------------------------------
# tier/id selection + the repo pin
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tierc_findings():
    """ONE tier-C repo lint shared by the at-head assertions."""
    return linter.lint(REPO, rules=linter.select_rules(tier="C"))


class TestTierSelection:
    def test_tier_filter(self):
        ids = {r.id for r in linter.select_rules(tier="C")}
        assert ids == {"APX501", "APX502", "APX503", "APX504",
                       "APX505"}
        ids_a = {r.id for r in linter.select_rules(tier="A")}
        assert "APX101" in ids_a and not ids_a & ids

    def test_id_patterns(self):
        assert {r.id for r in linter.select_rules(ids=["APX5xx"])} \
            == {"APX501", "APX502", "APX503", "APX504", "APX505"}
        assert [r.id for r in linter.select_rules(
            ids=["APX501,APX505"])] == ["APX501", "APX505"]

    def test_unknown_selection_raises(self):
        with pytest.raises(ValueError):
            linter.select_rules(tier="B")
        with pytest.raises(ValueError):
            linter.select_rules(ids=["APX9xx"])

    def test_empty_rules_pattern_raises(self):
        # review regression: an unset CI variable (`--rules ""`) must
        # exit 2, not scan zero rules and pass vacuously
        with pytest.raises(ValueError):
            linter.select_rules(ids=[""])
        with pytest.raises(ValueError):
            linter.select_rules(ids=[" , "])

    def test_all_rules_carry_a_tier(self):
        from apex_tpu.analysis.rules import all_rules

        assert {r.tier for r in all_rules()} == {"A", "C"}

    def test_repo_tier_c_clean_at_head(self, tierc_findings):
        """THE enforcement pin: the threaded subsystems stay clean
        against the concurrency/lifecycle rules (suppressions carry
        their why inline; the baseline stays empty)."""
        new, _ = linter.diff_baseline(REPO, tierc_findings)
        assert not new, "new tier-C findings:\n" + "\n".join(
            f"  {fp} {f.path}:{f.line} {f.message}" for fp, f in new)


@pytest.mark.slow
def test_stress_smoke_tiny():
    """A miniature of the concurrency_audit stress (the full seeded
    version gates in the dryrun phase): exact counts, no underflow,
    clean shutdown."""
    from apex_tpu.analysis.stress import run_concurrency_stress

    stats = run_concurrency_stress(
        seed=1, observers=2, observations=50, scrapers=1,
        churn_iters=120, saves=2)
    assert stats["sketch_count_exact"], stats
    assert stats["refcount_underflows"] == 0
    assert stats["drained_clean"] == 1
    assert not stats["scrape_parse_failures"]
    assert not stats["leaked_threads"], stats
