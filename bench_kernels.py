"""Per-kernel perf ledger: fused kernels vs their XLA-composed equivalents.

The reference's value proposition is per-kernel speed ("optimized for
performance", /root/reference/README.md:3-6).  This microbenchmark times
every fused op in :mod:`apex_tpu.ops` against the plain jnp composition
XLA would produce (autodiff for backward) at the bench-matrix shapes, on
the real chip.  The measured winners justify each op's default backend;
BASELINE.md carries the resulting table per round.

Methodology: each variant is chained through a `lax.fori_loop` *inside*
one jit (the output of iteration i feeds iteration i+1), so the reported
per-iteration time contains no host dispatch and no cross-iteration
parallelism.  For fwd+bwd, the chained value is the gradient (same shape
as the input).  Reported number = best of 5 timed calls / INNER.

Usage:  PYTHONPATH=.:/root/.axon_site python bench_kernels.py [--json out]
"""

from __future__ import annotations

import argparse
import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

INNER = (64, 256, 1024)  # chained iteration counts; reported time is the
                         # least-squares slope over the points, which
                         # cancels the ~67 ms host<->tunnel round-trip
                         # per call and averages out its jitter
REPS = 5                 # timed outer calls per point; best is used


def _scalarize(tree):
    """Cheap on-device scalar depending on every leaf — only a float
    crosses the (slow) tunnel at sync time."""
    return sum(jnp.ravel(leaf)[0].astype(jnp.float32)
               for leaf in jax.tree_util.tree_leaves(tree))


def _best_of(run, args):
    out = run(*args)          # compile + warmup
    float(np.asarray(out))
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = run(*args)
        float(np.asarray(out))
        best = min(best, time.perf_counter() - t0)
    return best


def _time(make_run, args, inner=None):
    points = inner or INNER
    times = [_best_of(make_run(n), args) for n in points]
    slope = np.polyfit(points, times, 1)[0]
    return max(float(slope), 1e-9)


def chain_fwd(op, *args, inner=None):
    """Time op(x, *rest) chained through x (op(x) must have x's shape)."""

    def make_run(n):
        @jax.jit
        def run(x, *rest):
            return _scalarize(jax.lax.fori_loop(
                0, n, lambda i, t: op(t, *rest), x))
        return run

    return _time(make_run, args, inner)


def chain_grad(op, argnums, *args, inner=None):
    """Time jax.grad(sum-of-op) chained through the differentiated args."""
    k = len(argnums)
    g = jax.grad(
        lambda *a: op(*a).astype(jnp.float32).sum(), argnums=argnums)

    def make_run(n):
        @jax.jit
        def run(*a):
            def body(i, diff):
                return g(*diff, *a[k:])

            return _scalarize(jax.lax.fori_loop(0, n, body, a[:k]))
        return run

    return _time(make_run, args, inner)


def _fmt(name, pallas_s, xla_s):
    ratio = pallas_s / xla_s
    win = "pallas" if ratio < 1.0 else "xla"
    print(f"  {name:<44} pallas {pallas_s*1e6:9.1f}us   "
          f"xla {xla_s*1e6:9.1f}us   ratio {ratio:5.3f}  -> {win}")
    return {"pallas_us": round(pallas_s * 1e6, 1),
            "xla_us": round(xla_s * 1e6, 1),
            "pallas_over_xla": round(ratio, 3), "winner": win}


def bench_flash_attention(results):
    from apex_tpu.ops.flash_attention import flash_attention, mha_reference

    print("flash_attention (bf16, d=64)")
    rng = np.random.RandomState(0)
    for b, s, h, causal in ((8, 512, 12, True), (16, 1024, 12, True),
                            (4, 2048, 12, True), (8, 512, 12, False)):
        q = jnp.asarray(rng.randn(b, s, h, 64), jnp.bfloat16)
        k = jnp.asarray(rng.randn(b, s, h, 64), jnp.bfloat16)
        v = jnp.asarray(rng.randn(b, s, h, 64), jnp.bfloat16)
        tag = f"b{b}xs{s}{'_causal' if causal else ''}"

        fa = functools.partial(flash_attention, causal=causal)
        ref = functools.partial(mha_reference, causal=causal)
        results[f"flash_fwd_{tag}"] = _fmt(
            f"fwd   {tag}", chain_fwd(fa, q, k, v, inner=(16, 48, 160)),
            chain_fwd(ref, q, k, v, inner=(16, 48, 160)))
        results[f"flash_fwdbwd_{tag}"] = _fmt(
            f"fwd+bwd {tag}",
            chain_grad(fa, (0, 1, 2), q, k, v, inner=(16, 48, 160)),
            chain_grad(ref, (0, 1, 2), q, k, v, inner=(16, 48, 160)))


def bench_flash_gqa(results):
    """Grouped-K/V flash vs the repeat-then-flash composition a user
    would otherwise write (round-5 GQA-aware kernels): same math, but
    the repeated [b, s, n, d] K/V — written once and re-read by both
    kernel passes — never exists in HBM on the grouped path.  Ratio < 1
    is the measured form of the rep-x traffic claim."""
    from apex_tpu.ops.flash_attention import flash_attention

    print("flash_attention grouped K/V (GQA 12h -> g, bf16, d=64)")
    rng = np.random.RandomState(0)
    for b, s, h, g in ((16, 1024, 12, 4), (8, 512, 12, 4),
                       (16, 1024, 12, 1)):
        q = jnp.asarray(rng.randn(b, s, h, 64), jnp.bfloat16)
        k = jnp.asarray(rng.randn(b, s, g, 64), jnp.bfloat16)
        v = jnp.asarray(rng.randn(b, s, g, 64), jnp.bfloat16)
        tag = f"b{b}xs{s}_g{g}"
        rep = h // g

        fa = functools.partial(flash_attention, causal=True)

        def repeated(q, k, v, rep=rep):
            return fa(q, jnp.repeat(k, rep, axis=2),
                      jnp.repeat(v, rep, axis=2))

        results[f"flash_gqa_fwd_{tag}"] = _fmt(
            f"gqa fwd   {tag}", chain_fwd(fa, q, k, v, inner=(16, 48, 160)),
            chain_fwd(repeated, q, k, v, inner=(16, 48, 160)))
        results[f"flash_gqa_fwdbwd_{tag}"] = _fmt(
            f"gqa fwd+bwd {tag}",
            chain_grad(fa, (0, 1, 2), q, k, v, inner=(16, 48, 160)),
            chain_grad(repeated, (0, 1, 2), q, k, v, inner=(16, 48, 160)))


def bench_layer_norm(results):
    from apex_tpu.ops.layer_norm import (fused_layer_norm, fused_rms_norm,
                                         layer_norm_ref, rms_norm_ref)

    print("layer_norm / rms_norm")
    rng = np.random.RandomState(0)
    for rows, hidden, dtype in ((16384, 768, jnp.bfloat16),
                                (16384, 1024, jnp.bfloat16),
                                (16384, 768, jnp.float32)):
        x = jnp.asarray(rng.randn(rows, hidden), dtype)
        w = jnp.ones((hidden,), jnp.float32)
        b = jnp.zeros((hidden,), jnp.float32)
        tag = f"{rows}x{hidden}_{jnp.dtype(dtype).name}"

        ln = lambda x, w, b: fused_layer_norm(x, w, b)
        ref = lambda x, w, b: layer_norm_ref(x, w, b)
        results[f"ln_fwd_{tag}"] = _fmt(
            f"LN fwd   {tag}", chain_fwd(ln, x, w, b),
            chain_fwd(ref, x, w, b))
        results[f"ln_fwdbwd_{tag}"] = _fmt(
            f"LN fwd+bwd {tag}",
            chain_grad(ln, (0, 1, 2), x, w, b),
            chain_grad(ref, (0, 1, 2), x, w, b))

    x = jnp.asarray(rng.randn(16384, 768), jnp.bfloat16)
    w = jnp.ones((768,), jnp.float32)
    results["rms_fwdbwd_16384x768_bf16"] = _fmt(
        "RMS fwd+bwd 16384x768_bf16",
        chain_grad(lambda x, w: fused_rms_norm(x, w), (0, 1), x, w),
        chain_grad(lambda x, w: rms_norm_ref(x, w), (0, 1), x, w))


def bench_softmax(results):
    from apex_tpu.ops import softmax as sm

    print("scaled softmax (causal / plain)")
    rng = np.random.RandomState(0)
    for b, h, s in ((16, 12, 1024), (32, 16, 512)):
        x = jnp.asarray(rng.randn(b, h, s, s), jnp.bfloat16)
        tag = f"{b}x{h}x{s}x{s}"
        causal = lambda x: sm.scaled_upper_triang_masked_softmax(x, 0.125)
        causal_ref = lambda x: sm._softmax_fwd_ref(x, 0.125, None, True)
        results[f"softmax_causal_fwd_{tag}"] = _fmt(
            f"causal fwd {tag}", chain_fwd(causal, x),
            chain_fwd(causal_ref, x))
        results[f"softmax_causal_fwdbwd_{tag}"] = _fmt(
            f"causal fwd+bwd {tag}",
            chain_grad(causal, (0,), x),
            chain_grad(causal_ref, (0,), x))


def bench_xentropy(results):
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

    print("xentropy (fused lse-saving vs naive log_softmax)")
    rng = np.random.RandomState(0)
    rows, v = 16384, 50304
    logits = jnp.asarray(rng.randn(rows, v), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, v, (rows,)), jnp.int32)

    def naive(logits, labels):
        ls = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(ls, labels[..., None], axis=-1)[..., 0]
        return -picked

    fused = lambda lg, lb: softmax_cross_entropy_loss(lg, lb, 0.0, -100)
    results[f"xentropy_fwdbwd_{rows}x{v}"] = _fmt(
        f"fwd+bwd {rows}x{v}",
        chain_grad(fused, (0,), logits, labels),
        chain_grad(naive, (0,), logits, labels))


def bench_swiglu(results):
    from apex_tpu.ops.swiglu import bias_swiglu_ref, fused_bias_swiglu

    print("bias_swiglu (custom-vjp recompute vs autodiff)")
    rng = np.random.RandomState(0)
    rows, f2 = 16384, 6144
    x = jnp.asarray(rng.randn(rows, f2), jnp.bfloat16)
    b = jnp.asarray(rng.randn(f2) * 0.01, jnp.float32)
    results[f"swiglu_fwdbwd_{rows}x{f2}"] = _fmt(
        f"fwd+bwd {rows}x{f2}",
        chain_grad(fused_bias_swiglu, (0, 1), x, b),
        chain_grad(bias_swiglu_ref, (0, 1), x, b))


def bench_rope(results):
    from apex_tpu.ops.rope import fused_apply_rotary_pos_emb

    print("rope (custom-vjp adjoint vs autodiff)")
    rng = np.random.RandomState(0)
    s, b, h, d = 1024, 16, 12, 64
    t = jnp.asarray(rng.randn(s, b, h, d), jnp.bfloat16)
    freqs = jnp.asarray(rng.randn(s, 1, 1, d), jnp.float32)

    def naive(t, freqs):
        f32 = freqs.astype(jnp.float32)
        cos, sin = jnp.cos(f32), jnp.sin(f32)
        t32 = t.astype(jnp.float32)
        half = d // 2
        rot = jnp.concatenate([-t32[..., half:], t32[..., :half]], axis=-1)
        return (t32 * cos + rot * sin).astype(t.dtype)

    results[f"rope_fwdbwd_s{s}b{b}"] = _fmt(
        f"fwd+bwd s{s}b{b}h{h}d{d}",
        chain_grad(fused_apply_rotary_pos_emb, (0,), t, freqs),
        chain_grad(naive, (0,), t, freqs))


def bench_packed_attention(results):
    """Padding FLOPs recovered by the varlen (segment-id) kernel: the
    same token stream as right-padded b32xs512 batches (BERT-large
    attention geometry, ~50% fill) vs packed 512-token rows."""
    from apex_tpu.ops.flash_attention import flash_attention

    h, d, s = 16, 64, 512
    rng = np.random.RandomState(0)
    # 32 sequences, lengths ~ U(128, 384): mean 256 -> 8192 real tokens
    lengths = rng.randint(128, 385, size=32)
    total = int(lengths.sum())

    # padded layout: one sequence per 512-row + key-padding mask
    qp = jnp.asarray(rng.randn(32, s, h, d), jnp.bfloat16)
    kpm = jnp.asarray(
        np.arange(s)[None, :] >= lengths[:, None])          # True = pad

    # packed layout: first-fit whole sequences per row (a sequence never
    # spans rows — splitting would silently drop its cross-row attention
    # and inflate the measured speedup)
    rows_fill = []
    assign = []
    for i, L in enumerate(lengths):
        L = int(L)
        for r, used in enumerate(rows_fill):
            if used + L <= s:
                assign.append((r, used, L, i))
                rows_fill[r] += L
                break
        else:
            assign.append((len(rows_fill), 0, L, i))
            rows_fill.append(L)
    n_rows = len(rows_fill)
    seg = np.full((n_rows, s), -1, np.int32)
    for r, start, L, i in assign:
        seg[r, start:start + L] = i
    qk = jnp.asarray(rng.randn(n_rows, s, h, d), jnp.bfloat16)
    seg = jnp.asarray(seg)

    def padded(q):
        return flash_attention(q, q, q, key_padding_mask=kpm)

    def packed(q):
        return flash_attention(q, q, q, segment_ids=seg)

    t_pad = chain_grad(padded, (0,), qp, inner=(16, 48, 160))
    t_pack = chain_grad(packed, (0,), qk, inner=(16, 48, 160))
    tok_pad = total / t_pad
    tok_pack = total / t_pack
    speedup = tok_pack / tok_pad
    print("packed varlen attention (BERT-large geometry, s512)")
    print(f"  padded b32 fwd+bwd {t_pad*1e6:9.1f}us  "
          f"packed b{n_rows} {t_pack*1e6:9.1f}us  "
          f"-> {speedup:.2f}x tokens/s")
    results["packed_vs_padded_s512"] = {
        "padded_us": round(t_pad * 1e6, 1),
        "packed_us": round(t_pack * 1e6, 1),
        "padded_rows": 32, "packed_rows": n_rows,
        "real_tokens": total,
        "tokens_per_s_speedup": round(speedup, 3),
    }


def bench_adam(results):
    """Flat-buffer Adam, absolute time only: the Pallas kernel this row
    used to race was deleted in round 5 (1.82x XLA at its best swept
    block size — BASELINE.md win-or-delete rule), so the row now just
    tracks the XLA fused update the optimizers actually run."""
    from apex_tpu.ops.flat_adam import adam_kernel_flat

    print("flat Adam (88M fp32 buffer, XLA fused update)")
    n = 88_000_000
    rng = np.random.RandomState(0)
    g = jnp.asarray(rng.randn(n // 1000, 1000).reshape(-1)[:n] * 1e-3,
                    jnp.float32)
    p = jnp.asarray(rng.randn(n // 1000, 1000).reshape(-1)[:n] * 1e-2,
                    jnp.float32)
    scalars = jnp.asarray([1e-3, 0.9, 0.999, 1e-8, 0.01, 0.9, 0.999],
                          jnp.float32)

    def step(pmv, g, scalars):
        p, m, v = pmv
        u, m, v = adam_kernel_flat(g, p, m, v, scalars)
        return (p + u, m, v)

    zeros = jnp.zeros_like(p)

    def make_run(n):
        @jax.jit
        def run(p, m, v, g, scalars):
            return _scalarize(jax.lax.fori_loop(
                0, n, lambda i, pmv: step(pmv, g, scalars),
                (p, m, v)))
        return run

    t = _time(make_run, (p, zeros, zeros, g, scalars), inner=(16, 48, 160))
    print(f"  update 88M fp32 (xla)                        "
          f"{t*1e6:9.1f}us")
    results["adam_flat_88m"] = {"xla_us": round(t * 1e6, 1),
                                "winner": "xla",
                                "note": "pallas kernel deleted round 5"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default="KERNEL_BENCH.json")
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of benchmark names")
    args = ap.parse_args()

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})")
    results = {}
    benches = {
        "flash_attention": bench_flash_attention,
        "flash_gqa": bench_flash_gqa,
        "layer_norm": bench_layer_norm,
        "softmax": bench_softmax,
        "xentropy": bench_xentropy,
        "swiglu": bench_swiglu,
        "rope": bench_rope,
        "packed_attention": bench_packed_attention,
        "adam": bench_adam,
    }
    only = set(args.only.split(",")) if args.only else None
    for name, fn in benches.items():
        if only and name not in only:
            continue
        try:
            fn(results)
        except Exception as e:
            print(f"  {name} FAILED: {type(e).__name__}: {e}")
            results[f"{name}_error"] = f"{type(e).__name__}: {e}"[:200]
    with open(args.json, "w") as f:
        json.dump({"device": dev.device_kind, "inner": INNER,
                   "results": results}, f, indent=1)
    print(f"wrote {args.json}")


if __name__ == "__main__":
    main()
