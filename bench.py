"""Headline benchmark: GPT-2 125M AMP-O2 fused train step, tokens/sec/chip.

Mirrors the reference's flagship workload (BASELINE.json config 3: GPT-2 125M
with FusedLayerNorm + causal fused softmax + fused optimizer). The reference
repo publishes no absolute numbers (BASELINE.md), so ``vs_baseline`` is the
speedup of our full AMP-O2 + FusedAdam path over the plain fp32 + unfused
(optax-style pure-jnp Adam) step on the same hardware — the exact value
proposition apex itself sells (amp + multi_tensor fused optimizers vs eager
fp32, README.md:3-6).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import dataclasses
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.config import gpt_125m
from apex_tpu.models.gpt import make_gpt_train_step
from apex_tpu.optimizers import fused_adam


def _naive_adam(lr=1e-3, b1=0.9, b2=0.999, eps=1e-8):
    """Unfused reference Adam (per-tensor jnp ops, no multi-tensor fusion)."""
    import optax
    return optax.adam(lr, b1=b1, b2=b2, eps=eps)


def _time_steps(step, state, tokens, labels, iters):
    # NB: sync via scalar materialization, not jax.block_until_ready — the
    # latter does not actually block on tunneled TPU platforms.
    state, m = step(state, tokens, labels)          # compile + warmup
    float(m["loss"])
    state, m = step(state, tokens, labels)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, tokens, labels)
    float(m["loss"])                                # chain-dependent sync
    return (time.perf_counter() - t0) / iters


def main():
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        batch, seq, iters = 8, 1024, 20
        # flash attention removes the O(s²) activations; no remat needed
        cfg = gpt_125m(max_position_embeddings=seq, remat=False)
    else:  # CPU smoke path: tiny shapes so the script stays runnable anywhere
        batch, seq, iters = 2, 128, 3
        cfg = gpt_125m(num_layers=2, hidden_size=256,
                       num_attention_heads=4, vocab_size=8192,
                       max_position_embeddings=seq)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)

    # ours: AMP O2 (bf16 compute, fp32 master) + FusedAdam
    init, step = make_gpt_train_step(cfg, fused_adam(lr=1e-4), "O2")
    state = init(jax.random.PRNGKey(0))
    fused_s = _time_steps(step, state, tokens, labels, iters)
    del state

    # baseline: fp32 everywhere, unfused per-tensor Adam (the "eager" analog)
    cfg_fp32 = dataclasses.replace(
        cfg, compute_dtype=jnp.float32, ffn_hidden_size=cfg.ffn_hidden_size,
        kv_channels=cfg.kv_channels)
    init0, step0 = make_gpt_train_step(cfg_fp32, _naive_adam(lr=1e-4), "O0")
    state0 = init0(jax.random.PRNGKey(0))
    base_s = _time_steps(step0, state0, tokens, labels, iters)
    del state0

    tokens_per_sec = batch * seq / fused_s
    print(json.dumps({
        "metric": "gpt2_125m_amp_o2_fused_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(base_s / fused_s, 3),
    }))


if __name__ == "__main__":
    main()
